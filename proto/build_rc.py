#!/usr/bin/env python
"""Regenerate the restorecommerce-wire stubs (srv/gen/rc).

protoc emits package-rooted imports (``from io.restorecommerce import
...``) whose top-level package collides with the stdlib ``io`` module, so
the generated files are flattened into one package and their imports
rewritten to relative form.  Run from the repo root:

    python proto/build_rc.py
"""

import os
import re
import subprocess
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "proto", "rc")
OUT = os.path.join(REPO, "access_control_srv_tpu", "srv", "gen", "rc")


def main() -> None:
    protos = []
    for root, _, files in os.walk(SRC):
        for f in files:
            if f.endswith(".proto"):
                protos.append(
                    os.path.relpath(os.path.join(root, f), SRC)
                )
    with tempfile.TemporaryDirectory() as tmp:
        subprocess.run(
            ["protoc", f"--python_out={tmp}", *sorted(protos)],
            cwd=SRC, check=True,
        )
        os.makedirs(OUT, exist_ok=True)
        for root, _, files in os.walk(tmp):
            for f in files:
                if not f.endswith("_pb2.py"):
                    continue
                text = open(os.path.join(root, f), encoding="utf-8").read()
                text = re.sub(
                    r"from io\.restorecommerce import (\w+) as",
                    r"from . import \1 as",
                    text,
                )
                text = re.sub(
                    r"from grpc\.health\.v1 import (\w+) as",
                    r"from . import \1 as",
                    text,
                )
                open(os.path.join(OUT, f), "w", encoding="utf-8").write(text)
    init = os.path.join(OUT, "__init__.py")
    open(init, "w", encoding="utf-8").write(
        '"""Generated restorecommerce-wire stubs (see proto/build_rc.py);\n'
        "the proto sources under proto/rc/ are reconstructions of the\n"
        'public @restorecommerce/protos package."""\n'
    )
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
