#!/usr/bin/env python
"""Headline benchmark: batched isAllowed decisions/sec on one chip.

Config mirrors BASELINE.md config 2/5 shape: the seed policy set (super-
admin permit-all, data/seed_data/) evaluated against a large synthetic
request batch (50% super-admin role -> PERMIT, 50% ordinary -> INDETERMINATE)
on whatever accelerator jax.devices() exposes (the driver runs this on a
single TPU v5e-1 chip).

Prints ONE JSON line:
  {"metric": "isAllowed decisions/sec/chip (seed policy set)",
   "value": <decisions/sec>, "unit": "decisions/s",
   "vs_baseline": <value / 100_000>}

vs_baseline is measured against the BASELINE.json north-star target of
100k decisions/sec/chip (the reference publishes no numbers; its scalar
TypeScript engine evaluates one request per gRPC call).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_TARGET = 100_000.0

METRIC = "isAllowed decisions/sec/chip (seed policy set)"


def probe_backend(timeout: int | None = None, retries: int | None = None):
    """Initialize the jax backend in a THROWAWAY subprocess with a hard
    timeout. The machine's TPU plugin can hang (not fail) on init when the
    chip is unreachable; probing out-of-process is the only way to fail
    fast without wedging the bench process itself.

    Returns (info_dict, None) on success or (None, error_str) on failure.
    """
    timeout = timeout or int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    retries = retries or int(os.environ.get("BENCH_PROBE_RETRIES", 2))
    code = (
        "import jax, json\n"
        "d = jax.devices()\n"
        "x = jax.numpy.ones((8, 8))\n"
        "(x @ x).block_until_ready()\n"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'n_devices': len(d), 'device0': str(d[0])}))\n"
    )
    last_err = "no probe attempts"
    for _ in range(max(1, retries)):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            last_err = f"backend init hang: no response within {timeout}s"
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            try:
                return json.loads(proc.stdout.strip().splitlines()[-1]), None
            except json.JSONDecodeError:
                last_err = f"unparseable probe output: {proc.stdout[-200:]}"
                continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        last_err = (tail[-1] if tail else f"probe rc={proc.returncode}")[-400:]
    return None, last_err


def cpu_fallback(error: str) -> str:
    """Accelerator unreachable after the probe's retries: force the CPU
    backend and run the same measurement there, so the driver gets a valid
    rc=0 headline row annotated with the TPU error instead of a rc=1 /
    value-0.0 failure row that blanks the round (BENCH_r05 regression).
    Must run before the first jax backend touch — the machine pins
    JAX_PLATFORMS externally, so only jax.config can override it."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    return error


def build_batch(compiled, base: int = 4096, total: int = 1 << 18):
    """Encode `base` distinct requests, then tile to `total` at array level
    (string work is per-distinct-request; device work is per-row)."""
    from access_control_srv_tpu.models import Attribute, Request, Target, Urns
    from access_control_srv_tpu.ops import encode_requests
    from access_control_srv_tpu.ops.encode import RequestBatch

    urns = Urns()
    org = "urn:restorecommerce:acs:model:organization.Organization"
    entities = [
        org,
        "urn:restorecommerce:acs:model:user.User",
        "urn:restorecommerce:acs:model:address.Address",
        "urn:restorecommerce:acs:model:location.Location",
    ]
    actions = [urns["read"], urns["modify"], urns["create"], urns["delete"]]
    rng = np.random.default_rng(42)
    requests = []
    for i in range(base):
        role = "superadministrator-r-id" if i % 2 == 0 else f"role-{i % 7}"
        entity = entities[int(rng.integers(len(entities)))]
        requests.append(
            Request(
                target=Target(
                    subjects=[
                        Attribute(id=urns["role"], value=role),
                        Attribute(id=urns["subjectID"], value=f"user-{i % 512}"),
                    ],
                    resources=[
                        Attribute(id=urns["entity"], value=entity),
                        Attribute(id=urns["resourceID"], value=f"res-{i % 1024}"),
                    ],
                    actions=[
                        Attribute(
                            id=urns["actionID"],
                            value=actions[int(rng.integers(len(actions)))],
                        )
                    ],
                ),
                context={
                    "resources": [],
                    "subject": {
                        "id": f"user-{i % 512}",
                        "role_associations": [{"role": role, "attributes": []}],
                        "hierarchical_scopes": [],
                    },
                },
            )
        )
    batch = encode_requests(requests, compiled)
    assert bool(batch.eligible.all()), "bench requests must be kernel-eligible"

    reps = (total + base - 1) // base
    arrays = {k: np.tile(v, (reps,) + (1,) * (v.ndim - 1))[:total]
              for k, v in batch.arrays.items()}
    C = batch.cond_true.shape[0]
    return RequestBatch(
        B=total,
        arrays=arrays,
        rgx_set=batch.rgx_set,
        pfx_neq=batch.pfx_neq,
        cond_true=np.tile(batch.cond_true, (1, reps))[:, :total],
        cond_abort=np.tile(batch.cond_abort, (1, reps))[:, :total],
        cond_code=np.tile(batch.cond_code, (1, reps))[:, :total],
        eligible=np.ones((total,), bool),
    )


def main():
    tpu_error = None
    if os.environ.get("BENCH_SKIP_PROBE") != "1":
        info, err = probe_backend()
        if info is None:
            # one more out-of-process attempt (transient plugin hangs
            # resolve between probes), then fall back to a CPU-backend
            # headline row
            info, err2 = probe_backend(retries=1)
            if info is None:
                tpu_error = cpu_fallback(err or err2)

    import jax

    from access_control_srv_tpu.core import AccessController, load_seed_files
    from access_control_srv_tpu.ops import DecisionKernel, compile_policies

    engine = AccessController()
    seed = os.path.join(REPO, "data", "seed_data")
    for ps in load_seed_files(
        os.path.join(seed, "policy_sets.yaml"),
        os.path.join(seed, "policies.yaml"),
        os.path.join(seed, "rules.yaml"),
    ):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    kernel = DecisionKernel(compiled)

    total = int(os.environ.get("BENCH_BATCH", 1 << 18))
    batch = build_batch(compiled, total=total)

    import jax.numpy as jnp

    dev_args = (
        {k: jnp.asarray(v) for k, v in batch.arrays.items()},
        jnp.asarray(batch.rgx_set),
        jnp.asarray(batch.pfx_neq),
        jnp.asarray(batch.cond_true),
        jnp.asarray(batch.cond_abort),
        jnp.asarray(batch.cond_code),
    )
    # warmup / compile
    out = kernel._run(*dev_args)
    jax.block_until_ready(out)
    # sanity: 50% PERMIT, 50% INDETERMINATE
    dec = np.asarray(out[0])
    permit_frac = float((dec == 1).mean())
    assert 0.45 < permit_frac < 0.55, permit_frac

    iters = int(os.environ.get("BENCH_ITERS", 5))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = kernel._run(*dev_args)
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0
    value = total * iters / elapsed

    row = {
        "metric": METRIC,
        "value": round(value, 1),
        "unit": "decisions/s",
        "vs_baseline": round(value / BASELINE_TARGET, 3),
        "backend": jax.default_backend(),
        "eligible_pct": 100.0,
    }
    if tpu_error is not None:
        row["tpu_error"] = tpu_error
    print(json.dumps(row))


if __name__ == "__main__":
    main()
