"""Isolate the red differential case: tree round 2 (seed 1234), request 10
of grid_requests(n=60, seed=1002)."""
import random
import json

import tests.conftest  # noqa: F401  (force CPU platform)
from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.ops import DecisionKernel, compile_policies, encode_requests
from tests.test_kernel_differential import _random_policy_tree, grid_requests

rng = random.Random(1234)
docs = [_random_policy_tree(rng) for _ in range(12)]
doc = docs[2]
print(json.dumps(doc, indent=1))

engine = AccessController()
for ps in load_policy_sets(doc):
    engine.update_policy_set(ps)
compiled = compile_policies(engine.policy_sets, engine.urns)
assert compiled.supported

requests = grid_requests(n=60, seed=1002)
req = requests[10]
print("\n=== REQUEST 10 ===")
print("target.subjects:", [(a.id, a.value) for a in req.target.subjects])
print("target.resources:", [(a.id, a.value) for a in req.target.resources])
print("target.actions:", [(a.id, a.value) for a in req.target.actions])
print("context:", json.dumps(req.context, indent=1, default=str))

expected = engine.is_allowed(req)
print("\noracle:", expected.decision, expected.operation_status)

kernel = DecisionKernel(compiled)
batch = encode_requests([req], compiled)
print("eligible:", batch.eligible[0])
decision, cacheable, status = kernel.evaluate(batch)
print("kernel decision:", decision[0], "cacheable:", cacheable[0], "status:", status[0])
