#!/usr/bin/env python
"""Round-long TPU capture loop (VERDICT r2 item 2).

The machine's TPU plugin can wedge on init for hours at a time; the first
window when the chip answers must produce committed benchmark evidence.
This script probes the backend out-of-process every PROBE_INTERVAL seconds,
appends every attempt to TPU_PROBE_LOG.jsonl (timestamped proof of chip
availability over the round), and on first success runs bench.py and
bench_all.py and commits the artifacts.

Run detached:  nohup python tpu_probe_loop.py >/dev/null 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from bench import probe_backend  # noqa: E402

LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
PROBE_INTERVAL = int(os.environ.get("PROBE_INTERVAL", 300))
MAX_HOURS = float(os.environ.get("PROBE_MAX_HOURS", 11.0))


def log_attempt(entry: dict) -> None:
    entry["ts"] = datetime.now(timezone.utc).isoformat(timespec="seconds")
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")


def run_and_capture() -> bool:
    """Chip is up: run the headline bench and the evidence matrix."""
    ok = True
    env = dict(os.environ)
    env.pop("BENCH_SKIP_PROBE", None)
    try:
        head = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=1800, env=env,
        )
        line = (head.stdout or "").strip().splitlines()
        if head.returncode == 0 and line:
            with open(os.path.join(REPO, "BENCH_TPU_CAPTURE.json"), "w") as f:
                f.write(line[-1] + "\n")
        else:
            ok = False
            log_attempt({"phase": "bench.py", "rc": head.returncode,
                         "err": (head.stderr or "")[-400:]})
    except subprocess.TimeoutExpired:
        ok = False
        log_attempt({"phase": "bench.py", "err": "bench timeout 1800s"})
    try:
        matrix = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench_all.py")],
            capture_output=True, text=True, timeout=3600, env=env,
        )
        if matrix.returncode != 0:
            ok = False
            log_attempt({"phase": "bench_all.py", "rc": matrix.returncode,
                         "err": (matrix.stderr or "")[-400:]})
    except subprocess.TimeoutExpired:
        ok = False
        log_attempt({"phase": "bench_all.py", "err": "bench_all timeout 3600s"})
    return ok


def commit_artifacts() -> None:
    files = ["TPU_PROBE_LOG.jsonl", "BENCH_TPU_CAPTURE.json", "BENCH_ALL.json"]
    present = [f for f in files if os.path.exists(os.path.join(REPO, f))]
    for attempt in range(10):
        add = subprocess.run(["git", "-C", REPO, "add", *present],
                             capture_output=True)
        if add.returncode != 0:
            time.sleep(30)
            continue
        cm = subprocess.run(
            ["git", "-C", REPO, "commit", "-m",
             "Capture TPU benchmark evidence on chip-up window"],
            capture_output=True,
        )
        if cm.returncode == 0:
            return
        time.sleep(30)


def main() -> None:
    deadline = time.time() + MAX_HOURS * 3600
    while time.time() < deadline:
        info, err = probe_backend(timeout=120, retries=1)
        if info is not None:
            log_attempt({"ok": True, **info})
            captured = run_and_capture()
            commit_artifacts()
            if captured:
                return
            # partial failure: keep probing, maybe a later window is cleaner
        else:
            log_attempt({"ok": False, "err": err})
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    main()
