"""Cluster chaos: kill -9 one of two replicas mid-CRUD-churn with a
closed-loop client running through the router.

Acceptance (ISSUE PR 9): zero failed client requests beyond honest shed
statuses, the restarted replica converges byte-identically (same policy
epoch + table fingerprint as the survivor), and a stale-decision oracle
finds zero stale decisions — every response's decision matches the
policy state its stamped epoch claims, so the decision cache's
cluster-wide scoped invalidation provably works under churn.

The oracle is journal-exact: after the run it reads the broker's rules
topic, so it knows EXACTLY which effect the chaos rule had after k
applied rule frames.  A response stamped with epoch e was served from a
tree reflecting e CRUD frames; its decision must match the effect at
that journal position (with one-frame tolerance when a flip was in
flight during the request — the stamp is read after evaluation, so a
concurrent apply can advance it by one)."""

import threading
import time

import grpc
import pytest

from access_control_srv_tpu.parallel.cluster import LocalCluster
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.router import POLICY_EPOCH_METADATA_KEY

from .cluster_util import (
    create_reader_policy_tree,
    reader_rule_doc,
    seed_paths,
    upsert_rule,
    wait_converged,
    wire_request,
)

SHED_CODES = (429, 503, 504)
RULE_ID = "r_chaos"


@pytest.mark.cluster(timeout=240)
def test_kill9_replica_mid_crud_churn(tmp_path):
    cluster = LocalCluster(
        n_replicas=2,
        seed_cfg=seed_paths(),
        router_cfg={"health_interval_s": 0.3, "max_retries": 1},
        base_dir=str(tmp_path),
    ).start()
    channel = grpc.insecure_channel(cluster.router.addr)
    try:
        create_reader_policy_tree(channel, RULE_ID)
        addrs = [r.addr for r in cluster.replicas]
        wait_converged(addrs, timeout_s=30.0, min_epoch=1)

        is_allowed = channel.unary_unary(
            "/acstpu.AccessControlService/IsAllowed",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.Response.FromString,
        )
        stop = threading.Event()
        records: list = []   # (t_send, t_recv, code, decision, epoch)
        transport_errors: list = []

        def client_loop():
            msg = wire_request(role="reader-role")
            while not stop.is_set():
                t_send = time.monotonic()
                try:
                    resp, call = is_allowed.with_call(msg, timeout=10)
                except grpc.RpcError as err:
                    transport_errors.append(
                        (time.monotonic(), err.code(), err.details())
                    )
                    time.sleep(0.02)
                    continue
                trailers = dict(call.trailing_metadata() or ())
                records.append((
                    t_send,
                    time.monotonic(),
                    resp.operation_status.code,
                    resp.decision,
                    int(trailers.get(POLICY_EPOCH_METADATA_KEY, -1)),
                ))
                time.sleep(0.004)

        flip_acks: list = []  # (t_before_send, t_ack)
        state = {"effect": "PERMIT"}

        def churn_loop():
            while not stop.is_set():
                effect = "DENY" if state["effect"] == "PERMIT" \
                    else "PERMIT"
                t_before = time.monotonic()
                try:
                    code = upsert_rule(
                        channel, reader_rule_doc(RULE_ID, effect=effect)
                    )
                except grpc.RpcError:
                    time.sleep(0.05)
                    continue
                if code == 200:
                    flip_acks.append((t_before, time.monotonic()))
                    state["effect"] = effect
                time.sleep(0.12)

        client = threading.Thread(target=client_loop, daemon=True)
        churn = threading.Thread(target=churn_loop, daemon=True)
        client.start()
        churn.start()

        time.sleep(1.5)                    # steady churn, both replicas
        victim = cluster.replicas[1]
        victim.kill()                      # SIGKILL mid-churn
        time.sleep(2.5)                    # churn + serving on survivor
        restarted = cluster.restart_replica(1)
        # restarted replica must converge byte-identically with the
        # survivor (journal replay through the delta path)
        ids = wait_converged(
            [cluster.replicas[0].addr, restarted.addr], timeout_s=60.0,
        )
        time.sleep(1.0)                    # traffic lands on both again
        stop.set()
        client.join(timeout=15)
        churn.join(timeout=15)
        assert not client.is_alive() and not churn.is_alive()

        # ---- acceptance 1: no failed requests beyond honest sheds ----
        assert not transport_errors, transport_errors[:5]
        bad_codes = {
            code for _, _, code, _, _ in records
            if code != 200 and code not in SHED_CODES
        }
        assert not bad_codes, bad_codes
        assert len(records) > 100  # the loop really ran through the kill

        # ---- acceptance 2: byte-identical convergence -----------------
        assert len({
            (i["policy_epoch"], i["table_fingerprint"]) for i in ids
        }) == 1, ids
        assert ids[0]["table_fingerprint"] is not None

        # ---- acceptance 3: journal-exact stale-decision oracle --------
        from access_control_srv_tpu.srv.broker import SocketEventBus

        bus = SocketEventBus(cluster.broker_addr)
        try:
            rule_frames = bus.topic(
                "io.restorecommerce.rules.resource"
            ).read(0)
            # store.py topic scheme: io.restorecommerce.{kind}s.resource
            other = sum(
                len(bus.topic(
                    f"io.restorecommerce.{kind}s.resource"
                ).read(0))
                for kind in ("policy", "policy_set")
            )
        finally:
            bus.close()
        # effect of the chaos rule after k applied rule frames
        effect_at: list = []
        current = None
        for _event, message in rule_frames:
            doc = (message or {}).get("payload") or {}
            if doc.get("id") == RULE_ID:
                current = doc.get("effect")
            effect_at.append(current)
        expected_decision = {
            "PERMIT": pb.PERMIT, "DENY": pb.DENY, None: None,
        }

        def ok_at(epoch: int, decision) -> bool:
            k = epoch - other  # rule frames applied at this epoch
            if k < 1 or k > len(effect_at):
                return False
            want = expected_decision[effect_at[k - 1]]
            return want is not None and decision == want

        stale = []
        for t_send, t_recv, code, decision, epoch in records:
            if code != 200:
                continue  # honest shed: INDETERMINATE, not a decision
            assert epoch >= 0, "decision response missing epoch stamp"
            if ok_at(epoch, decision):
                continue
            # one-frame tolerance only while a flip was near in flight
            # (replica apply lags the CRUD ack by the replicator
            # debounce; a truly stale cache entry misses by many frames)
            in_flight = any(
                t_before <= t_recv + 0.25 and t_ack >= t_send - 1.0
                for t_before, t_ack in flip_acks
            )
            if in_flight and (
                ok_at(epoch - 1, decision) or ok_at(epoch + 1, decision)
            ):
                continue
            stale.append((t_send, code, decision, epoch))
        assert not stale, (
            f"{len(stale)} stale decisions, e.g. {stale[:5]}; "
            f"{len(rule_frames)} rule frames, other={other}"
        )
        assert len(flip_acks) >= 5  # churn actually churned
    finally:
        channel.close()
        cluster.stop()


@pytest.mark.cluster(timeout=180)
def test_restarted_replica_serves_correct_decisions(tmp_path):
    """A killed+restarted replica must serve the post-churn policy state
    directly (not only report matching fingerprints): flip the chaos
    rule to DENY while the replica is down, restart, and ask IT."""
    cluster = LocalCluster(
        n_replicas=2, seed_cfg=seed_paths(), base_dir=str(tmp_path),
        router_cfg={"health_interval_s": 0.3},
    ).start()
    channel = grpc.insecure_channel(cluster.router.addr)
    try:
        create_reader_policy_tree(channel, RULE_ID)
        wait_converged([r.addr for r in cluster.replicas], timeout_s=30.0)
        cluster.replicas[1].kill()
        assert upsert_rule(
            channel, reader_rule_doc(RULE_ID, effect="DENY")
        ) == 200
        restarted = cluster.restart_replica(1)
        wait_converged(
            [cluster.replicas[0].addr, restarted.addr], timeout_s=60.0,
        )
        from access_control_srv_tpu.srv.transport_grpc import GrpcClient

        direct = GrpcClient(restarted.addr)
        try:
            resp = direct.is_allowed(wire_request(role="reader-role"))
            assert resp.operation_status.code == 200
            assert resp.decision == pb.DENY
        finally:
            direct.close()
    finally:
        channel.close()
        cluster.stop()


# -------------------------------------------------- lock-order soak


@pytest.mark.slow
@pytest.mark.cluster(timeout=180)
def test_no_lock_order_cycles_in_router_under_chaos(tmp_path):
    """Runtime lock-order detection over the chaos tier's IN-PROCESS
    surface — the ClusterRouter, its health loop, and the SocketEventBus
    client — while a replica is killed and restarted mid-churn.  Replica
    subprocesses are out of scope by construction (the watch patches this
    process's lock factories); the router is where cross-thread lock
    nesting lives on this tier, and a cycle in its acquisition graph is a
    deadlock the scheduler merely hasn't dealt yet.  See
    access_control_srv_tpu/analysis/locktrace.py."""
    from access_control_srv_tpu.analysis.locktrace import lock_order_watch

    with lock_order_watch() as watch:
        cluster = LocalCluster(
            n_replicas=2,
            seed_cfg=seed_paths(),
            router_cfg={"health_interval_s": 0.2, "max_retries": 1},
            base_dir=str(tmp_path),
        ).start()
        channel = grpc.insecure_channel(cluster.router.addr)
        try:
            create_reader_policy_tree(channel, RULE_ID)
            wait_converged(
                [r.addr for r in cluster.replicas], timeout_s=30.0,
                min_epoch=1,
            )
            is_allowed = channel.unary_unary(
                "/acstpu.AccessControlService/IsAllowed",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.Response.FromString,
            )
            stop = threading.Event()

            def client_loop():
                msg = wire_request(role="reader-role")
                while not stop.is_set():
                    try:
                        is_allowed(msg, timeout=10)
                    except grpc.RpcError:
                        pass
                    time.sleep(0.004)

            def churn_loop():
                flip = 0
                while not stop.is_set():
                    flip += 1
                    effect = "PERMIT" if flip % 2 else "DENY"
                    try:
                        upsert_rule(
                            channel,
                            reader_rule_doc(RULE_ID, effect=effect),
                        )
                    except grpc.RpcError:
                        pass
                    time.sleep(0.1)

            threads = [threading.Thread(target=client_loop, daemon=True)
                       for _ in range(2)]
            threads.append(
                threading.Thread(target=churn_loop, daemon=True)
            )
            for thread in threads:
                thread.start()
            time.sleep(1.0)
            cluster.replicas[1].kill()      # health loop must notice
            time.sleep(1.5)
            cluster.restart_replica(1)      # ...and re-admit
            time.sleep(1.0)
            stop.set()
            for thread in threads:
                thread.join(timeout=15)
                assert not thread.is_alive()
        finally:
            channel.close()
            cluster.stop()
    watch.assert_acyclic()
