"""Native C++ wire encoder vs Python encoder: array-level differential.

The native encoder (access_control_srv_tpu/native) parses serialized
``acstpu.Request`` wire bytes directly; it must produce exactly the same
row arrays, eligibility mask, regex matrices and (through the kernel) the
same decisions as the Python encoder run on the deserialized requests.
"""

import numpy as np
import pytest

from access_control_srv_tpu import native
from access_control_srv_tpu.ops import (
    DecisionKernel,
    compile_policies,
    encode_requests,
)
from access_control_srv_tpu.srv.transport_grpc import request_from_pb, request_to_pb

from .test_kernel_differential import DEC_CODE, grid_requests
from .utils import make_engine

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native encoder unavailable: {native.build_error()}",
)


def wire_roundtrip(requests):
    """Serialize to wire bytes + the deserialized twins the Python encoder
    sees (the honest comparison: both sides read the same wire)."""
    messages = [request_to_pb(r).SerializeToString() for r in requests]
    twins = []
    for m in messages:
        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
        from access_control_srv_tpu.srv.service import unmarshall_context

        msg = pb.Request.FromString(m)
        req = request_from_pb(msg)
        if isinstance(req.context, dict):
            req.context = unmarshall_context(req.context)
        twins.append(req)
    return messages, twins


@pytest.mark.parametrize(
    "fixture_name",
    [
        "basic_policies.yml",
        "policy_targets.yml",
        "policy_set_targets.yml",
        "role_scopes.yml",
        "acl_policies.yml",
        "props_single.yml",
        "props_multi_rules_entities.yml",
        "ops_multi.yml",
    ],
)
def test_wire_differential(fixture_name):
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    if compiled.conditions:
        pytest.skip("condition fixtures stay on the Python encoder")
    enc = native.NativeBatchEncoder(compiled)

    requests = grid_requests(n=120, seed=31)
    messages, twins = wire_roundtrip(requests)
    nb = enc.encode_wire(messages)
    # the native encoder fills the fixed floor shapes; compare the Python
    # encoder at the same caps (adaptive caps are a Python-path feature)
    from access_control_srv_tpu.ops.encode import _CAPS_FLOOR

    pb_batch = encode_requests(twins, compiled, caps=_CAPS_FLOOR)

    assert np.array_equal(nb.eligible, pb_batch.eligible)
    for name in nb.arrays:
        assert np.array_equal(nb.arrays[name], pb_batch.arrays[name]), name
    assert np.array_equal(nb.rgx_set, pb_batch.rgx_set)
    assert np.array_equal(nb.pfx_neq, pb_batch.pfx_neq)
    assert nb.eligible.sum() > 60  # the sweep must exercise the kernel path


def test_wire_decisions_match_oracle():
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)
    kernel = DecisionKernel(compiled)

    requests = grid_requests(n=100, seed=77)
    messages, twins = wire_roundtrip(requests)
    nb = enc.encode_wire(messages)
    decision, cacheable, status = kernel.evaluate(nb)
    for b, twin in enumerate(twins):
        if not nb.eligible[b]:
            continue
        expected = engine.is_allowed(twin)
        assert decision[b] == DEC_CODE[expected.decision], b


def test_edge_shapes():
    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)
    from access_control_srv_tpu.models import Attribute, Request, Target, Urns

    urns = Urns()
    cases = [
        Request(target=None, context=None),  # no target -> ineligible
        Request(target=Target(subjects=[], resources=[], actions=[]),
                context=None),
        Request(
            target=Target(
                subjects=[Attribute(id=urns["role"], value="member")],
                resources=[],
                actions=[],
            ),
            # token subject -> host path
            context={"subject": {"token": "tok"}, "resources": []},
        ),
        Request(
            target=Target(
                subjects=[],
                # unknown resource attribute id -> ineligible
                resources=[Attribute(id="custom:attr", value="v")],
                actions=[],
            ),
            context=None,
        ),
    ]
    messages, twins = wire_roundtrip(cases)
    nb = enc.encode_wire(messages)
    pb_batch = encode_requests(twins, compiled)
    assert np.array_equal(nb.eligible, pb_batch.eligible)
    for name in nb.arrays:
        assert np.array_equal(nb.arrays[name], pb_batch.arrays[name]), name


def test_conditions_tree_rejected():
    engine = make_engine("conditions.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    if not compiled.conditions:
        pytest.skip("fixture has no conditions")
    with pytest.raises(RuntimeError):
        native.NativeBatchEncoder(compiled)


def test_native_wire_path_end_to_end():
    """The gRPC batch endpoint must take the native path (not silently
    fall back) and agree with the oracle."""
    import os

    from access_control_srv_tpu.srv import Worker
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

    from .test_grpc_transport import SEED, wire_request

    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        assert worker.evaluator.native_active, "native encoder should engage"
        batch = pb.BatchRequest(
            requests=[
                wire_request(),
                wire_request(role="nobody"),
                wire_request(),
            ]
        )
        out = client.is_allowed_batch(batch)
        decisions = [r.decision for r in out.responses]
        assert decisions == [pb.PERMIT, pb.INDETERMINATE, pb.PERMIT]
        assert all(r.operation_status.code == 200 for r in out.responses)
    finally:
        client.close()
        server.stop()
        worker.stop()


def test_malformed_wire_rows_not_fabricated():
    """Corrupt protobuf or JSON must never produce a fabricated 200
    decision from the native path -- such rows go ineligible."""
    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)

    good = wire_roundtrip(grid_requests(n=1, seed=5))[0][0]
    bad_proto = good + b"\xff\xff\xff"          # trailing garbage field
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

    msg = pb.Request.FromString(good)
    msg.context.subject.value = b'{"id": "u", "role_assoc'  # truncated JSON
    bad_json = msg.SerializeToString()

    nb = enc.encode_wire([good, bad_proto, bad_json])
    assert nb.eligible[0]
    assert not nb.eligible[1]
    assert not nb.eligible[2]


def test_concurrent_encode_wire():
    """Concurrent batches on one encoder must stay consistent (the
    interner is shared mutable state guarded by the encoder lock)."""
    from concurrent.futures import ThreadPoolExecutor

    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)
    kernel = DecisionKernel(compiled)

    def job(seed):
        reqs = grid_requests(n=40, seed=seed)
        messages, twins = wire_roundtrip(reqs)
        nb = enc.encode_wire(messages)
        decision, _, status = kernel.evaluate(nb)
        out = []
        for b, twin in enumerate(twins):
            if nb.eligible[b] and status[b] == 200:
                out.append((b, int(decision[b]), engine.is_allowed(twin).decision))
        return out

    with ThreadPoolExecutor(max_workers=8) as pool:
        for rows in pool.map(job, range(200, 216)):
            for b, got, expected in rows:
                assert got == DEC_CODE[expected], b


def test_trailing_garbage_json_rejected():
    """JSON with trailing garbage or non-RFC numbers must not stay
    kernel-eligible (json.loads would raise on the pb path)."""
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)

    good = wire_roundtrip(grid_requests(n=1, seed=5))[0][0]
    cases = [b'{"id": "u"}garbage', b'{"n": +5}', b'{"n": -}', b'{"n": 5.}']
    messages = []
    for payload in cases:
        msg = pb.Request.FromString(good)
        msg.context.subject.value = payload
        messages.append(msg.SerializeToString())
    nb = enc.encode_wire(messages)
    assert not nb.eligible.any()


def test_deeply_nested_json_no_stack_overflow():
    """A JSON nesting bomb (well under the gRPC message cap) must not
    overflow the C stack -- past the parser depth cap the row goes
    ineligible and falls back to the Python path."""
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)

    good = wire_roundtrip(grid_requests(n=1, seed=5))[0][0]
    messages = []
    # the {"id": ...} wrapper itself consumes one depth level, so inner
    # array depth 63 hits the cap (64) exactly and 64 exceeds it
    for depth in (30, 63, 64, 200_000):
        bomb = b"[" * depth + b"]" * depth
        msg = pb.Request.FromString(good)
        msg.context.subject.value = b'{"id": ' + bomb + b"}"
        messages.append(msg.SerializeToString())
    nb = enc.encode_wire(messages)
    # depths under the cap parse fine; past the cap the row is ineligible
    assert nb.eligible[0] and nb.eligible[1]
    assert not nb.eligible[2]
    assert not nb.eligible[3]


def test_strict_string_parsing_matches_json_loads():
    """Strings json.loads rejects must make the row ineligible, never
    silently decode to garbage and serve a decision from a misparse."""
    import json as _json

    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)
    good = wire_roundtrip(grid_requests(n=1, seed=5))[0][0]

    bad = [
        b'{"id": "unterminated',       # no closing quote
        b'{"id": "trunc\\u12"}',       # truncated \uXXXX
        b'{"id": "bad\\uzzzz"}',       # non-hex \uXXXX
        b'{"id": "esc\\x41"}',         # unknown escape
        b'{"id": "ctl\x01char"}',      # raw control character
        b'{"id": "end\\',              # escape at end of input
    ]
    # json.loads ACCEPTS these, but the native path cannot reproduce
    # Python's surrogate decoding — it must fall back (conservatively
    # ineligible) rather than emit CESU-8 and serve from a misparse
    conservative = [
        b'{"id": "pair\\ud83d\\ude00"}',
        b'{"id": "lone\\ud800"}',
    ]
    ok = [
        b'{"id": "fine\\u0041\\n\\"q\\\\"}',
        b'{"id": "slash\\/ok"}',
    ]
    for payload in bad:
        with pytest.raises(Exception):
            _json.loads(payload.decode("utf-8", "surrogateescape"))
    for payload in conservative + ok:
        _json.loads(payload.decode())

    messages = []
    for payload in bad + conservative + ok:
        msg = pb.Request.FromString(good)
        msg.context.subject.value = payload
        messages.append(msg.SerializeToString())
    nb = enc.encode_wire(messages)
    n_ineligible = len(bad) + len(conservative)
    assert not nb.eligible[:n_ineligible].any()
    assert nb.eligible[n_ineligible:].all()


def test_wire_acl_absent_values_ineligible():
    """ADVICE r2 (high), native side: JSON null ACL entity/instance values
    reach intern_jstr as ABSENT; such rows must fall back to the oracle
    (eligible=False), matching the Python encoder."""
    from access_control_srv_tpu.models import Attribute, Request, Target

    from .utils import URNS

    ORG = "urn:restorecommerce:acs:model:organization.Organization"
    USER = "urn:restorecommerce:acs:model:user.User"
    engine = make_engine("acl_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)

    def mk(acls):
        return Request(
            target=Target(
                subjects=[
                    Attribute(id=URNS["role"], value="member"),
                    Attribute(id=URNS["subjectID"], value="ada"),
                ],
                resources=[
                    Attribute(id=URNS["entity"], value=ORG),
                    Attribute(id=URNS["resourceID"], value="res-1"),
                ],
                actions=[Attribute(id=URNS["actionID"], value=URNS["create"])],
            ),
            context={
                "resources": [{"id": "res-1", "meta": {"owners": [],
                                                       "acls": acls}}],
                "subject": {
                    "id": "ada",
                    "role_associations": [
                        {"role": "member", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                },
            },
        )

    requests = [
        mk([{"id": URNS["aclIndicatoryEntity"], "value": None,
             "attributes": [{"id": URNS["aclInstance"], "value": "ada"}]}]),
        mk([{"id": URNS["aclIndicatoryEntity"], "value": USER,
             "attributes": [{"id": URNS["aclInstance"], "value": None}]}]),
        mk([{"id": URNS["aclIndicatoryEntity"], "value": USER,
             "attributes": [{"id": URNS["aclInstance"], "value": "ada"}]}]),
    ]
    messages, twins = wire_roundtrip(requests)
    nb = enc.encode_wire(messages)
    pb_batch = encode_requests(twins, compiled)
    assert not nb.eligible[0]
    assert not nb.eligible[1]
    assert nb.eligible[2]
    assert np.array_equal(nb.eligible, pb_batch.eligible)


def _deep_hr_request(n_nodes: int, role="member", owner="org-1-x"):
    """A role_scopes-fixture request whose HR tree flattens to n_nodes
    pairs (over the NHR floor of 32 when n_nodes > 32)."""
    from .utils import URNS, build_request

    ORG = "urn:restorecommerce:acs:model:organization.Organization"
    LOC = "urn:restorecommerce:acs:model:location.Location"
    # wide tree (depth 2): n_nodes flattened pairs without tripping the
    # JSON parser's nesting-depth cap
    node = {
        "id": "org-0-n",
        "role": role,
        "children": [
            {"id": f"org-{i + 1}-n"} for i in range(n_nodes - 1)
        ],
    }
    return build_request(
        subject_id="deep-user",
        subject_role=role,
        role_scoping_entity=ORG,
        role_scoping_instance="org-0-n",
        resource_type=LOC,
        resource_id="L1",
        action_type="urn:restorecommerce:acs:names:action:read",
        owner_indicatory_entity=ORG,
        owner_instance=owner,
        hierarchical_scopes=[node],
    )


def test_overcap_flag_and_ceiling_reencode():
    """Rows beyond the floor caps are flagged overcap (not just
    ineligible), and a ceiling-caps re-encode makes them eligible with
    kernel decisions matching the oracle."""
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    enc = native.NativeBatchEncoder(compiled)

    deep = _deep_hr_request(64, owner="org-40-n")
    shallow = _deep_hr_request(3, owner="org-1-n")
    messages, twins = wire_roundtrip([deep, shallow])

    floor_batch = enc.encode_wire(messages)
    assert not floor_batch.eligible[0] and floor_batch.overcap[0]
    assert floor_batch.eligible[1] and not floor_batch.overcap[1]

    from access_control_srv_tpu.ops.encode import _CAPS_CEIL

    ceil_batch = enc.encode_wire(messages, caps=dict(_CAPS_CEIL))
    assert ceil_batch.eligible.all()
    kernel = DecisionKernel(compiled)
    dec, _, status = kernel.evaluate(ceil_batch)
    for b, req in enumerate(twins):
        expected = engine.is_allowed(req)
        assert dec[b] == DEC_CODE[expected.decision], b
        assert status[b] == 200


def test_wire_path_serves_deep_hr_rows_via_ceiling():
    """The serving path keeps over-cap rows native: the evaluator
    re-encodes them at the ceiling and the telemetry records the path."""
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator
    from access_control_srv_tpu.srv.telemetry import Telemetry

    engine = make_engine("role_scopes.yml")
    telemetry = Telemetry()
    ev = HybridEvaluator(engine, telemetry=telemetry)
    if not ev.native_active:
        pytest.skip("native encoder not active for this tree")

    reqs = [_deep_hr_request(64, owner="org-40-n"),
            _deep_hr_request(3, owner="org-1-n"),
            _deep_hr_request(50, owner="nowhere")]
    messages, twins = wire_roundtrip(reqs)
    out = ev.is_allowed_batch_wire(messages)
    assert out is not None
    batch, decision, cacheable, status = out
    assert bool(batch.eligible.all()), "deep rows must stay native"
    assert telemetry.paths.get("native-wire-ceil") == 2
    for b, req in enumerate(twins):
        expected = engine.is_allowed(req)
        assert decision[b] == DEC_CODE[expected.decision], b


# ------------------------------------------------- owner-bit packer parity


def _owner_bits_encoder():
    """A native encoder over an HR-scoped tree (hrv vocab non-empty) whose
    vocab the fuzz below overrides per case."""
    import bench_all
    from access_control_srv_tpu.ops.compile import compile_policies

    if not native.available():
        pytest.skip(f"native encoder unavailable: {native.build_error()}")
    engine, _ = bench_all._stress_engine(600, scoped=True)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    return native.NativeBatchEncoder(compiled), compiled


def test_owner_bits_native_matches_python_packer_on_wire_traffic():
    """End-to-end parity on real wire traffic: the C++ packer's
    r_own_runs/r_own_bits equal ops/encode.pack_owner_bitplanes over the
    same raw arrays."""
    from access_control_srv_tpu.ops import encode as pyenc

    enc, compiled = _owner_bits_encoder()
    orgs = [f"org-{j}" for j in range(5)]
    rng = np.random.default_rng(3)
    reqs = []
    for i in range(48):
        k = int(rng.integers(64))
        tree = [{"id": orgs[0], "role": f"role-{i % 97}",
                 "children": [{"id": o}
                              for o in orgs[1:1 + int(rng.integers(4))]]}]
        from .utils import URNS, build_request

        reqs.append(build_request(
            subject_id=f"u{i}", subject_role=f"role-{i % 97}",
            role_scoping_entity=(
                "urn:restorecommerce:acs:model:organization.Organization"
            ),
            role_scoping_instance=orgs[int(rng.integers(3))],
            resource_type=(
                f"urn:restorecommerce:acs:model:stress{k}.Stress{k}"
            ),
            resource_id=f"res-{i}", action_type=URNS["read"],
            owner_indicatory_entity=(
                "urn:restorecommerce:acs:model:organization.Organization"
            ),
            owner_instance=orgs[int(rng.integers(5))],
            hierarchical_scopes=tree,
        ))
    messages = [request_to_pb(r).SerializeToString() for r in reqs]
    batch = enc.encode_wire(messages)
    raw = {k: v for k, v in batch.arrays.items() if not k.startswith("r_own")}
    ref = pyenc.pack_owner_bitplanes(raw, compiled)
    np.testing.assert_array_equal(ref["r_own_runs"],
                                  batch.arrays["r_own_runs"])
    np.testing.assert_array_equal(ref["r_own_bits"],
                                  batch.arrays["r_own_bits"])


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_owner_bits_fuzz_matches_python_packer(seed):
    """Structure-free fuzz: random raw row arrays (random shapes, random
    ids including ABSENT) and a random role-scope vocab — the C++ packer
    must be bit-identical to the Python packer on every case, including
    wide-entry layouts (ebits > 32)."""
    from types import SimpleNamespace

    from access_control_srv_tpu.ops import encode as pyenc
    from access_control_srv_tpu.ops.encode import alloc_row_arrays

    enc, _ = _owner_bits_encoder()
    rng = np.random.default_rng(seed)
    B = int(rng.integers(1, 24))
    caps = {
        "NR": 4, "NI": int(rng.integers(1, 6)), "NP": 8, "NSUB": 8,
        "NACT": 4, "NOP": int(rng.integers(1, 4)),
        "NOWN": int(rng.integers(1, 5)), "NRA": int(rng.integers(1, 10)),
        "NHR": int(rng.integers(1, 34)), "NROLE": 4, "NACLE": 4,
        "NACLI": 8, "NHRR": 8,
    }
    a = alloc_row_arrays(B, caps)

    def rand_into(name, lo=-1, hi=12):
        arr = a[name]
        arr[...] = rng.integers(lo, hi, size=arr.shape).astype(arr.dtype)

    for name in ("r_inst_run", "r_inst_owner_ent", "r_inst_owner_inst",
                 "r_op_vals", "r_op_owner_ent", "r_op_owner_inst",
                 "r_ra3", "r_ra2", "r_hr"):
        rand_into(name)
    a["r_inst_run"][...] = rng.integers(-1, caps["NR"],
                                        size=a["r_inst_run"].shape)
    for name in ("r_inst_valid", "r_inst_present", "r_inst_has_owners",
                 "r_op_present", "r_op_has_owners"):
        a[name][...] = rng.integers(0, 2, size=a[name].shape).astype(bool)

    # random vocab, sized to also exercise the multi-word layout:
    # ebits = 2*(nru+NOP) can exceed 32 when NI (hence nru) is large
    RV = int(rng.integers(1, 40))
    hrv_role = rng.integers(-1, 12, size=RV).astype(np.int32)
    hrv_scope = rng.integers(0, 12, size=RV).astype(np.int32)
    enc._hrv_role = np.ascontiguousarray(hrv_role)
    enc._hrv_scope = np.ascontiguousarray(hrv_scope)
    fake_compiled = SimpleNamespace(arrays={
        "hrv_role": hrv_role, "hrv_scope": hrv_scope,
        "t_has_scoping": np.array([True]),
        "t_n_subjects": np.array([1]),
    })
    ref = pyenc.pack_owner_bitplanes(a, fake_compiled)
    got = enc.owner_bits_native(a, B)
    np.testing.assert_array_equal(ref["r_own_runs"], got["r_own_runs"],
                                  err_msg=f"seed {seed} runs")
    np.testing.assert_array_equal(ref["r_own_bits"], got["r_own_bits"],
                                  err_msg=f"seed {seed} bits")


def _relation_encoder():
    from .utils import fixture

    from access_control_srv_tpu.core import AccessController, populate

    engine = AccessController()
    populate(engine, fixture("relation_policies.yml"))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    enc = native.NativeBatchEncoder(compiled)
    assert enc.needs_relation_bits
    return engine, enc, compiled


def test_relation_bits_wire_differential():
    """Relation-bearing wire traffic: the C++ packer's
    r_rel_runs/r_rel_bits (built from NATIVE-space verdict tables) equal
    the Python encoder's (HOST-space tables) on the same wire bytes —
    the two interners assign different ids post-preload, so this parity
    also pins the id-space translation in native_relation_tables."""
    from access_control_srv_tpu.ops.encode import _CAPS_FLOOR
    from access_control_srv_tpu.srv.relations import RelationTupleStore

    from .utils import URNS, build_request

    engine, enc, compiled = _relation_encoder()
    doc = "urn:restorecommerce:acs:model:document.Document"
    store = RelationTupleStore()
    store.set_rewrite(doc, "viewer",
                      [("this",), ("computed_userset", "owner")])
    store.create([
        (doc, "doc1", "owner", "alice"),
        (doc, "doc2", "viewer", "bob"),
        (doc, "doc3", "viewer",
         {"object": {"entity": "group", "id": "g"}, "relation": "member"}),
        ("group", "g", "member", "carol"),
    ])
    reqs = [
        build_request(subject_id=s, resource_type=doc, resource_id=r,
                      action_type=URNS["read"])
        for s in ("alice", "bob", "carol", "mallory")
        for r in ("doc1", "doc2", "doc3", ["doc1", "doc3"])
    ]
    messages, twins = wire_roundtrip(reqs)
    nb = enc.encode_wire(
        messages, relation_tables=enc.native_relation_tables(store)
    )
    pb_batch = encode_requests(
        twins, compiled, caps=_CAPS_FLOOR,
        relation_tables=store.tables_for(compiled),
    )
    assert np.array_equal(nb.eligible, pb_batch.eligible)
    for name in nb.arrays:
        assert np.array_equal(nb.arrays[name], pb_batch.arrays[name]), name

    # and through the kernel: wire decisions == the scalar oracle's walk
    engine.relation_store = store
    kernel = DecisionKernel(compiled)
    decision, _, status = kernel.evaluate(nb)
    n = 0
    for b, req in enumerate(twins):
        if not nb.eligible[b] or status[b] != 200:
            continue
        assert decision[b] == DEC_CODE[engine.is_allowed(req).decision], b
        n += 1
    assert n >= len(reqs) - 1


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_relation_bits_fuzz_matches_python_packer(seed):
    """Structure-free fuzz: random raw row arrays and random (valid)
    flat verdict tables — the C++ relation packer must be bit-identical
    to ops/relation.pack_relation_bitplanes on every case, including the
    multi-word layout (ebits = 2*nru > 32, forced on the later seeds by
    wide NR/NI so rows carry >16 distinct instance runs)."""
    from types import SimpleNamespace

    from access_control_srv_tpu.ops import relation as rel
    from access_control_srv_tpu.ops.encode import (
        alloc_row_arrays,
        owner_bit_layout,
    )

    _, enc, real_compiled = _relation_encoder()
    rng = np.random.default_rng(seed)
    wide = seed >= 2
    B = int(rng.integers(8, 24)) if wide else int(rng.integers(1, 16))
    caps = dict(
        NR=34 if wide else int(rng.integers(1, 8)),
        NI=48 if wide else int(rng.integers(1, 8)),
        NP=8, NSUB=8, NACT=4, NOP=2, NOWN=2, NRA=2, NHR=2, NROLE=4,
        NACLE=2, NACLI=2, NHRR=2,
    )
    a = alloc_row_arrays(B, caps)
    a["r_inst_run"][...] = rng.integers(-1, caps["NR"],
                                        size=a["r_inst_run"].shape)
    a["r_inst_valid"][...] = rng.integers(
        0, 2, size=a["r_inst_valid"].shape).astype(bool)
    a["r_ent_vals"][...] = rng.integers(-1, 12, size=a["r_ent_vals"].shape)
    a["r_inst_id"][...] = rng.integers(-1, 12, size=a["r_inst_id"].shape)
    a["r_subject_id"][...] = rng.integers(-1, 12,
                                          size=a["r_subject_id"].shape)

    RELV = int(rng.integers(1, 7))
    # random but VALID flat tables: per-(vocab, plane) sorted unique
    # object-key segments, plus one globally sorted (row<<32)|subject
    # membership array over ids drawn from the same [0, 12) pool
    segs = []
    for _ in range(2 * RELV):
        k = int(rng.integers(0, 5))
        keys = np.unique(
            (rng.integers(0, 12, size=k).astype(np.int64) << 32)
            | rng.integers(0, 12, size=k).astype(np.int64)
        )
        segs.append(np.sort(keys))
    obj_offs = np.zeros((2 * RELV + 1,), np.int64)
    obj_offs[1:] = np.cumsum([s.shape[0] for s in segs])
    obj_keys = (np.concatenate(segs) if segs
                else np.zeros((0,), np.int64)).astype(np.int64)
    pairs = []
    for row in range(obj_keys.shape[0]):
        for subj in np.unique(rng.integers(0, 12,
                                           size=int(rng.integers(0, 4)))):
            pairs.append((np.int64(row) << 32) | np.int64(subj))
    tables = {
        "obj_offs": obj_offs,
        "obj_keys": obj_keys,
        "pairs": np.sort(np.array(pairs, np.int64))
        if pairs else np.zeros((0,), np.int64),
    }

    fake_compiled = SimpleNamespace(arrays={
        "relv_path": np.zeros((RELV,), np.int32),
        "t_rel_idx": np.array([0], np.int32),
    })
    ref = rel.pack_relation_bitplanes(a, fake_compiled, tables)
    enc.compiled = fake_compiled
    try:
        got = enc.relation_bits_native(a, B, tables=tables)
    finally:
        enc.compiled = real_compiled
    if wide:
        nru = ref["r_rel_runs"].shape[1]
        ebits, epw, _, _ = owner_bit_layout(RELV, nru, 0)
        assert ebits > 32 and epw == 0, "wide seeds must hit multi-word"
    np.testing.assert_array_equal(ref["r_rel_runs"], got["r_rel_runs"],
                                  err_msg=f"seed {seed} runs")
    np.testing.assert_array_equal(
        ref["r_rel_bits"], got["r_rel_bits"].view(np.int32),
        err_msg=f"seed {seed} bits")
