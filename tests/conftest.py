"""Test-session environment: force the CPU platform with 8 virtual devices
so multi-chip sharding paths compile and run without TPU hardware."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")
