"""Test-session environment: force the CPU platform with 8 virtual devices
so multi-chip sharding paths compile and run without TPU hardware.

Note: this machine pre-sets JAX_PLATFORMS=axon (the TPU tunnel); the env
var is overridden externally, so the platform must be forced through
jax.config instead."""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/load tests excluded from the tier-1 run",
    )
