"""Test-session environment: force the CPU platform with 8 virtual devices
so multi-chip sharding paths compile and run without TPU hardware.

Note: this machine pre-sets JAX_PLATFORMS=axon (the TPU tunnel); the env
var is overridden externally, so the platform must be forced through
jax.config instead."""

import os
import signal
import sys
import tempfile

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite compiles the same fixture
# programs from many modules (dense/prefilter/shard/explain variants over
# the same shapes), and CPU backend compiles dominate tier-1 wall clock.
# Keyed on HLO, so later modules hit entries written by earlier ones even
# on a cold run; repeated runs start warm.  Honors an externally-set
# JAX_COMPILATION_CACHE_DIR; errors degrade to a plain compile (JAX
# default jax_raise_persistent_cache_errors=False).
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
    tempfile.gettempdir(), "acs_jax_compile_cache"
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/load tests excluded from the tier-1 run",
    )
    config.addinivalue_line(
        "markers",
        "cluster: multi-process cluster-tier tests (subprocess broker + "
        "replicas behind a router); enforced hard per-test timeout — "
        "override with @pytest.mark.cluster(timeout=N)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection matrix tests (failpoints armed inside "
        "subprocess replicas, crash/corruption recovery); enforced hard "
        "per-test timeout — override with @pytest.mark.chaos(timeout=N)",
    )


# hard ceiling for one cluster-marked test: a hung replica handshake or
# a stuck convergence poll must fail the test, not the whole tier-1 run
CLUSTER_TEST_TIMEOUT_S = 180
# chaos tests deliberately wedge processes (hangs, torn journals) before
# recovering, so they get more headroom than plain cluster bring-up
CHAOS_TEST_TIMEOUT_S = 300


@pytest.fixture(autouse=True)
def _cluster_hard_timeout(request):
    """SIGALRM watchdog for @pytest.mark.cluster / @pytest.mark.chaos
    tests (no pytest-timeout in the image).  Tests run on the main
    thread, so the alarm handler's TimeoutError surfaces as an ordinary
    test failure with a traceback pointing at the stuck line."""
    marker = request.node.get_closest_marker("cluster")
    default_s = CLUSTER_TEST_TIMEOUT_S
    if marker is None:
        marker = request.node.get_closest_marker("chaos")
        default_s = CHAOS_TEST_TIMEOUT_S
    if marker is None or not hasattr(signal, "SIGALRM"):
        yield
        return
    timeout_s = int(marker.kwargs.get("timeout", default_s))

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"cluster test exceeded its {timeout_s}s hard timeout"
        )

    previous = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(timeout_s)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, previous)


@pytest.fixture(scope="session", autouse=True)
def _reap_cluster_orphans():
    """Session backstop: SIGKILL any broker/replica child process a
    cluster test leaked (crashed mid-teardown, timed out before stop()).
    Scans direct children of this process for the package CLI signature
    so an orphan can never outlive the test session."""
    yield
    me = os.getpid()
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:  # non-procfs platform: nothing to sweep
        return
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat") as fh:
                ppid = int(fh.read().split()[3])
            if ppid != me:
                continue
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                cmdline = fh.read().decode(errors="replace")
        except (OSError, ValueError, IndexError):
            continue
        if "access_control_srv_tpu" in cmdline:
            try:
                os.kill(int(pid), signal.SIGKILL)
            except OSError:
                pass
