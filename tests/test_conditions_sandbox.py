"""Condition-sandbox hardening tests: escapes must raise (-> deny-by-default
at the engine), legitimate conditions must evaluate, runaway conditions must
hit the execution budget."""

import time

import pytest

from access_control_srv_tpu.core.conditions import (
    ConditionBudgetExceeded,
    ConditionValidationError,
    condition_matches,
)
from access_control_srv_tpu.models import Request, Target

REQ = Request(
    target=Target(),
    context={"subject": {"id": "ada"}, "resources": [{"id": "ada"}]},
)

ESCAPES = [
    "__import__('os').system('true')",
    "open('/etc/passwd').read()",
    "[c for c in ().__class__.__base__.__subclasses__()][0]",
    "getattr(context, '_obj')",
    "(lambda: __builtins__)()",
    'bool(re.enum.sys.modules["os"].system("true"))',
    'len("{0.__class__.__init__.__globals__}".format(request)) > 0',
    '"{x}".format_map(context)',
    "import os",
    "exec('1')",
    "type(request)",
]


@pytest.mark.parametrize("condition", ESCAPES)
def test_escape_blocked(condition):
    with pytest.raises(Exception) as err:
        condition_matches(condition, REQ)
    assert isinstance(
        err.value, (ConditionValidationError, SyntaxError, AttributeError,
                    NameError, TypeError)
    ), err.value


@pytest.mark.parametrize(
    "condition",
    [
        "def check(request, target, context):\n    while True:\n        pass",
        "sum(1 for i in range(10**12)) > 0",
        "all(True for a in range(10**9) for b in range(10**9))",
    ],
)
def test_runaway_budget(condition):
    t0 = time.time()
    with pytest.raises(ConditionBudgetExceeded):
        condition_matches(condition, REQ)
    assert time.time() - t0 < 5


@pytest.mark.parametrize(
    "condition,expected",
    [
        ("any(r.id == context.subject.id for r in context.resources)", True),
        ("context.subject.id == 'ben'", False),
        ("re.search('ad', context.subject.id)", True),
        ("len(context.resources) == 1", True),
        (
            "def check(request, target, context):\n"
            "    return context.subject.id == 'ada'",
            True,
        ),
        ("lambda request, target, context: True", True),
    ],
)
def test_legitimate_conditions(condition, expected):
    assert condition_matches(condition, REQ) is expected
