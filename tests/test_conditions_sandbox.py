"""Condition-sandbox hardening tests: escapes must raise (-> deny-by-default
at the engine), legitimate conditions must evaluate, runaway conditions must
hit the execution budget."""

import time

import pytest

from access_control_srv_tpu.core.conditions import (
    ConditionBudgetExceeded,
    ConditionValidationError,
    condition_matches,
)
from access_control_srv_tpu.models import Request, Target

REQ = Request(
    target=Target(),
    context={"subject": {"id": "ada"}, "resources": [{"id": "ada"}]},
)

ESCAPES = [
    "__import__('os').system('true')",
    "open('/etc/passwd').read()",
    "[c for c in ().__class__.__base__.__subclasses__()][0]",
    "getattr(context, '_obj')",
    "(lambda: __builtins__)()",
    'bool(re.enum.sys.modules["os"].system("true"))',
    'len("{0.__class__.__init__.__globals__}".format(request)) > 0',
    '"{x}".format_map(context)',
    "import os",
    "exec('1')",
    "type(request)",
]


@pytest.mark.parametrize("condition", ESCAPES)
def test_escape_blocked(condition):
    with pytest.raises(Exception) as err:
        condition_matches(condition, REQ)
    assert isinstance(
        err.value, (ConditionValidationError, SyntaxError, AttributeError,
                    NameError, TypeError)
    ), err.value


@pytest.mark.parametrize(
    "condition",
    [
        "def check(request, target, context):\n    while True:\n        pass",
        "sum(1 for i in range(10**12)) > 0",
        "all(True for a in range(10**9) for b in range(10**9))",
        # C-level loops/allocations the trace budget never sees
        "sum(range(10**12)) > 0",
        "len('x' * 10**10) > 0",
        "10**10**8 > 0",
        "(1 << 10**9) > 0",
        "max(range(10**13)) > 0",
        "len(list(zip(range(10**10), range(10**10)))) > 0",
        "len(dict(zip(range(10**10), range(10**10)))) > 0",
        "len(sorted(range(10**10))) > 0",
        "def check(request, target, context):\n"
        "    s = 'xx'\n"
        "    for i in range(200):\n"
        "        s = s + s\n"
        "    return True",
        "def check(request, target, context):\n"
        "    s = 'xx'\n"
        "    for i in range(200):\n"
        "        s *= 2\n"
        "    return True",
    ],
)
def test_runaway_budget(condition):
    t0 = time.time()
    with pytest.raises(ConditionBudgetExceeded):
        condition_matches(condition, REQ)
    assert time.time() - t0 < 5


@pytest.mark.parametrize(
    "condition",
    ["'x'.zfill(10**9)", "'x'.center(10**9)", "'x'.rjust(10**9)"],
)
def test_allocator_methods_banned(condition):
    with pytest.raises(ConditionValidationError):
        condition_matches(condition, REQ)


@pytest.mark.parametrize(
    "condition",
    [
        # subscript AugAssign would bypass the guarded-binop rewrite
        "def check(request, target, context):\n"
        "    s = ['xx']\n"
        "    for i in range(200):\n"
        "        s[0] += s[0]\n"
        "    return True",
        # oversized f-string format-spec widths
        "len(f'{1:>99999999999}') > 0",
        # dynamic format specs
        "len(f'{1:{99999999999}}') > 0",
    ],
)
def test_validation_blocks_alloc_bypasses(condition):
    with pytest.raises(ConditionValidationError):
        condition_matches(condition, REQ)


@pytest.mark.parametrize(
    "condition",
    [
        # %-format width allocators
        "len('%099999999999d' % 1) > 0",
        # replace amplification: 1M * 1M -> 10^12 chars
        "len(('a' * 1000000).replace('a', 'b' * 1000000)) > 0",
        # join amplification
        "len('-'.join('a' * 1000000 for i in range(100000))) > 0",
        # cumulative allocation: each 1M string is individually legal
        "def check(request, target, context):\n"
        "    parts = []\n"
        "    for i in range(100000):\n"
        "        parts = parts + ['a' * 1000000]\n"
        "    return True",
        # single-C-call bulk mutators consuming unbounded iterators
        "def check(request, target, context):\n"
        "    s = []\n"
        "    s.extend(zip(range(10**10), range(10**10)))\n"
        "    return True",
        "def check(request, target, context):\n"
        "    s = set()\n"
        "    s.update(range(10**10))\n"
        "    return True",
        # sum() with a sequence start = unguarded list concatenation
        "def check(request, target, context):\n"
        "    s = list(range(1000))\n"
        "    for i in range(40):\n"
        "        s = sum([s, s], [])\n"
        "    return True",
        # '*'-width takes the pad width from the args, not the format string
        "len('%*d' % (10**11, 1)) > 0",
    ],
)
def test_runtime_alloc_guards(condition):
    t0 = time.time()
    with pytest.raises(ConditionBudgetExceeded):
        condition_matches(condition, REQ)
    assert time.time() - t0 < 10


@pytest.mark.parametrize(
    "condition,expected",
    [
        ("'%s-%d' % ('a', 1) == 'a-1'", True),
        ("'a,b'.replace(',', ';') == 'a;b'", True),
        ("'-'.join(['a', 'b']) == 'a-b'", True),
        ("f'{1:>3}' == '  1'", True),
        ("7 % 3 == 1", True),
        (
            "def check(request, target, context):\n"
            "    s = [1]\n"
            "    s.extend([2, 3])\n"
            "    d = {}\n"
            "    d.update({'a': 1})\n"
            "    return s == [1, 2, 3] and d == {'a': 1}",
            True,
        ),
        ("sum([1, 2], 3) == 6", True),
    ],
)
def test_guarded_string_ops_preserve_semantics(condition, expected):
    assert condition_matches(condition, REQ) is expected


@pytest.mark.parametrize(
    "condition,expected",
    [
        ("1 + 1 == 2", True),
        ("2 * 3 == 6", True),
        ("2 ** 10 == 1024", True),
        ("1 << 4 == 16", True),
        ("'ab' + 'cd' == 'abcd'", True),
        ("'ab' * 2 == 'abab'", True),
        ("sum(range(100)) == 4950", True),
        ("sorted([3, 1, 2]) == [1, 2, 3]", True),
        ("min([3, 1, 2]) == 1 and max(3, 1, 2) == 3", True),
        ("dict(zip(['a'], ['b'])) == {'a': 'b'}", True),
        (
            "def check(request, target, context):\n"
            "    n = 1\n"
            "    n *= 8\n"
            "    return n == 8",
            True,
        ),
    ],
)
def test_guarded_ops_preserve_semantics(condition, expected):
    assert condition_matches(condition, REQ) is expected


@pytest.mark.parametrize(
    "condition,expected",
    [
        ("any(r.id == context.subject.id for r in context.resources)", True),
        ("context.subject.id == 'ben'", False),
        ("re.search('ad', context.subject.id)", True),
        ("len(context.resources) == 1", True),
        (
            "def check(request, target, context):\n"
            "    return context.subject.id == 'ada'",
            True,
        ),
        ("lambda request, target, context: True", True),
    ],
)
def test_legitimate_conditions(condition, expected):
    assert condition_matches(condition, REQ) is expected
