"""Extended randomized differential: one fuzz pass drives all three device
paths — dense kernel, candidate-compacted (prefiltered) kernel and the
batched reverse query — against the scalar oracle on policy/request shapes
the base generator does not reach:

- entities from FOREIGN URN namespaces (exercises the regex-mode prefix
  mismatch RESET, kernel sticky-state scan; reference
  accessController.ts:545-566);
- occasional None ACL entity/instance values (must fall back, advisor r2);
- deep hierarchical-scope trees (adaptive caps path);
- wider property lists and mixed operation/entity requests."""

import copy
import random

import numpy as np

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.ops import (
    DecisionKernel,
    PrefilteredKernel,
    ReverseQueryKernel,
    compile_policies,
    encode_requests,
    what_is_allowed_batch,
)

from .test_kernel_differential import (
    ACTIONS,
    DEC_CODE,
    ENTITIES,
    OWNERS,
    PROPS,
    ROLES,
    SUBJECTS,
    _random_policy_tree,
)
from .test_prefilter import force_active
from .test_reverse import rq_shape
from .utils import URNS, build_request

FOREIGN = [
    "urn:acme:models:gadget.Gadget",
    "urn:other:ns:thing.Thing",
    "urn:restorecommerce:acs:model:widget.Widget",
]


def _extended_tree(rng: random.Random):
    """Base random tree with some entity values swapped to foreign
    namespaces (regex prefix comparisons now genuinely differ)."""
    doc = _random_policy_tree(rng)
    for ps in doc["policy_sets"]:
        for pol in ps["policies"]:
            for node in [pol] + list(pol.get("rules") or []):
                tgt = node.get("target") or {}
                for attr in tgt.get("resources") or []:
                    if attr["id"] == URNS["entity"] and rng.random() < 0.3:
                        attr["value"] = rng.choice(FOREIGN)
    return doc


def _deep_scopes(rng: random.Random):
    depth = rng.randint(3, 6)

    def node(i, d):
        out = {"id": f"n{d}-{i}"}
        if d < depth:
            out["children"] = [node(j, d + 1) for j in range(2)]
        if rng.random() < 0.3:
            out["role"] = rng.choice(ROLES)
        return out

    return [node(0, 0)]


def _extended_requests(rng: random.Random, n: int):
    out = []
    pool = ENTITIES + FOREIGN
    for i in range(n):
        multi = rng.random() < 0.35
        rtype = rng.sample(pool, 2) if multi else rng.choice(pool)
        rid = [f"id-{k}" for k in range(2)] if multi else "id-0"
        kwargs = dict(
            subject_id=rng.choice(SUBJECTS),
            subject_role=rng.choice(ROLES),
            role_scoping_entity=(
                "urn:restorecommerce:acs:model:organization.Organization"
            ),
            role_scoping_instance=rng.choice(OWNERS),
            resource_type=rtype,
            resource_id=rid,
            action_type=rng.choice(ACTIONS[:4]),
        )
        if rng.random() < 0.6:
            kwargs["resource_property"] = rng.sample(PROPS, rng.randint(1, 3))
        if rng.random() < 0.5:
            kwargs["owner_indicatory_entity"] = (
                "urn:restorecommerce:acs:model:organization.Organization"
            )
            kwargs["owner_instance"] = (
                [rng.choice(OWNERS), rng.choice(OWNERS)] if multi
                else rng.choice(OWNERS)
            )
        if rng.random() < 0.25:
            kwargs["acl_indicatory_entity"] = rng.choice(pool[:2])
            kwargs["acl_instances"] = rng.sample(OWNERS, rng.randint(1, 2))
        request = build_request(**kwargs)
        if rng.random() < 0.2:
            request.context["subject"]["hierarchical_scopes"] = (
                _deep_scopes(rng)
            )
        if rng.random() < 0.1:
            # inject a None ACL value: must fall back, never diverge
            request.context["resources"].append({
                "id": "id-0",
                "meta": {"owners": [], "acls": [{
                    "id": URNS["aclIndicatoryEntity"], "value": None,
                    "attributes": [
                        {"id": URNS["aclInstance"], "value": "x"}
                    ],
                }]},
            })
        out.append(request)
    return out


def test_extended_fuzz_all_device_paths():
    rng = random.Random(9000)
    total_eligible = 0
    for round_ in range(8):
        doc = _extended_tree(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        requests = _extended_requests(rng, 40)

        batch = encode_requests(requests, compiled)
        dense = DecisionKernel(compiled)
        dd, dc, ds = dense.evaluate(batch)
        pre = force_active(PrefilteredKernel(compiled))
        pd_, pc, ps_ = pre.evaluate(batch)
        assert np.array_equal(dd, pd_), f"round {round_}: prefilter != dense"
        assert np.array_equal(dc, pc)
        assert np.array_equal(ds, ps_)

        for b, request in enumerate(requests):
            expected = engine.is_allowed(copy.deepcopy(request))
            if not batch.eligible[b]:
                continue
            total_eligible += 1
            assert dd[b] == DEC_CODE[expected.decision], (
                f"round {round_} request {b}: kernel={dd[b]} "
                f"oracle={expected.decision}"
            )

        rq_kernel = ReverseQueryKernel(compiled, engine.policy_sets)
        oracle_rq = [
            engine.what_is_allowed(copy.deepcopy(r)) for r in requests
        ]
        kernel_rq = what_is_allowed_batch(
            engine, compiled, rq_kernel,
            [copy.deepcopy(r) for r in requests],
        )
        for b in range(len(requests)):
            assert rq_shape(kernel_rq[b]) == rq_shape(oracle_rq[b]), (
                f"round {round_} request {b}: reverse query diverged"
            )
    assert total_eligible > 120  # the fuzz must exercise the device path


def _force_scoped_tree(rng: random.Random):
    """Random tree with a roleScopingEntity forced onto EVERY role-bearing
    subject (and random HR-disable attributes): stage B is then
    non-trivial for every role-targeted row, driving the owner-bitplane
    path on arbitrary random shapes instead of the curated fixtures."""
    doc = _extended_tree(rng)
    for ps in doc["policy_sets"]:
        for pol in ps["policies"]:
            for node in [pol] + list(pol.get("rules") or []):
                tgt = node.get("target") or {}
                subs = tgt.get("subjects") or []
                has_role = any(a["id"] == URNS["role"] for a in subs)
                has_scope = any(
                    a["id"] == URNS["roleScopingEntity"] for a in subs
                )
                if has_role and not has_scope:
                    subs.append({
                        "id": URNS["roleScopingEntity"],
                        "value": (
                            "urn:restorecommerce:acs:model:"
                            "organization.Organization"
                        ),
                    })
                    if rng.random() < 0.25:
                        subs.append({
                            "id": URNS["hierarchicalRoleScoping"],
                            "value": "false",
                        })
    return doc


def test_owner_bitplane_fuzz():
    """Owner-bitplane fuzz: fully role-scoped random trees (stage B active
    on every role row) against request shapes covering empty owner sets,
    deep HR closures, multi-entity owner rows and the HR-disable
    attribute — dense kernel, prefiltered signature kernel and oracle must
    stay bit-identical."""
    rng = random.Random(4242)
    total_eligible = 0
    for round_ in range(6):
        doc = _force_scoped_tree(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        requests = _extended_requests(rng, 40)
        batch = encode_requests(requests, compiled)
        dense = DecisionKernel(compiled)
        dd, dc, ds = dense.evaluate(batch)
        pre = force_active(PrefilteredKernel(compiled))
        pd_, pc, ps_ = pre.evaluate(batch)
        assert np.array_equal(dd, pd_), (
            f"round {round_}: prefilter != dense (owner bitplanes)"
        )
        assert np.array_equal(dc, pc)
        assert np.array_equal(ds, ps_)
        for b, request in enumerate(requests):
            if not batch.eligible[b]:
                continue
            expected = engine.is_allowed(copy.deepcopy(request))
            total_eligible += 1
            assert dd[b] == DEC_CODE[expected.decision], (
                f"round {round_} request {b}: kernel={dd[b]} "
                f"oracle={expected.decision}"
            )
    assert total_eligible > 80


CONDITIONS = [
    "any(r.id == context.subject.id for r in (context.resources or []))",
    "context.subject.id == 'ada'",
    "len(context.resources or []) > 0",
    "1 <= 2",
    # raising condition: missing attribute -> DENY with error code+message
    "context.subject.nonexistent_field == 1",
]


def _tree_with_conditions(rng: random.Random):
    doc = _extended_tree(rng)
    for ps in doc["policy_sets"]:
        for pol in ps["policies"]:
            for rule in pol.get("rules") or []:
                if rng.random() < 0.25:
                    rule["condition"] = rng.choice(CONDITIONS)
    return doc


def test_token_and_context_query_fuzz():
    """Host-pipeline fuzz (ISSUE 3): random condition trees with adapter
    context queries sprinkled on ~half the condition rules, random
    requests where ~half the subjects arrive as bare tokens — the full
    evaluator path (batched resolution -> prefetch/fusion -> kernel/oracle
    hybrid) must stay bit-identical to the oracle for every row, whatever
    mix of fused, degraded and unresolved rows a round produces."""
    from access_control_srv_tpu.srv.cache import HRScopeProvider, SubjectCache
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator
    from access_control_srv_tpu.srv.identity import (
        CachingIdentityClient,
        StaticIdentityClient,
    )

    class StubAdapter:
        def query(self, context_query, request):
            # deterministic, filter-dependent result so fused rows and
            # oracle re-pulls observe the same data
            filters = getattr(context_query, "filters", None) or []
            value = None
            if filters:
                from access_control_srv_tpu.core.common import get_field

                value = get_field(filters[0], "value")
            return [{"id": value or "id-0"}]

    rng = random.Random(77001)
    checked = fused = token_rows = 0
    for round_ in range(6):
        doc = _tree_with_conditions(rng)
        for ps in doc["policy_sets"]:
            for pol in ps["policies"]:
                for rule in pol.get("rules") or []:
                    if rule.get("condition") and rng.random() < 0.5:
                        rule["context_query"] = {
                            "filters": [{"field": "id", "operation": "eq",
                                         "value": "id-0"}],
                            "query": "query q { all { id } }",
                        }
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        engine.resource_adapter = StubAdapter()

        ids = StaticIdentityClient()
        subject_cache = SubjectCache()
        engine.identity_client = CachingIdentityClient(ids)
        engine.hr_scope_provider = HRScopeProvider(subject_cache)

        requests = _extended_requests(rng, 40)
        for i, request in enumerate(requests):
            if rng.random() >= 0.5:
                continue
            subject = request.context["subject"]
            token = f"fuzz-tok-{round_}-{i}"
            subject_id = subject.get("id") or f"anon-{i}"
            ids.register(token, {
                "id": subject_id,
                "tokens": [{"token": token, "interactive": True}],
                "role_associations": subject.get("role_associations"),
            })
            scopes = subject.get("hierarchical_scopes")
            if scopes is not None:
                subject_cache.set(f"cache:{subject_id}:hrScopes", scopes)
            # occasional unresolvable token: must degrade, never diverge
            request.context["subject"] = {
                "token": token if rng.random() < 0.85 else f"bad-{token}"
            }
            token_rows += 1

        expected = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        ev = HybridEvaluator(engine)
        responses = ev.is_allowed_batch([copy.deepcopy(r) for r in requests])
        for b in range(len(requests)):
            checked += 1
            assert responses[b].decision == expected[b].decision, (
                round_, b, responses[b].decision, expected[b].decision)
            assert responses[b].operation_status.code == \
                expected[b].operation_status.code, (round_, b)
            assert responses[b].evaluation_cacheable == \
                expected[b].evaluation_cacheable, (round_, b)
        prepared = [copy.deepcopy(r) for r in requests]
        ev.prepare_batch(prepared)
        batch = encode_requests(
            prepared, ev._compiled, engine.resource_adapter
        )
        fused += int(batch.eligible.sum())
    assert checked >= 200
    assert token_rows >= 80
    assert fused >= 100  # the pipeline must actually keep rows on device


def test_conditions_fuzz_through_evaluator():
    """Randomized trees WITH conditions through the full evaluator batch
    path: decisions, status codes AND operation_status messages (the
    abort-message fast path) must equal the oracle for every row."""
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    rng = random.Random(31337)
    checked = 0
    for round_ in range(6):
        doc = _tree_with_conditions(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        ev = HybridEvaluator(engine)
        requests = _extended_requests(rng, 40)
        expected = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        responses = ev.is_allowed_batch([copy.deepcopy(r) for r in requests])
        for b in range(len(requests)):
            checked += 1
            assert responses[b].decision == expected[b].decision, (
                round_, b, responses[b].decision, expected[b].decision)
            assert responses[b].operation_status.code == \
                expected[b].operation_status.code, (round_, b)
            assert responses[b].operation_status.message == \
                expected[b].operation_status.message, (round_, b)
            assert responses[b].evaluation_cacheable == \
                expected[b].evaluation_cacheable, (round_, b)
    assert checked >= 200
