"""Golden tests for policy-level and policy-set-level targets, property
rules, bare-effect policies and HR owner matching."""

import pytest

from access_control_srv_tpu.models import Decision

from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
ADDR = "urn:restorecommerce:acs:model:address.Address"
LOC = "urn:restorecommerce:acs:model:location.Location"
READ = URNS["read"]
MODIFY = URNS["modify"]


def check(engine, expected, **kwargs):
    defaults = dict(
        subject_role="member",
        role_scoping_entity=ORG,
        role_scoping_instance="Org1",
    )
    defaults.update(kwargs)
    response = engine.is_allowed(build_request(**defaults))
    assert response.decision == expected, kwargs
    return response


class TestPolicyTargets:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("policy_targets.yml")

    def test_permit_read_secret(self, engine):
        check(engine, Decision.PERMIT, subject_id="ben", resource_type=ORG,
              resource_property=ORG + "#secret_field", resource_id="Ben GmbH",
              action_type=READ)

    def test_deny_modify_secret(self, engine):
        check(engine, Decision.DENY, subject_id="ben", resource_type=ORG,
              resource_property=ORG + "#secret_field", resource_id="Ben GmbH",
              action_type=MODIFY)

    def test_policy_combining_permits_ada(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=ORG,
              resource_property=ORG + "#secret_field", resource_id="Ada GmbH",
              action_type=MODIFY)

    def test_indeterminate_out_of_policy_target(self, engine):
        check(engine, Decision.INDETERMINATE, subject_id="ada", resource_type=USER,
              resource_property=USER + "#password", resource_id="ada",
              action_type=MODIFY)

    def test_permit_street_rule(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=ADDR,
              resource_property=ADDR + "#street", resource_id="Main St",
              action_type=MODIFY)

    def test_permit_bare_effect_policy(self, engine):
        check(engine, Decision.PERMIT, subject_id="dee", resource_type=ORG,
              resource_property=ORG + "#name", resource_id="Dee Inc",
              action_type=READ)


class TestPolicySetTargets:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("policy_set_targets.yml")

    def test_permit_read_org(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=ORG,
              resource_property=ORG + "#name", resource_id="O1", action_type=READ)

    def test_indeterminate_user_for_member(self, engine):
        check(engine, Decision.INDETERMINATE, subject_id="ada", resource_type=USER,
              resource_property=USER + "#name", resource_id="ben", action_type=READ)

    def test_deny_modify_org(self, engine):
        check(engine, Decision.DENY, subject_id="ben", resource_type=ORG,
              resource_property=ORG + "#name", resource_id="O1", action_type=MODIFY)

    def test_permit_guest_read_user(self, engine):
        check(engine, Decision.PERMIT, subject_id="kai", subject_role="guest",
              resource_type=USER, resource_property=USER + "#name",
              resource_id="ben", action_type=READ)

    def test_deny_guest_modify_user(self, engine):
        check(engine, Decision.DENY, subject_id="kai", subject_role="guest",
              resource_type=USER, resource_property=USER + "#name",
              resource_id="ben", action_type=MODIFY)

    def test_indeterminate_owner_outside_hr_scope(self, engine):
        check(engine, Decision.INDETERMINATE, subject_id="ada",
              subject_role="manager", resource_type=LOC, resource_id="L1",
              action_type=MODIFY, owner_indicatory_entity=ORG,
              owner_instance="Org4")

    def test_permit_owner_in_hr_scope(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", subject_role="manager",
              resource_type=LOC, resource_id="L1", action_type=MODIFY,
              owner_indicatory_entity=ORG, owner_instance="Org2")
