"""Observability: latency histograms, decision counters, secret-masking
logging, and the metrics command (SURVEY.md §5 aux subsystems)."""

import logging

import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.telemetry import (
    Histogram,
    MaskingFilter,
    Telemetry,
    mask_secrets,
)

from .test_srv import admin_request, seed_cfg


def test_mask_secrets_deep():
    payload = {
        "subject": {"id": "u", "token": "s3cret", "password": "pw"},
        "items": [{"apiKey": "k", "name": "ok"}],
        "authorization": "Bearer xyz",
        "note": "keep",
    }
    masked = mask_secrets(payload)
    assert masked["subject"]["token"] == "***"
    assert masked["subject"]["password"] == "***"
    assert masked["items"][0]["apiKey"] == "***"
    assert masked["authorization"] == "***"
    assert masked["note"] == "keep"
    assert masked["subject"]["id"] == "u"
    # original untouched
    assert payload["subject"]["token"] == "s3cret"


def test_masking_filter_on_log_args():
    # a single-dict args tuple is unpacked to the dict by LogRecord itself
    record = logging.LogRecord(
        "t", logging.INFO, __file__, 1, "ctx %s", ({"token": "abc"},), None
    )
    assert MaskingFilter().filter(record)
    assert record.args["token"] == "***"

    record = logging.LogRecord(
        "t", logging.INFO, __file__, 1, "a=%s b=%s",
        ({"password": "x"}, "plain"), None
    )
    assert MaskingFilter().filter(record)
    assert record.args[0]["password"] == "***"
    assert record.args[1] == "plain"


def test_histogram_buckets_and_mean():
    h = Histogram()
    for v in (1e-5, 1e-3, 0.1, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["buckets"]["inf"] == 4
    assert snap["buckets"]["5e-05"] == 1
    assert abs(snap["mean_s"] - (1e-5 + 1e-3 + 0.1 + 5.0) / 4) < 1e-6


def test_histogram_percentile_estimates():
    """snapshot() reports interpolated p50/p95/p99 so consumers
    (health_check, bench rows) read percentiles, not bucket arrays."""
    h = Histogram()
    for _ in range(98):
        h.observe(0.010)       # bucket (0.0128]: (0.0032, 0.0128]
    for _ in range(2):
        h.observe(100.0)       # inf bucket
    snap = h.snapshot()
    # p50 interpolates inside the 3.2ms..12.8ms bucket
    assert 0.0032 <= snap["p50_s"] <= 0.0128
    assert 0.0032 <= snap["p95_s"] <= 0.0128
    # p99 lands in the inf bucket -> clamped to the last finite bound
    assert snap["p99_s"] == pytest.approx(52.4)
    # monotone
    assert snap["p50_s"] <= snap["p95_s"] <= snap["p99_s"]


def test_empty_histogram_percentiles_are_none():
    snap = Histogram().snapshot()
    assert snap["p50_s"] is None
    assert snap["p95_s"] is None
    assert snap["p99_s"] is None


def test_value_histogram_percentiles():
    from access_control_srv_tpu.srv.telemetry import ValueHistogram

    h = ValueHistogram()
    for depth in (1, 2, 3, 4, 100, 200, 300, 400, 500, 5000):
        h.observe(depth)
    snap = h.snapshot()
    assert snap["p50"] is not None
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    assert snap["max"] == 5000


def test_service_records_latency_and_decisions():
    w = Worker().start(seed_cfg())
    try:
        for _ in range(3):
            w.service.is_allowed(admin_request())
        w.service.is_allowed_batch([admin_request(), admin_request()])
        w.service.what_is_allowed(admin_request())
        snap = w.telemetry.snapshot()
        assert snap["is_allowed_latency"]["count"] == 3
        assert snap["batch_latency"]["count"] == 1
        assert snap["what_is_allowed_latency"]["count"] == 1
        assert snap["decisions"].get("PERMIT", 0) >= 5
        # the metrics command serves the same snapshot
        via_cmd = w.command_interface.command("metrics", {})
        assert via_cmd["decisions"] == snap["decisions"]
    finally:
        w.stop()


def test_telemetry_paths_counter():
    t = Telemetry()
    t.record_path("kernel", 10)
    t.record_path("oracle", 2)
    t.record_path("kernel", 5)
    assert t.paths.snapshot() == {"kernel": 15, "oracle": 2}


def test_error_paths_still_counted():
    w = Worker().start(seed_cfg())
    try:
        # a request shape that blows up in coercion -> deny-on-exception
        w.service.is_allowed({"target": object()})
        snap = w.telemetry.snapshot()
        assert snap["is_allowed_latency"]["count"] == 1
        assert snap["decisions"].get("DENY", 0) == 1
    finally:
        w.stop()


def test_paths_counter_instrumented():
    w = Worker().start(seed_cfg())
    try:
        w.service.is_allowed_batch([admin_request(), admin_request()])
        paths = w.telemetry.paths.snapshot()
        assert paths.get("kernel", 0) == 2, paths
    finally:
        w.stop()


def test_health_check_reports_decision_cache_counters():
    """Decision-cache hits/misses/evictions + hit ratio surface on BOTH
    operator surfaces: the health_check payload and the telemetry snapshot
    (ISSUE 1 satellite: cache efficacy must be observable)."""
    w = Worker().start(seed_cfg())
    try:
        w.service.is_allowed(admin_request())  # cold: miss + write-through
        w.service.is_allowed(admin_request())  # warm: hit
        health = w.command_interface.command("health_check")
        dc = health["decision_cache"]
        assert dc["hits"] >= 1
        assert dc["misses"] >= 1
        assert dc["stores"] >= 1
        assert 0.0 < dc["hit_ratio"] <= 1.0
        assert dc["entries"] >= 1
        # the same counters flow through the Telemetry.cache counter into
        # the metrics snapshot
        snap = w.telemetry.snapshot()["decision_cache"]
        assert snap.get("hits", 0) == dc["hits"]
        assert snap.get("misses", 0) == dc["misses"]
        # cache-hit rows are attributed on the serving-path counter too
        assert w.telemetry.paths.snapshot().get("cache-hit", 0) >= 1
    finally:
        w.stop()


def test_mask_namedtuple_survives():
    from collections import namedtuple

    Point = namedtuple("Point", "x y")
    masked = mask_secrets({"p": Point(1, 2), "token": "x"})
    assert masked["p"] == Point(1, 2)
    assert masked["token"] == "***"


def test_masking_filter_extra_payload():
    record = logging.LogRecord("t", logging.INFO, __file__, 1, "msg", (), None)
    record.ctx = {"token": "leak"}
    assert MaskingFilter().filter(record)
    assert record.ctx["token"] == "***"


def test_native_wire_path_records_metrics():
    import os

    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

    from .test_grpc_transport import SEED, wire_request

    w = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )
    server = GrpcServer(w, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        if not w.evaluator.native_active:
            import pytest

            pytest.skip("native encoder unavailable")
        client.is_allowed_batch(
            pb.BatchRequest(requests=[wire_request(), wire_request()])
        )
        snap = w.telemetry.snapshot()
        assert snap["batch_latency"]["count"] == 1
        assert snap["decisions"].get("PERMIT", 0) == 2
        assert snap["paths"].get("native-wire", 0) == 2
    finally:
        client.close()
        server.stop()
        w.stop()


def test_json_sink_ships_masked_structured_lines(tmp_path):
    """logging:json_sink appends one JSON object per record with extra
    fields included and secrets masked — the shape external shippers
    tail (the reference's production Elasticsearch-transport role)."""
    import json
    import logging

    from access_control_srv_tpu.srv.telemetry import make_logger

    sink = tmp_path / "acs.log.jsonl"
    logger = make_logger("test-json-sink", json_sink=str(sink))
    try:
        logger.info("policy loaded", extra={
            "policy_sets": 3,
            "subject": {"id": "u1", "token": "supersecret"},
        })
        logger.warning("auth failed", extra={"password": "hunter2"})
    finally:
        for h in list(logger.handlers):
            h.close()
            logger.removeHandler(h)
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert lines[0]["message"] == "policy loaded"
    assert lines[0]["policy_sets"] == 3
    assert lines[0]["subject"]["token"] == "***"
    assert lines[1]["level"] == "WARNING"
    assert lines[1]["password"] == "***"
    assert all("@timestamp" in ln for ln in lines)


def test_health_check_reports_latency_percentiles():
    """health_check surfaces interpolated latency percentiles, not raw
    bucket arrays (observability satellite)."""
    w = Worker().start(seed_cfg())
    try:
        for _ in range(5):
            w.service.is_allowed(admin_request())
        health = w.command_interface.command("health_check")
        latency = health["latency"]["is_allowed"]
        assert latency["count"] == 5
        assert latency["p50_ms"] is not None
        assert latency["p50_ms"] <= latency["p95_ms"] <= latency["p99_ms"]
    finally:
        w.stop()


def test_prometheus_exposition_format():
    """The registry renders valid Prometheus text exposition: HELP/TYPE
    headers, labeled counter series, cumulative histogram buckets with
    +Inf, _sum and _count."""
    t = Telemetry()
    t.decisions.inc("PERMIT", 3)
    t.decisions.inc("DENY")
    t.is_allowed_latency.observe(0.002)
    t.is_allowed_latency.observe(0.004)
    body = t.prometheus()
    assert "# TYPE acs_decisions_total counter" in body
    assert 'acs_decisions_total{decision="PERMIT"} 3' in body
    assert 'acs_decisions_total{decision="DENY"} 1' in body
    assert "# TYPE acs_is_allowed_latency_seconds histogram" in body
    assert 'acs_is_allowed_latency_seconds_bucket{le="+Inf"} 2' in body
    assert "acs_is_allowed_latency_seconds_count 2" in body
    # cumulative buckets are monotone non-decreasing
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if line.startswith("acs_is_allowed_latency_seconds_bucket")
    ]
    assert counts == sorted(counts)
    # empty counters render nothing (no empty families)
    assert "acs_admission_events_total" not in body


def test_prometheus_label_escaping():
    t = Telemetry()
    t.paths.inc('weird"key\\with\nstuff')
    body = t.prometheus()
    assert 'path="weird\\"key\\\\with\\nstuff"' in body


def test_snapshot_deep_copy_under_mutation_stress():
    """Concurrent metrics/health_check readers must never observe a dict
    mutating mid-serialization: snapshot() assembles under the lock and
    returns a deep copy, so json.dumps over it cannot race a writer."""
    import json as _json
    import threading

    t = Telemetry()
    stop = threading.Event()
    errors = []

    def writer(n):
        i = 0
        while not stop.is_set():
            t.decisions.inc(f"D{i % 37}")
            t.paths.inc(f"path-{i % 11}", 2)
            t.admission.inc(f"k{i % 7}")
            t.is_allowed_latency.observe(0.001 * (i % 5))
            t.stage_histogram(f"stage-{i % 3}").observe(0.0001)
            i += 1

    def reader():
        while not stop.is_set():
            snap = t.snapshot()
            try:
                _json.dumps(snap)
            except Exception as err:  # noqa: BLE001
                errors.append(err)
                return
            # mutating the returned snapshot must not touch live state
            snap["decisions"]["INJECTED"] = 1

    threads = [threading.Thread(target=writer, args=(n,)) for n in range(2)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for thread in threads:
        thread.start()
    import time as _time

    _time.sleep(0.5)
    stop.set()
    for thread in threads:
        thread.join()
    assert not errors, errors
    assert "INJECTED" not in t.decisions.snapshot()


def test_sampled_logger_importable_from_telemetry():
    from access_control_srv_tpu.srv.telemetry import SampledLogger

    slog = SampledLogger(None, max_per_interval=2)
    slog.warning("k", "m")  # None logger: no-op by contract


def test_worker_config_wires_json_sink(tmp_path):
    import json
    import os

    from access_control_srv_tpu.srv import Worker

    seed = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "seed_data",
    )
    sink = tmp_path / "worker.jsonl"
    worker = Worker().start({
        "logging": {"json_sink": str(sink)},
        "policies": {"type": "database"},
        "seed_data": {
            "policy_sets": os.path.join(seed, "policy_sets.yaml"),
            "policies": os.path.join(seed, "policies.yaml"),
            "rules": os.path.join(seed, "rules.yaml"),
        },
    })
    try:
        worker.logger.info("sink probe", extra={"probe": True})
    finally:
        worker.stop()
        for h in list(worker.logger.handlers):
            h.close()
            worker.logger.removeHandler(h)
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert any(ln.get("probe") for ln in lines)


def test_stages_view_is_detached_snapshot():
    """The histogram_group for per-stage durations must hand render() a
    COPY of the stage map: iterating the live dict while stage_histogram
    lazily inserts a new stage raises ``dict changed size during
    iteration`` mid-scrape (regression for the registered live-dict fn)."""
    t = Telemetry()
    t.stage_histogram("encode").observe(0.001)
    view = t._stages_view()
    assert view is not t.stages
    # a late-bound stage appears in the live map but not the taken view
    t.stage_histogram("dispatch").observe(0.002)
    assert "dispatch" in t.stages and "dispatch" not in view
    # the NEXT render does see it (late-bound members appear at scrape)
    body = t.prometheus()
    assert 'acs_stage_duration_seconds_count{stage="dispatch"} 1' in body
    assert 'acs_stage_duration_seconds_count{stage="encode"} 1' in body


def test_prometheus_render_survives_stage_insertions():
    """Scrape concurrently with lazy stage creation: before _stages_view
    the group fn returned the live dict and render() died with
    RuntimeError('dict changed size during iteration')."""
    import threading as _threading

    t = Telemetry()
    stop = _threading.Event()
    errors = []

    def inserter():
        i = 0
        while not stop.is_set():
            t.stage_histogram(f"stage-{i}").observe(0.0001)
            i += 1

    def scraper():
        while not stop.is_set():
            try:
                t.prometheus()
            except RuntimeError as err:
                errors.append(err)
                return

    threads = [_threading.Thread(target=inserter),
               _threading.Thread(target=scraper),
               _threading.Thread(target=scraper)]
    for thread in threads:
        thread.start()
    import time as _time

    _time.sleep(0.4)
    stop.set()
    for thread in threads:
        thread.join()
    assert not errors, errors
