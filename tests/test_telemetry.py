"""Observability: latency histograms, decision counters, secret-masking
logging, and the metrics command (SURVEY.md §5 aux subsystems)."""

import logging

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.telemetry import (
    Histogram,
    MaskingFilter,
    Telemetry,
    mask_secrets,
)

from .test_srv import admin_request, seed_cfg


def test_mask_secrets_deep():
    payload = {
        "subject": {"id": "u", "token": "s3cret", "password": "pw"},
        "items": [{"apiKey": "k", "name": "ok"}],
        "authorization": "Bearer xyz",
        "note": "keep",
    }
    masked = mask_secrets(payload)
    assert masked["subject"]["token"] == "***"
    assert masked["subject"]["password"] == "***"
    assert masked["items"][0]["apiKey"] == "***"
    assert masked["authorization"] == "***"
    assert masked["note"] == "keep"
    assert masked["subject"]["id"] == "u"
    # original untouched
    assert payload["subject"]["token"] == "s3cret"


def test_masking_filter_on_log_args():
    # a single-dict args tuple is unpacked to the dict by LogRecord itself
    record = logging.LogRecord(
        "t", logging.INFO, __file__, 1, "ctx %s", ({"token": "abc"},), None
    )
    assert MaskingFilter().filter(record)
    assert record.args["token"] == "***"

    record = logging.LogRecord(
        "t", logging.INFO, __file__, 1, "a=%s b=%s",
        ({"password": "x"}, "plain"), None
    )
    assert MaskingFilter().filter(record)
    assert record.args[0]["password"] == "***"
    assert record.args[1] == "plain"


def test_histogram_buckets_and_mean():
    h = Histogram()
    for v in (1e-5, 1e-3, 0.1, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["buckets"]["inf"] == 4
    assert snap["buckets"]["5e-05"] == 1
    assert abs(snap["mean_s"] - (1e-5 + 1e-3 + 0.1 + 5.0) / 4) < 1e-6


def test_service_records_latency_and_decisions():
    w = Worker().start(seed_cfg())
    try:
        for _ in range(3):
            w.service.is_allowed(admin_request())
        w.service.is_allowed_batch([admin_request(), admin_request()])
        w.service.what_is_allowed(admin_request())
        snap = w.telemetry.snapshot()
        assert snap["is_allowed_latency"]["count"] == 3
        assert snap["batch_latency"]["count"] == 1
        assert snap["what_is_allowed_latency"]["count"] == 1
        assert snap["decisions"].get("PERMIT", 0) >= 5
        # the metrics command serves the same snapshot
        via_cmd = w.command_interface.command("metrics", {})
        assert via_cmd["decisions"] == snap["decisions"]
    finally:
        w.stop()


def test_telemetry_paths_counter():
    t = Telemetry()
    t.record_path("kernel", 10)
    t.record_path("oracle", 2)
    t.record_path("kernel", 5)
    assert t.paths.snapshot() == {"kernel": 15, "oracle": 2}


def test_error_paths_still_counted():
    w = Worker().start(seed_cfg())
    try:
        # a request shape that blows up in coercion -> deny-on-exception
        w.service.is_allowed({"target": object()})
        snap = w.telemetry.snapshot()
        assert snap["is_allowed_latency"]["count"] == 1
        assert snap["decisions"].get("DENY", 0) == 1
    finally:
        w.stop()


def test_paths_counter_instrumented():
    w = Worker().start(seed_cfg())
    try:
        w.service.is_allowed_batch([admin_request(), admin_request()])
        paths = w.telemetry.paths.snapshot()
        assert paths.get("kernel", 0) == 2, paths
    finally:
        w.stop()


def test_health_check_reports_decision_cache_counters():
    """Decision-cache hits/misses/evictions + hit ratio surface on BOTH
    operator surfaces: the health_check payload and the telemetry snapshot
    (ISSUE 1 satellite: cache efficacy must be observable)."""
    w = Worker().start(seed_cfg())
    try:
        w.service.is_allowed(admin_request())  # cold: miss + write-through
        w.service.is_allowed(admin_request())  # warm: hit
        health = w.command_interface.command("health_check")
        dc = health["decision_cache"]
        assert dc["hits"] >= 1
        assert dc["misses"] >= 1
        assert dc["stores"] >= 1
        assert 0.0 < dc["hit_ratio"] <= 1.0
        assert dc["entries"] >= 1
        # the same counters flow through the Telemetry.cache counter into
        # the metrics snapshot
        snap = w.telemetry.snapshot()["decision_cache"]
        assert snap.get("hits", 0) == dc["hits"]
        assert snap.get("misses", 0) == dc["misses"]
        # cache-hit rows are attributed on the serving-path counter too
        assert w.telemetry.paths.snapshot().get("cache-hit", 0) >= 1
    finally:
        w.stop()


def test_mask_namedtuple_survives():
    from collections import namedtuple

    Point = namedtuple("Point", "x y")
    masked = mask_secrets({"p": Point(1, 2), "token": "x"})
    assert masked["p"] == Point(1, 2)
    assert masked["token"] == "***"


def test_masking_filter_extra_payload():
    record = logging.LogRecord("t", logging.INFO, __file__, 1, "msg", (), None)
    record.ctx = {"token": "leak"}
    assert MaskingFilter().filter(record)
    assert record.ctx["token"] == "***"


def test_native_wire_path_records_metrics():
    import os

    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

    from .test_grpc_transport import SEED, wire_request

    w = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )
    server = GrpcServer(w, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        if not w.evaluator.native_active:
            import pytest

            pytest.skip("native encoder unavailable")
        client.is_allowed_batch(
            pb.BatchRequest(requests=[wire_request(), wire_request()])
        )
        snap = w.telemetry.snapshot()
        assert snap["batch_latency"]["count"] == 1
        assert snap["decisions"].get("PERMIT", 0) == 2
        assert snap["paths"].get("native-wire", 0) == 2
    finally:
        client.close()
        server.stop()
        w.stop()


def test_json_sink_ships_masked_structured_lines(tmp_path):
    """logging:json_sink appends one JSON object per record with extra
    fields included and secrets masked — the shape external shippers
    tail (the reference's production Elasticsearch-transport role)."""
    import json
    import logging

    from access_control_srv_tpu.srv.telemetry import make_logger

    sink = tmp_path / "acs.log.jsonl"
    logger = make_logger("test-json-sink", json_sink=str(sink))
    try:
        logger.info("policy loaded", extra={
            "policy_sets": 3,
            "subject": {"id": "u1", "token": "supersecret"},
        })
        logger.warning("auth failed", extra={"password": "hunter2"})
    finally:
        for h in list(logger.handlers):
            h.close()
            logger.removeHandler(h)
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert lines[0]["message"] == "policy loaded"
    assert lines[0]["policy_sets"] == 3
    assert lines[0]["subject"]["token"] == "***"
    assert lines[1]["level"] == "WARNING"
    assert lines[1]["password"] == "***"
    assert all("@timestamp" in ln for ln in lines)


def test_worker_config_wires_json_sink(tmp_path):
    import json
    import os

    from access_control_srv_tpu.srv import Worker

    seed = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "seed_data",
    )
    sink = tmp_path / "worker.jsonl"
    worker = Worker().start({
        "logging": {"json_sink": str(sink)},
        "policies": {"type": "database"},
        "seed_data": {
            "policy_sets": os.path.join(seed, "policy_sets.yaml"),
            "policies": os.path.join(seed, "policies.yaml"),
            "rules": os.path.join(seed, "rules.yaml"),
        },
    })
    try:
        worker.logger.info("sink probe", extra={"probe": True})
    finally:
        worker.stop()
        for h in list(worker.logger.handlers):
            h.close()
            worker.logger.removeHandler(h)
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert any(ln.get("probe") for ln in lines)
