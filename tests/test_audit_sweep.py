"""Permission-lattice audit-engine tests (ops/lattice.py +
srv/audit_sweep.py): the combining-fold differential against the scalar
isAllowed oracle (decisions AND deciding-rule provenance), snapshot
JSONL/bitmap round trips with audit-log masking, the one-rule-flip diff
oracle (the diff must name exactly the flipped rule's cells), the sweep
job lifecycle over the batcher's BULK class (pause/resume/cancel, honest
sheds with bounded retries), the decision-cache no-pollution regression,
reverse-kernel program identity across sweep chunks, the shadow twin
loop, and the config-gated worker/command integration."""

import copy
import json
import os
import threading
import time

import numpy as np
import pytest
import yaml

import bench_all
from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.models.model import (
    OperationStatus,
    PolicyRQ,
    PolicySetRQ,
    ReverseQuery,
    RuleRQ,
)
from access_control_srv_tpu.ops import reverse as reverse_mod
from access_control_srv_tpu.ops.lattice import (
    CODE_CONDITIONAL,
    CODE_DENY,
    CODE_NOT_APPLICABLE,
    CODE_PERMIT,
    LatticeSpec,
    SnapshotWriter,
    diff_snapshots,
    fold_reverse_query,
    load_bitmap,
    load_snapshot,
    pack_codes,
    unpack_codes,
)
from access_control_srv_tpu.srv import audit_sweep as audit_mod
from access_control_srv_tpu.srv.audit_sweep import AuditSweepManager
from access_control_srv_tpu.srv.config import Config
from access_control_srv_tpu.srv.decision_cache import DecisionCache
from access_control_srv_tpu.srv.evaluator import HybridEvaluator
from access_control_srv_tpu.srv.shadow import ShadowEvaluator
from access_control_srv_tpu.srv.telemetry import Telemetry

from .test_admission import StubEvaluator, controller, make_batcher

DO = bench_all.DO
PO = bench_all.PO

ALL_ACTIONS = ("read", "modify", "create", "delete")


def stress_engine(n_rules=48, flip_every=0):
    doc, _ = bench_all._stress_doc(n_rules, flip_every=flip_every)
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    return engine


def small_spec(n=8, actions=ALL_ACTIONS):
    return LatticeSpec.stress(n, n, actions=actions)


# ------------------------------------------------------------------- fold


class TestFold:
    def test_fold_matches_is_allowed_oracle_with_provenance(self):
        """fold(whatIsAllowed(cell)) must equal isAllowed(cell) on every
        lattice cell of a condition-free tree — decision AND deciding
        rule id (the engine's EffectEvaluation.source)."""
        engine = stress_engine(48)
        for index_req in small_spec(8).chunks(256):
            for _, req in index_req:
                rq = engine.what_is_allowed(copy.deepcopy(req))
                verdict = fold_reverse_query(rq)
                resp = engine.is_allowed(copy.deepcopy(req))
                assert verdict.decision == resp.decision
                if resp.decision in (Decision.PERMIT, Decision.DENY):
                    assert verdict.rule_id == resp._rule_id

    def _rq(self, algorithm, rules, set_algorithm=DO):
        policy = PolicyRQ(
            id="p0", combining_algorithm=algorithm, has_rules=True,
            rules=[
                RuleRQ(id=rid, effect=eff, condition=cond)
                for rid, eff, cond in rules
            ],
        )
        return ReverseQuery(policy_sets=[PolicySetRQ(
            id="s0", combining_algorithm=set_algorithm, policies=[policy],
        )])

    def test_deny_overrides_first_deny_wins(self):
        v = fold_reverse_query(self._rq(DO, [
            ("r0", "PERMIT", ""), ("r1", "DENY", ""), ("r2", "DENY", ""),
        ]))
        assert (v.decision, v.rule_id) == (Decision.DENY, "r1")

    def test_deny_overrides_no_deny_takes_last(self):
        v = fold_reverse_query(self._rq(DO, [
            ("r0", "PERMIT", ""), ("r1", "PERMIT", ""),
        ]))
        assert (v.decision, v.rule_id) == (Decision.PERMIT, "r1")

    def test_permit_overrides_first_permit_wins(self):
        v = fold_reverse_query(self._rq(PO, [
            ("r0", "DENY", ""), ("r1", "PERMIT", ""), ("r2", "PERMIT", ""),
        ]))
        assert (v.decision, v.rule_id) == (Decision.PERMIT, "r1")

    def test_first_applicable_takes_first(self):
        fa = ("urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
              "first-applicable")
        v = fold_reverse_query(self._rq(fa, [
            ("r0", "DENY", ""), ("r1", "PERMIT", ""),
        ]))
        assert (v.decision, v.rule_id) == (Decision.DENY, "r0")

    def test_last_set_with_effects_wins(self):
        """The engine's cross-set collection overwrites: the LAST policy
        set producing effects decides (core/engine.py isAllowed loop)."""
        rq_a = self._rq(PO, [("r0", "DENY", "")])
        rq_b = self._rq(PO, [("r1", "PERMIT", "")])
        rq = ReverseQuery(
            policy_sets=rq_a.policy_sets + rq_b.policy_sets
        )
        v = fold_reverse_query(rq)
        assert (v.decision, v.rule_id) == (Decision.PERMIT, "r1")

    def test_ruleless_policy_contributes_own_effect(self):
        policy = PolicyRQ(id="p0", effect="PERMIT", has_rules=False)
        rq = ReverseQuery(policy_sets=[PolicySetRQ(
            id="s0", combining_algorithm=DO, policies=[policy],
        )])
        v = fold_reverse_query(rq)
        assert (v.decision, v.rule_id) == (Decision.PERMIT, "p0")

    def test_policy_with_rules_defined_but_none_matched_is_inert(self):
        """has_rules=True with an empty matched-rule list must NOT fall
        back to the policy effect — mirrors engine.py:285 (the effect
        stands in only for genuinely rule-less policies)."""
        policy = PolicyRQ(id="p0", effect="PERMIT", has_rules=True)
        rq = ReverseQuery(policy_sets=[PolicySetRQ(
            id="s0", combining_algorithm=DO, policies=[policy],
        )])
        assert fold_reverse_query(rq).decision == Decision.INDETERMINATE

    def test_conditional_rule_flags_cell(self):
        """whatIsAllowed never evaluates conditions, so any cell whose
        winning tree contains one is an optimistic bound — flagged and
        coded CONDITIONAL in the bitmap, never presented as definitive."""
        v = fold_reverse_query(self._rq(DO, [
            ("r0", "PERMIT", "context.subject.id === 'u1'"),
        ]))
        assert v.decision == Decision.PERMIT
        assert v.conditional and v.code == CODE_CONDITIONAL

    def test_unknown_combining_algorithm_is_honest_indeterminate(self):
        v = fold_reverse_query(self._rq("urn:custom:nope", [
            ("r0", "PERMIT", ""),
        ]))
        assert v.decision == Decision.INDETERMINATE
        assert v.rule_id is None

    def test_shed_tree_carries_code(self):
        rq = ReverseQuery(operation_status=OperationStatus(
            code=429, message="overload"
        ))
        v = fold_reverse_query(rq)
        assert v.decision == Decision.INDETERMINATE
        assert v.shed_code == 429


# --------------------------------------------------------------- snapshot


class TestSnapshot:
    def test_roundtrip_jsonl_and_bitmap(self, tmp_path):
        engine = stress_engine(48)
        spec = small_spec(6)
        path = str(tmp_path / "snap.jsonl")
        writer = SnapshotWriter(path, spec, source="production",
                                policy_epoch=7)
        expected = {}
        for chunk in spec.chunks(50):
            for index, req in chunk:
                v = fold_reverse_query(engine.what_is_allowed(req))
                writer.write(index, v)
                expected[index] = v
        summary = writer.close()
        assert summary["cells"] == spec.n_cells

        header, cells, footer = load_snapshot(path)
        assert header["shape"] == list(spec.shape)
        assert header["policy_epoch"] == 7
        assert footer["cells"] == spec.n_cells
        decided = {
            i for i, v in expected.items()
            if v.decision in (Decision.PERMIT, Decision.DENY)
        }
        assert set(cells) == {spec.unravel(i) for i in decided}
        for index in decided:
            row = cells[spec.unravel(index)]
            assert row["d"] == expected[index].decision
            assert row["r"] == expected[index].rule_id

        codes = load_bitmap(path + ".bits.npy", spec.n_cells)
        for index, v in expected.items():
            assert codes[index] == v.code

    def test_bitmap_pack_unpack_identity(self):
        rng = np.random.default_rng(5)
        codes = rng.integers(0, 4, size=997).astype(np.uint8)
        assert (unpack_codes(pack_codes(codes), 997) == codes).all()
        packed = pack_codes(codes)
        assert packed.nbytes == (997 + 3) // 4

    def test_secret_named_axis_urns_are_masked(self, tmp_path):
        """The masking guarantee of the exported matrix: axis values
        whose attribute URN names a secret (the PR 6 audit-log rule) are
        ``***`` in the header, and cell lines reference axis indices
        only — a secret principal id can never leak into the artifact."""
        spec = LatticeSpec(
            subjects=(("sup3rsecret-token-1", "admin"),),
            resources=(("res0", "urn:restorecommerce:acs:model:a.A"),),
            actions=("urn:restorecommerce:acs:names:action:read",),
            subject_id_urn="urn:restorecommerce:acs:names:token",
        )
        path = str(tmp_path / "masked.jsonl")
        writer = SnapshotWriter(path, spec)
        writer.write(0, fold_reverse_query(ReverseQuery()))
        writer.close()
        text = open(path).read()
        assert "sup3rsecret-token-1" not in text
        header, _, _ = load_snapshot(path)
        assert header["axes"]["subjects"][0]["id"] == "***"
        # roles ride a non-secret URN and stay readable
        assert header["axes"]["subjects"][0]["role"] == "admin"

    def test_cell_lines_carry_indices_never_values(self, tmp_path):
        """Schema guarantee: every non-header line is either a cell row
        ``{c, d, r?, q?, s?}`` or the summary — no attribute values."""
        engine = stress_engine(48)
        spec = small_spec(4)
        path = str(tmp_path / "schema.jsonl")
        writer = SnapshotWriter(path, spec)
        for chunk in spec.chunks(64):
            for index, req in chunk:
                writer.write(
                    index, fold_reverse_query(engine.what_is_allowed(req))
                )
        writer.close()
        lines = [json.loads(l) for l in open(path) if l.strip()]
        assert lines[0]["kind"] == "acs-lattice-snapshot"
        assert lines[-1]["kind"] == "acs-lattice-summary"
        for row in lines[1:-1]:
            assert set(row) <= {"c", "d", "r", "q", "s"}
            assert all(isinstance(i, int) for i in row["c"])


# ------------------------------------------------------------------- diff


class TestDiff:
    def test_one_rule_flip_names_exactly_the_flipped_cells(self, tmp_path):
        """The acceptance oracle: sweeping a candidate with exactly one
        rule flipped (bench_all._stress_doc flip_every > rid range flips
        only r0) must diff exactly the cells where the scalar oracle's
        decisions differ, and every diff cell must name r0."""
        engine_a = stress_engine(48)
        engine_b = stress_engine(48, flip_every=10 ** 9)
        spec = small_spec(10)
        paths = {}
        for name, engine in (("a", engine_a), ("b", engine_b)):
            paths[name] = str(tmp_path / f"{name}.jsonl")
            writer = SnapshotWriter(paths[name], spec, source=name)
            for chunk in spec.chunks(128):
                for index, req in chunk:
                    writer.write(
                        index,
                        fold_reverse_query(engine.what_is_allowed(req)),
                    )
            writer.close()

        diff = diff_snapshots(paths["a"], paths["b"])
        expected = set()
        for chunk in spec.chunks(256):
            for index, req in chunk:
                da = engine_a.is_allowed(copy.deepcopy(req)).decision
                db = engine_b.is_allowed(copy.deepcopy(req)).decision
                if da != db:
                    expected.add(spec.unravel(index))
        assert expected, "the flip must affect at least one cell"
        assert {tuple(c["cell"]) for c in diff["cells"]} == expected
        assert diff["cells_changed"] == len(expected)
        assert diff["rules"] == ["r0"]
        for cell in diff["cells"]:
            assert "r0" in (cell["a"]["rule"], cell["b"]["rule"])

    def test_identical_snapshots_diff_empty(self, tmp_path):
        engine = stress_engine(48)
        spec = small_spec(4)
        paths = []
        for name in ("x", "y"):
            path = str(tmp_path / f"{name}.jsonl")
            writer = SnapshotWriter(path, spec)
            for chunk in spec.chunks(64):
                for index, req in chunk:
                    writer.write(
                        index,
                        fold_reverse_query(engine.what_is_allowed(req)),
                    )
            writer.close()
            paths.append(path)
        diff = diff_snapshots(*paths)
        assert diff["cells_changed"] == 0 and diff["cells"] == []

    def test_shape_mismatch_raises(self, tmp_path):
        for name, n in (("a", 2), ("b", 3)):
            writer = SnapshotWriter(
                str(tmp_path / f"{name}.jsonl"), small_spec(n)
            )
            writer.close()
        with pytest.raises(ValueError, match="shapes differ"):
            diff_snapshots(
                str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
            )

    def test_diff_limit_truncates_explicitly(self, tmp_path):
        cells_a = {(0, 0, i): {"c": [0, 0, i], "d": "PERMIT", "r": "ra"}
                   for i in range(8)}
        from access_control_srv_tpu.ops.lattice import diff_cells

        diff = diff_cells(cells_a, {}, limit=3)
        assert diff["cells_changed"] == 8
        assert len(diff["cells"]) == 3 and diff["truncated"] == 5


# ---------------------------------------------------------- sweep manager


class ShedOnceEvaluator(StubEvaluator):
    """First bulk batch sheds (an overloaded window), retries succeed."""

    def __init__(self):
        super().__init__()
        self.calls = 0

    def what_is_allowed_batch(self, requests):
        self.calls += 1
        code = 429 if self.calls == 1 else 200
        self.bulk_batches.append(len(requests))
        return [
            ReverseQuery(operation_status=OperationStatus(code=code))
            for _ in requests
        ]


class TestSweepManager:
    def test_bulk_sweep_completes_and_counts(self, tmp_path):
        telemetry = Telemetry()
        batcher = make_batcher(StubEvaluator(), controller())
        manager = AuditSweepManager(
            batcher.evaluator, batcher=batcher, telemetry=telemetry,
            out_dir=str(tmp_path), chunk_size=16,
        )
        try:
            job = manager.start_sweep(
                spec=small_spec(4, actions=("read",)), wait=True,
                wait_timeout=60,
            )
            assert job.state == "done"
            assert job.cells_done == 16
            assert os.path.exists(job.snapshot_path)
            assert os.path.exists(job.bitmap_path)
            events = telemetry.snapshot()["audit"]
            assert events["jobs_started"] == 1
            assert events["jobs_completed"] == 1
            assert events["cells"] == 16
        finally:
            manager.stop()
            batcher.stop()

    def test_pause_freezes_and_cancel_finishes_early(self, tmp_path):
        batcher = make_batcher(StubEvaluator(delay_s=0.01), controller())
        manager = AuditSweepManager(
            batcher.evaluator, batcher=batcher,
            out_dir=str(tmp_path), chunk_size=4,
        )
        try:
            job = manager.start_sweep(
                spec=LatticeSpec.stress(32, 32, actions=("read",))
            )
            deadline = time.monotonic() + 10
            while job.status()["cells_done"] == 0:
                assert time.monotonic() < deadline, "sweep never started"
                time.sleep(0.005)
            manager.pause(job.job_id)
            time.sleep(0.1)
            frozen = job.status()["cells_done"]
            time.sleep(0.15)
            assert job.status()["cells_done"] == frozen, (
                "a paused sweep kept dispatching bulk chunks"
            )
            manager.resume(job.job_id)
            deadline = time.monotonic() + 10
            while job.status()["cells_done"] <= frozen:
                assert time.monotonic() < deadline, "resume never moved"
                time.sleep(0.005)
            manager.cancel(job.job_id)
            assert job.wait(10)
            assert job.state == "cancelled"
            assert job.cells_done < job.spec.n_cells
            # the partial snapshot is still well-formed (header + footer)
            header, _, footer = load_snapshot(job.snapshot_path)
            assert footer is not None
        finally:
            manager.stop()
            batcher.stop()

    def test_shed_cells_retry_then_succeed(self, tmp_path):
        evaluator = ShedOnceEvaluator()
        batcher = make_batcher(evaluator, controller())
        manager = AuditSweepManager(
            evaluator, batcher=batcher,
            out_dir=str(tmp_path), chunk_size=8, max_retries=3,
        )
        try:
            job = manager.start_sweep(
                spec=LatticeSpec.stress(2, 4, actions=("read",)),
                wait=True, wait_timeout=60,
            )
            assert job.state == "done"
            assert job.retries >= 1
            assert job.summary["sheds"] == 0, (
                "retried cells must land as real verdicts, not sheds"
            )
        finally:
            manager.stop()
            batcher.stop()

    def test_exhausted_retries_land_as_honest_sheds(self, tmp_path):
        class AlwaysShed(StubEvaluator):
            def what_is_allowed_batch(self, requests):
                self.bulk_batches.append(len(requests))
                return [
                    ReverseQuery(operation_status=OperationStatus(code=429))
                    for _ in requests
                ]

        batcher = make_batcher(AlwaysShed(), controller())
        manager = AuditSweepManager(
            batcher.evaluator, batcher=batcher,
            out_dir=str(tmp_path), chunk_size=4, max_retries=1,
        )
        try:
            job = manager.start_sweep(
                spec=LatticeSpec.stress(2, 2, actions=("read",)),
                wait=True, wait_timeout=60,
            )
            assert job.state == "done"
            assert job.summary["sheds"] == 4
            _, cells, _ = load_snapshot(job.snapshot_path)
            assert all(row["s"] == 429 for row in cells.values())
            assert all(
                row["d"] == Decision.INDETERMINATE for row in cells.values()
            )
        finally:
            manager.stop()
            batcher.stop()

    def test_sweep_never_pollutes_decision_cache(self, tmp_path):
        """The satellite regression: submit_reverse bypasses the decision
        cache BY DESIGN (srv/batcher.py) — a full sweep must insert
        nothing into the interactive cache or its tenant namespaces."""
        engine = stress_engine(48)
        cache = DecisionCache(enabled=True)
        evaluator = HybridEvaluator(
            engine, backend="oracle", decision_cache=cache
        )
        batcher = make_batcher(evaluator, controller())
        manager = AuditSweepManager(
            evaluator, batcher=batcher,
            out_dir=str(tmp_path), chunk_size=16,
        )
        try:
            job = manager.start_sweep(
                spec=small_spec(4, actions=("read",)), wait=True,
                wait_timeout=120,
            )
            assert job.state == "done"
            stats = cache.stats()
            assert stats["stores"] == 0, "sweep traffic reached the cache"
            assert stats["entries"] == 0
            assert stats["hits"] == 0 and stats["misses"] == 0
        finally:
            manager.stop()
            batcher.stop()
            evaluator.shutdown()


# ----------------------------------------------------- program identity


class TestProgramIdentity:
    def test_sweep_reuses_reverse_kernel_programs(self, monkeypatch,
                                                  tmp_path):
        """Zero new XLA compiles across sweep chunks: after a warm
        sweep, a second identical sweep adds no jit-registry keys and
        keeps the SAME ReverseQueryKernel object (compiled program
        reuse, the tpu_compat_audit audit-sweep-program-identity row)."""
        monkeypatch.setattr(reverse_mod, "REVERSE_MIN_RULES", 0)
        engine = stress_engine(48)
        telemetry = Telemetry()
        evaluator = HybridEvaluator(
            engine, backend="kernel", telemetry=telemetry
        )
        manager = AuditSweepManager(
            evaluator, out_dir=str(tmp_path), chunk_size=32,
        )
        spec = small_spec(6)
        try:
            warm = manager.start_sweep(spec=spec, wait=True,
                                       wait_timeout=120)
            assert warm.state == "done"
            kernel = evaluator._rq_kernel
            assert kernel is not None, "sweep never engaged the kernel"
            keys_before = set(kernel._runs)
            version_before = kernel.compiled.version
            job = manager.start_sweep(spec=spec, wait=True,
                                      wait_timeout=120)
            assert job.state == "done"
            assert evaluator._rq_kernel is kernel
            assert set(kernel._runs) == keys_before, (
                "a sweep chunk traced a new reverse-kernel program"
            )
            assert kernel.compiled.version == version_before
            assert telemetry.paths.get("kernel-wia"), (
                "sweep cells must ride the device-assisted wia path"
            )
        finally:
            manager.stop()
            evaluator.shutdown()


# ------------------------------------------------------------- twin loop


class TestTwinLoop:
    def test_twin_report_names_flipped_rule_and_live_diffs(self, tmp_path):
        """The learned-policy loop: a mined candidate (here: one flipped
        rule) loads through ShadowEvaluator with zero new compiles, the
        twin sweep diffs the full lattice naming the flipped rule, and
        the same report carries the live-traffic diff counters."""
        doc_b, _ = bench_all._stress_doc(48, flip_every=10 ** 9)
        candidate = str(tmp_path / "candidate.yml")
        with open(candidate, "w") as fh:
            yaml.safe_dump(doc_b, fh)
        engine = stress_engine(48)
        production = HybridEvaluator(engine, backend="oracle")
        shadow = ShadowEvaluator(production, [candidate])

        class WorkerStub:
            pass

        worker = WorkerStub()
        worker.shadow = shadow
        manager = AuditSweepManager(
            production, worker=worker, out_dir=str(tmp_path), chunk_size=64,
        )
        try:
            report = manager.sweep_twin(
                spec=small_spec(8), wait_timeout=120
            )
            assert report["production"]["state"] == "done"
            assert report["candidate"]["state"] == "done"
            diff = report["lattice_diff"]
            assert diff["rules"] == ["r0"]
            assert diff["cells_changed"] >= 1
            assert report["live_traffic"]["enabled"] is True
            assert shadow.new_program_keys == []
        finally:
            manager.stop()
            shadow.stop()
            production.shutdown()

    def test_shadow_target_requires_loaded_candidate(self, tmp_path):
        manager = AuditSweepManager(
            StubEvaluator(), out_dir=str(tmp_path)
        )
        with pytest.raises(RuntimeError, match="shadow"):
            manager.start_sweep(target="shadow")
        manager.stop()


# ------------------------------------------------------- config / command


class TestConfigGating:
    def test_disabled_by_default_builds_nothing(self):
        cfg = Config({})
        assert cfg.get("audit:enabled") is False
        assert audit_mod.from_config(cfg, evaluator=StubEvaluator()) is None

    def test_enabled_builds_manager_from_block(self, tmp_path):
        cfg = Config({"audit": {
            "enabled": True,
            "out_dir": str(tmp_path),
            "chunk_size": 64,
            "max_retries": 1,
            "lattice": {"subjects": 4, "resources": 4,
                        "actions": ["read"]},
        }})
        manager = audit_mod.from_config(cfg, evaluator=StubEvaluator())
        assert isinstance(manager, AuditSweepManager)
        assert manager.chunk_size == 64
        assert manager.max_retries == 1
        job = manager.start_sweep(wait=True, wait_timeout=30)
        assert job.state == "done"
        assert job.spec.n_cells == 16
        manager.stop()


class TestWorkerIntegration:
    def test_worker_audit_command_end_to_end(self, tmp_path):
        """audit:enabled worker: the audit_sweep command starts, reports
        and diffs sweeps over the seed policies, health_check grows a
        compact audit block, and telemetry exports acs_audit_* counters."""
        from .test_srv import seed_cfg
        from access_control_srv_tpu.srv import Worker

        cfg = seed_cfg()
        cfg["audit"] = {
            "enabled": True,
            "out_dir": str(tmp_path),
            "chunk_size": 32,
            "lattice": {"subjects": 4, "resources": 4,
                        "actions": ["read"]},
        }
        worker = Worker().start(cfg)
        try:
            assert worker.audit is not None
            started = worker.command_interface.command(
                "audit_sweep", {"action": "start", "wait": True}
            )
            assert started["state"] == "done"
            assert started["cells_done"] == 16
            status = worker.command_interface.command(
                "audit_sweep", {"action": "status"}
            )
            assert status["running"] == 0
            health = worker.command_interface.command("health_check", {})
            assert health["audit"]["jobs"][0]["state"] == "done"
            assert "acs_audit_events_total" in worker.telemetry.prometheus()
            # a second sweep diffs clean against the first (same tree)
            second = worker.command_interface.command(
                "audit_sweep", {"action": "start", "wait": True}
            )
            diff = worker.command_interface.command(
                "audit_sweep",
                {"action": "diff", "a": started["job"], "b": second["job"]},
            )
            assert diff["cells_changed"] == 0
        finally:
            worker.stop()

    def test_worker_disabled_default_has_no_surface(self):
        from .test_srv import seed_cfg
        from access_control_srv_tpu.srv import Worker

        worker = Worker().start(seed_cfg())
        try:
            assert worker.audit is None
            out = worker.command_interface.command(
                "audit_sweep", {"action": "start"}
            )
            assert out == {"enabled": False}
            health = worker.command_interface.command("health_check", {})
            assert "audit" not in health
            snapshot = worker.telemetry.snapshot()
            assert "audit" not in snapshot
        finally:
            worker.stop()
