"""Candidate-filtered oracle walk: bit-identical to the full walk.

The index (core/candidate_index.py) lets fallback-served requests skip
rules that provably cannot target-match — the same normative reasoning
as the kernel's candidate pre-filter.  These differentials drive
randomized trees (exact entities, regex entities incl. literal-value
substring aliasing, operations, property-only targets, no-target rules,
mixed cacheable flags) through both walks and require identical
responses, including evaluation_cacheable (the reference clears the
policy-level cacheable flag for every non-cacheable rule, matched or
not — the skip happens after that aggregation).
"""

import numpy as np
import pytest

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.candidate_index import CandidateIndex
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.models import Attribute, Request, Target, Urns

URNS = Urns()
DO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides"
PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
FA = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable"


def build_engine(seed):
    rng = np.random.default_rng(seed)
    ents = [f"urn:restorecommerce:acs:model:v{k}.V{k}" for k in range(9)]
    policies = []
    rid = 0
    for p in range(30):
        rules = []
        for q in range(int(rng.integers(1, 25))):
            kind = int(rng.integers(10))
            resources = []
            if kind < 6:  # exact entity
                resources = [{"id": URNS["entity"], "value": ents[rid % 9]}]
            elif kind == 6:  # regex-ish entity (literal substring quirk)
                resources = [{"id": URNS["entity"],
                              "value": "urn:restorecommerce:acs:model:V[0-4]"}]
            elif kind == 7:  # operation target
                resources = [{"id": URNS["operation"], "value": f"op-{rid % 5}"}]
            elif kind == 8:  # property-only resources
                resources = [{"id": URNS["property"],
                              "value": ents[rid % 9] + "#f"}]
            # kind == 9: no resources at all
            target = {
                "resources": resources,
                "actions": (
                    [{"id": URNS["actionID"],
                      "value": [URNS["read"], URNS["modify"]][rid % 2]}]
                    if rng.integers(3) else []
                ),
            }
            if rng.integers(2):
                target["subjects"] = [
                    {"id": URNS["role"], "value": f"role-{rid % 6}"}
                ]
            rules.append({
                "id": f"r{rid}",
                "target": target if (resources or target["actions"]
                                     or target.get("subjects")) else None,
                "effect": ["PERMIT", "DENY"][int(rng.integers(2))],
                "evaluation_cacheable": bool(rng.integers(2)),
            })
            rid += 1
        policies.append({
            "id": f"p{p}",
            "combining_algorithm": [DO, PO, FA][p % 3],
            "rules": rules,
        })
    doc = {"policy_sets": [
        {"id": "s", "combining_algorithm": DO, "policies": policies}
    ]}
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    return engine


def make_request(rng, ents):
    role = f"role-{int(rng.integers(8))}"
    resources = []
    if rng.integers(4):
        resources.append(Attribute(id=URNS["entity"],
                                   value=ents[int(rng.integers(9))]))
        resources.append(Attribute(id=URNS["resourceID"], value="res-1"))
    if not rng.integers(3):
        resources.append(Attribute(id=URNS["operation"],
                                   value=f"op-{int(rng.integers(6))}"))
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value=role),
                      Attribute(id=URNS["subjectID"], value="u1")],
            resources=resources,
            actions=[Attribute(
                id=URNS["actionID"],
                value=[URNS["read"], URNS["modify"],
                       URNS["create"]][int(rng.integers(3))])],
        ),
        context={"resources": [], "subject": {
            "id": "u1",
            "role_associations": [{"role": role, "attributes": []}],
            "hierarchical_scopes": [],
        }},
    )


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_filtered_walk_bit_identical(seed):
    engine = build_engine(seed)
    index = CandidateIndex(engine.policy_sets, engine.urns)
    ents = [f"urn:restorecommerce:acs:model:v{k}.V{k}" for k in range(9)]
    rng = np.random.default_rng(seed + 100)
    skipped_total = 0
    for _ in range(200):
        request = make_request(rng, ents)
        cands = index.candidates(request, engine.urns)
        full = engine.is_allowed(request)
        filtered = engine.is_allowed(request, candidate_rules=cands)
        assert filtered.decision == full.decision
        assert filtered.evaluation_cacheable == full.evaluation_cacheable
        assert filtered.operation_status.code == full.operation_status.code
        skipped_total += index.n_rules - len(cands)
    assert skipped_total > 0, "index never skipped anything"


def test_evaluator_uses_index_and_survives_hot_mutation():
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    engine = build_engine(7)
    evaluator = HybridEvaluator(engine)
    assert evaluator._cand is not None
    ents = [f"urn:restorecommerce:acs:model:v{k}.V{k}" for k in range(9)]
    rng = np.random.default_rng(11)
    request = make_request(rng, ents)
    expected = engine.is_allowed(request)
    assert evaluator.is_allowed(request).decision == expected.decision

    # a tree swap invalidates the index instantly (identity guard) and
    # refresh() rebuilds it
    import copy

    new_tree = copy.deepcopy(engine.policy_sets)
    engine.replace_policy_sets(new_tree)
    assert evaluator._cand[0] is not engine.policy_sets
    r1 = evaluator.is_allowed(request)  # unfiltered during the window
    assert r1.decision == expected.decision
    evaluator.refresh(wait=True)
    assert evaluator._cand[0] is engine.policy_sets
    assert evaluator.is_allowed(request).decision == expected.decision


def test_oracle_backend_builds_the_index():
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    engine = build_engine(9)
    evaluator = HybridEvaluator(engine, backend="oracle")
    assert evaluator._cand is not None
    ents = [f"urn:restorecommerce:acs:model:v{k}.V{k}" for k in range(9)]
    rng = np.random.default_rng(13)
    for _ in range(20):
        request = make_request(rng, ents)
        assert (evaluator.is_allowed(request).decision
                == engine.is_allowed(request).decision)


def test_small_trees_skip_the_index():
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator
    from access_control_srv_tpu.core import populate
    import os

    engine = AccessController()
    populate(engine, os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "basic_policies.yml",
    ))
    evaluator = HybridEvaluator(engine)
    assert evaluator._cand is None
