"""Differential tests: the batched JAX kernel must produce decisions
identical to the scalar oracle for every kernel-eligible request.

This is the framework's substitute for the reference's race-detection /
sanitizer class (SURVEY.md section 5): the oracle is the normative
semantics; the kernel is property-tested against it on fixture-driven
grids and randomized policies/requests."""

import itertools
import random

import numpy as np
import pytest

from access_control_srv_tpu.core import AccessController, populate
from access_control_srv_tpu.models import Attribute, Request, Target
from access_control_srv_tpu.ops import (
    DecisionKernel,
    compile_policies,
    encode_requests,
)

from .utils import URNS, build_request, fixture, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
ADDR = "urn:restorecommerce:acs:model:address.Address"
LOC = "urn:restorecommerce:acs:model:location.Location"
WIDGET = "urn:restorecommerce:acs:model:widget.Widget"
BUCKET = "urn:restorecommerce:acs:model:bucket.Bucket"

DEC_CODE = {"INDETERMINATE": 0, "PERMIT": 1, "DENY": 2}

SUBJECTS = ["ada", "ben", "gil", "dee", "eva", "kai", "zoe", "Alice"]
ROLES = ["member", "manager", "guest", "Admin", "SimpleUser", "supervisor"]
ENTITIES = [ORG, USER, ADDR, LOC, WIDGET, BUCKET]
ACTIONS = [URNS["read"], URNS["modify"], URNS["create"], URNS["delete"],
           URNS["execute"]]
PROPS = [ORG + "#name", ORG + "#secret_field", USER + "#name",
         USER + "#password", ADDR + "#street", LOC + "#address",
         LOC + "#id", LOC + "#description", ORG + "#id", ORG + "#description"]
OWNERS = ["Org1", "Org2", "Org3", "Org4", "SuperOrg1", "otherOrg"]


def run_differential(engine: AccessController, requests: list[Request]):
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported, compiled.unsupported_reason
    kernel = DecisionKernel(compiled)
    batch = encode_requests(requests, compiled)
    decision, cacheable, status = kernel.evaluate(batch)

    n_eligible = 0
    for b, request in enumerate(requests):
        expected = engine.is_allowed(request)
        if not batch.eligible[b]:
            continue
        n_eligible += 1
        assert decision[b] == DEC_CODE[expected.decision], (
            f"request {b}: kernel={decision[b]} oracle={expected.decision} "
        )
        exp_cach = expected.evaluation_cacheable
        exp_code = -1 if exp_cach is None else int(bool(exp_cach))
        assert cacheable[b] == exp_code, (
            f"request {b}: cacheable kernel={cacheable[b]} oracle={exp_cach}"
        )
        assert status[b] == expected.operation_status.code, (
            f"request {b}: status kernel={status[b]} "
            f"oracle={expected.operation_status.code}"
        )
    return n_eligible


def grid_requests(n=None, seed=7):
    """A randomized sweep over the request option space."""
    rng = random.Random(seed)
    out = []
    for _ in range(n or 160):
        action = rng.choice(ACTIONS)
        multi = rng.random() < 0.3 and action != URNS["execute"]
        if action == URNS["execute"]:
            rtype = rng.choice(["mutation.runPipeline", "mutation.other"])
            rid = rtype
        elif multi:
            rtype = rng.sample(ENTITIES, 2)
            rid = [f"id-{i}" for i in range(2)]
        else:
            rtype = rng.choice(ENTITIES)
            rid = "id-0"
        prop = None
        if rng.random() < 0.5 and action != URNS["execute"]:
            prop = rng.sample(PROPS, rng.randint(1, 2))
        owner = None
        owner_ent = None
        if rng.random() < 0.7:
            owner_ent = ORG
            owner = (
                [rng.choice(OWNERS) for _ in range(2)]
                if multi
                else rng.choice(OWNERS)
            )
        subject = rng.choice(SUBJECTS)
        acl_kwargs = {}
        roll = rng.random()
        if roll < 0.2:
            acl_kwargs = dict(
                acl_indicatory_entity=rng.choice([ORG, USER]),
                acl_instances=rng.sample(OWNERS + SUBJECTS, rng.randint(1, 3)),
            )
        elif roll < 0.35:
            acl_kwargs = dict(
                multiple_acl_indicatory_entity=[ORG, USER],
                org_instances=rng.sample(OWNERS, rng.randint(1, 3)),
                subject_instances=rng.sample([subject] + SUBJECTS, 2),
            )
        out.append(
            build_request(
                subject_id=subject,
                subject_role=rng.choice(ROLES),
                role_scoping_entity=ORG,
                role_scoping_instance=rng.choice(OWNERS),
                resource_type=rtype,
                resource_id=rid,
                resource_property=prop,
                action_type=action,
                owner_indicatory_entity=owner_ent,
                owner_instance=owner,
                **acl_kwargs,
            )
        )
    return out


@pytest.mark.parametrize(
    "fixture_name",
    [
        "basic_policies.yml",
        "policy_targets.yml",
        "policy_set_targets.yml",
        "role_scopes.yml",
        "hr_disabled.yml",
        "conditions.yml",
        "acl_policies.yml",
        "props_single.yml",
        "props_rules_noprop.yml",
        "props_multi_rules.yml",
        "props_multi_rules_entities.yml",
        "ops_multi.yml",
    ],
)
def test_fixture_differential(fixture_name):
    engine = make_engine(fixture_name)
    n = run_differential(engine, grid_requests())
    assert n > 100  # the sweep must actually exercise the kernel


def test_multi_fixture_tree():
    """All fixtures loaded into one engine: multiple policy sets,
    last-set-wins interactions."""
    engine = make_engine()
    for name in ["basic_policies.yml", "policy_targets.yml", "role_scopes.yml"]:
        populate(engine, fixture(name))
    n = run_differential(engine, grid_requests(seed=11))
    assert n > 100


def _random_policy_tree(rng: random.Random):
    """Generate a random policy tree within the kernel-supported subset."""
    cas = [
        "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides",
        "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides",
        "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable",
    ]

    def maybe_target(allow_scoping=True):
        t = {}
        if rng.random() < 0.6:
            subs = []
            if rng.random() < 0.5:
                subs.append({"id": URNS["subjectID"], "value": rng.choice(SUBJECTS)})
            else:
                subs.append({"id": URNS["role"], "value": rng.choice(ROLES)})
                if allow_scoping and rng.random() < 0.6:
                    subs.append({"id": URNS["roleScopingEntity"], "value": ORG})
                    if rng.random() < 0.3:
                        subs.append(
                            {"id": URNS["hierarchicalRoleScoping"], "value": "false"}
                        )
            t["subjects"] = subs
        if rng.random() < 0.7:
            res = []
            if rng.random() < 0.85:
                res.append({"id": URNS["entity"], "value": rng.choice(ENTITIES)})
                for p in rng.sample(PROPS, rng.randint(0, 2)):
                    res.append({"id": URNS["property"], "value": p})
            else:
                res.append(
                    {"id": URNS["operation"], "value": "mutation.runPipeline"}
                )
            t["resources"] = res
        if rng.random() < 0.6:
            t["actions"] = [
                {"id": URNS["actionID"], "value": rng.choice(ACTIONS)}
            ]
        return t or None

    doc = {"policy_sets": []}
    for s in range(rng.randint(1, 3)):
        ps = {
            "id": f"ps{s}",
            "combining_algorithm": rng.choice(cas),
            "policies": [],
        }
        if rng.random() < 0.3:
            tgt = maybe_target(allow_scoping=False)
            if tgt:
                ps["target"] = tgt
        for p in range(rng.randint(1, 3)):
            pol = {
                "id": f"ps{s}p{p}",
                "combining_algorithm": rng.choice(cas),
            }
            if rng.random() < 0.4:
                tgt = maybe_target()
                if tgt:
                    pol["target"] = tgt
            if rng.random() < 0.25:
                pol["effect"] = rng.choice(["PERMIT", "DENY"])
            else:
                pol["rules"] = []
                for q in range(rng.randint(1, 4)):
                    rule = {
                        "id": f"ps{s}p{p}r{q}",
                        "effect": rng.choice(["PERMIT", "DENY"]),
                    }
                    if rng.random() < 0.3:
                        rule["evaluation_cacheable"] = True
                    tgt = maybe_target()
                    if tgt:
                        rule["target"] = tgt
                    pol["rules"].append(rule)
            ps["policies"].append(pol)
        doc["policy_sets"].append(ps)
    return doc


def test_acl_failure_paths_differential():
    """Requests with no resourceID/operation attributes exercise
    verify_acl's pre-ACL failure paths (empty role associations -> False,
    non-CRUD action -> False); the kernel must agree with the oracle."""
    engine = make_engine("policy_targets.yml")
    requests = []
    for role_assocs in ([], [{"role": "member", "attributes": []}]):
        for action in [URNS["read"], URNS["modify"], "custom:action", None]:
            req = Request(
                target=Target(
                    subjects=[
                        Attribute(id=URNS["role"], value="member"),
                        Attribute(id=URNS["subjectID"], value="ada"),
                    ],
                    # entity attribute only: no resourceID
                    resources=[Attribute(id=URNS["entity"], value=ORG)],
                    actions=(
                        [Attribute(id=URNS["actionID"], value=action)]
                        if action
                        else []
                    ),
                ),
                context={
                    "resources": [],
                    "subject": {
                        "id": "ada",
                        "role_associations": role_assocs,
                        "hierarchical_scopes": [],
                    },
                },
            )
            requests.append(req)
    n = run_differential(engine, requests)
    assert n == len(requests)  # all must stay kernel-eligible


def test_missing_hierarchical_scopes_falls_back():
    """hierarchical_scopes missing + role associations present makes the
    oracle raise; such requests must not stay kernel-eligible."""
    engine = make_engine("policy_targets.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    req = Request(
        target=Target(
            subjects=[
                Attribute(id=URNS["role"], value="member"),
                Attribute(id=URNS["subjectID"], value="ada"),
            ],
            resources=[Attribute(id=URNS["entity"], value=ORG)],
            actions=[Attribute(id=URNS["actionID"], value=URNS["read"])],
        ),
        context={
            "resources": [],
            "subject": {
                "id": "ada",
                "role_associations": [{"role": "member", "attributes": []}],
            },
        },
    )
    batch = encode_requests([req], compiled)
    assert not batch.eligible[0]


def test_randomized_differential():
    from access_control_srv_tpu.core.loader import load_policy_sets

    rng = random.Random(1234)
    total_eligible = 0
    for round_ in range(12):
        doc = _random_policy_tree(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        total_eligible += run_differential(
            engine, grid_requests(n=60, seed=1000 + round_)
        )
    assert total_eligible > 300


def test_multi_entity_property_relevance_regression():
    """Round-2 regression (VERDICT r2 weak #1): r_prop_tail was interned from
    the last-dot segment ("Organization") while t_ent_tails used the
    after-last-colon segment ("organization.Organization"), so the kernel
    never saw a request property as relevant to a matched entity and let
    PERMIT rules with unmatched properties apply (kernel PERMIT vs oracle
    INDETERMINATE on multi-entity requests; reference substring check:
    accessController.ts:509-525)."""
    from access_control_srv_tpu.core.loader import load_policy_sets

    doc = {
        "policy_sets": [{
            "id": "ps0",
            "combining_algorithm":
                "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
                "first-applicable",
            "policies": [{
                "id": "ps0p0",
                "combining_algorithm":
                    "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
                    "first-applicable",
                "target": {
                    "resources": [
                        {"id": URNS["entity"], "value": WIDGET},
                        {"id": URNS["property"], "value": ORG + "#description"},
                        {"id": URNS["property"], "value": ORG + "#id"},
                    ],
                    "actions": [
                        {"id": URNS["actionID"], "value": URNS["delete"]},
                    ],
                },
                "rules": [{
                    "id": "ps0p0r0",
                    "effect": "PERMIT",
                    "target": {
                        "subjects": [
                            {"id": URNS["subjectID"], "value": "gil"},
                        ],
                        "resources": [
                            {"id": URNS["entity"], "value": ORG},
                            {"id": URNS["property"], "value": ORG + "#id"},
                            {"id": URNS["property"], "value": USER + "#name"},
                        ],
                    },
                }],
            }],
        }],
    }
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)

    def req(prop):
        return Request(
            target=Target(
                subjects=[
                    Attribute(id=URNS["role"], value="member"),
                    Attribute(id=URNS["subjectID"], value="gil"),
                ],
                resources=[
                    Attribute(id=URNS["entity"], value=WIDGET),
                    Attribute(id=URNS["resourceID"], value="id-0"),
                    Attribute(id=URNS["property"], value=prop),
                    Attribute(id=URNS["entity"], value=ORG),
                    Attribute(id=URNS["resourceID"], value="id-1"),
                    Attribute(id=URNS["property"], value=prop),
                ],
                actions=[Attribute(id=URNS["actionID"], value=URNS["delete"])],
            ),
            context={
                "resources": [
                    {"id": "id-0", "meta": {"owners": []}},
                    {"id": "id-1", "meta": {"owners": []}},
                ],
                "subject": {"id": "gil", "role_associations": [],
                            "hierarchical_scopes": []},
            },
        )

    # Org#description is a property OF the matched entity but not granted by
    # the rule: the PERMIT rule must not apply (oracle: INDETERMINATE)
    bad = req(ORG + "#description")
    assert engine.is_allowed(bad).decision == "INDETERMINATE"
    # positive control: the granted property keeps the rule applicable
    good = req(ORG + "#id")
    assert engine.is_allowed(good).decision == "PERMIT"
    n = run_differential(engine, [bad, good])
    assert n == 2


def _scoped_role_tree(n_roles: int, hr_disable_every: int = 3):
    """Synthetic tree with ``n_roles`` distinct role-scoped rules: the
    stage-B (role, scoping) vocab then has ~n_roles+1 entries, so a
    parametrized sweep straddles the owner-bitplane word-packing
    boundaries (ops/encode.owner_bit_layout packs ``32 // (2*(NRU+NOP))``
    entries per int32 — 5/word at the floor caps, 8/word for op-free
    layouts).  Every ``hr_disable_every``-th rule carries the HR-disable
    attribute so the hr_check=False bit plane (B bits) is exercised too."""
    ca = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
    rules = []
    for i in range(n_roles):
        subjects = [
            {"id": URNS["role"], "value": f"obrole-{i}"},
            {"id": URNS["roleScopingEntity"], "value": ORG},
        ]
        if hr_disable_every and i % hr_disable_every == 2:
            subjects.append(
                {"id": URNS["hierarchicalRoleScoping"], "value": "false"}
            )
        rules.append({
            "id": f"obr{i}",
            "effect": "PERMIT" if i % 3 else "DENY",
            "target": {
                "subjects": subjects,
                "resources": [
                    {"id": URNS["entity"],
                     "value": ENTITIES[i % len(ENTITIES)]}
                ],
                "actions": [
                    {"id": URNS["actionID"],
                     "value": ACTIONS[i % 2]}
                ],
            },
        })
    return {"policy_sets": [{
        "id": "ob", "combining_algorithm": ca,
        "policies": [{"id": "obp", "combining_algorithm": ca,
                      "rules": rules}],
    }]}


def _owner_bit_requests(rng: random.Random, n: int):
    """Owner-check edge cases: in/out-of-scope owners, EMPTY owner sets
    (context resource present, meta.owners == []), multi-entity rows whose
    instances span two runs (exercises the NRU>1 bit groups), and deep HR
    closures."""
    out = []
    for i in range(n):
        multi = rng.random() < 0.3
        rtype = rng.sample(ENTITIES, 2) if multi else rng.choice(ENTITIES)
        rid = [f"id-{k}" for k in range(2)] if multi else "id-0"
        deep = rng.random() < 0.3
        if deep:
            depth = rng.randint(3, 6)

            def node(d, j=0):
                o = {"id": f"deep-{d}-{j}"}
                if d < depth:
                    o["children"] = [node(d + 1, k) for k in range(2)]
                return o

            scopes = [dict(node(0), role=f"obrole-{i % 19}")]
            owner = f"deep-{rng.randint(0, depth)}-0"
        else:
            scopes = None
            owner = rng.choice(OWNERS)
        empty_owners = rng.random() < 0.25
        kwargs = dict(
            subject_id=rng.choice(SUBJECTS),
            subject_role=f"obrole-{i % 19}",
            role_scoping_entity=ORG,
            role_scoping_instance=(
                scopes[0]["id"] if deep else rng.choice(OWNERS)
            ),
            resource_type=rtype,
            resource_id=rid,
            action_type=rng.choice(ACTIONS[:2]),
            hierarchical_scopes=scopes,
        )
        if not empty_owners:
            kwargs["owner_indicatory_entity"] = ORG
            kwargs["owner_instance"] = (
                [owner, rng.choice(OWNERS)] if multi else owner
            )
        out.append(build_request(**kwargs))
    return out


@pytest.mark.parametrize("n_roles", [3, 4, 5, 7, 8, 9, 15, 16, 17])
def test_owner_bitplane_vocab_boundaries(n_roles):
    """Role-scope vocab sizes straddling the owner-bitplane packing
    boundaries: dense kernel, prefiltered signature kernel and the scalar
    oracle must stay bit-identical for owner-bearing, empty-owner-set,
    HR-disabled and deep-closure rows at every vocab width."""
    from access_control_srv_tpu.core.loader import load_policy_sets
    from access_control_srv_tpu.ops import PrefilteredKernel

    from .test_prefilter import force_active

    engine = AccessController()
    for ps in load_policy_sets(_scoped_role_tree(n_roles)):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    # the vocab carries one entry per distinct scoped role plus the
    # ABSENT pair from unscoped target rows
    rv = compiled.arrays["hrv_role"].shape[0]
    assert rv >= n_roles

    rng = random.Random(4000 + n_roles)
    requests = _owner_bit_requests(rng, 48)
    n = run_differential(engine, requests)
    assert n > 30  # owner-bearing rows must stay kernel-eligible

    batch = encode_requests(requests, compiled)
    assert batch.arrays["r_own_bits"].shape[1] >= 1
    dense = DecisionKernel(compiled)
    dd, dc, ds = dense.evaluate(batch)
    pre = force_active(PrefilteredKernel(compiled))
    pd_, pc, ps_ = pre.evaluate(batch)
    assert np.array_equal(dd, pd_), f"n_roles={n_roles}: prefilter != dense"
    assert np.array_equal(dc, pc)
    assert np.array_equal(ds, ps_)
    assert pre._bits, "HR signature path must engage"


def test_owner_bits_multi_run_grouping():
    """Two entity runs with owner-bearing instances in DIFFERENT runs and
    divergent collect outcomes per target row: the per-run bit groups
    (r_own_runs) must not fold across runs — a regression guard for the
    host packer's group mapping."""
    engine = make_engine("role_scopes.yml")
    rng = random.Random(77)
    requests = []
    for i in range(24):
        requests.append(build_request(
            subject_id="ada",
            subject_role=["member", "manager"][i % 2],
            role_scoping_entity=ORG,
            role_scoping_instance=rng.choice(OWNERS),
            resource_type=[rng.choice(ENTITIES), rng.choice(ENTITIES)],
            resource_id=["id-0", "id-1"],
            action_type=ACTIONS[i % 2],
            owner_indicatory_entity=ORG,
            owner_instance=[rng.choice(OWNERS), rng.choice(OWNERS)],
        ))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    batch = encode_requests(requests, compiled)
    # the batch must actually exercise multi-run bit groups
    assert batch.arrays["r_own_runs"].shape[1] >= 2
    n = run_differential(engine, requests)
    assert n > 12


def test_acl_absent_values_fall_back():
    """ADVICE r2 (high): an ACL entry whose aclIndicatoryEntity or
    aclInstance value is None interns to ABSENT; the kernel's validity
    masks would silently drop the entity/instance and pass verifyACL where
    the reference fails closed.  Such rows must be marked ineligible
    (oracle fallback), not evaluated on device."""
    engine = make_engine("acl_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)

    def mk(acls):
        return Request(
            target=Target(
                subjects=[
                    Attribute(id=URNS["role"], value="member"),
                    Attribute(id=URNS["subjectID"], value="ada"),
                ],
                resources=[
                    Attribute(id=URNS["entity"], value=ORG),
                    Attribute(id=URNS["resourceID"], value="res-1"),
                ],
                actions=[Attribute(id=URNS["actionID"], value=URNS["create"])],
            ),
            context={
                "resources": [{"id": "res-1", "meta": {"owners": [],
                                                       "acls": acls}}],
                "subject": {
                    "id": "ada",
                    "role_associations": [
                        {"role": "member", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                },
            },
        )

    none_entity = mk([{
        "id": URNS["aclIndicatoryEntity"], "value": None,
        "attributes": [{"id": URNS["aclInstance"], "value": "ada"}],
    }])
    none_instance = mk([{
        "id": URNS["aclIndicatoryEntity"], "value": USER,
        "attributes": [{"id": URNS["aclInstance"], "value": None}],
    }])
    control = mk([{
        "id": URNS["aclIndicatoryEntity"], "value": USER,
        "attributes": [{"id": URNS["aclInstance"], "value": "ada"}],
    }])
    batch = encode_requests([none_entity, none_instance, control], compiled)
    assert not batch.eligible[0]  # ABSENT entity value: oracle fallback
    assert not batch.eligible[1]  # ABSENT instance value: oracle fallback
    assert batch.eligible[2]
    # the oracle itself must not crash on the degenerate shapes
    for req in (none_entity, none_instance):
        engine.is_allowed(req)
    run_differential(engine, [none_entity, none_instance, control])
