"""PrefilteredKernel differential tests: candidate-compacted evaluation
must be bit-identical to the dense kernel (and hence the oracle) — the
pre-filter drops only rules that provably cannot match.

This is the rule-count scaling path (BASELINE config 5: large rule trees);
correctness here is what allows the stress bench to run compacted."""

import random

import numpy as np
import pytest

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.models import Attribute, Request, Target, Urns
from access_control_srv_tpu.ops import (
    DecisionKernel,
    PrefilteredKernel,
    compile_policies,
    encode_requests,
)
from access_control_srv_tpu.ops import prefilter as PF

from .test_kernel_differential import DEC_CODE, grid_requests
from .utils import URNS, make_engine

PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
DO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides"
FA = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable"


def force_active(kern: PrefilteredKernel) -> PrefilteredKernel:
    """Fixture trees sit under MIN_RULES; exercise the machinery anyway."""
    if not kern.active:
        kern.active = True
        kern._dense = None
    return kern


@pytest.mark.parametrize(
    "fixture_name",
    [
        "basic_policies.yml",
        "policy_targets.yml",
        "policy_set_targets.yml",
        "role_scopes.yml",
        "conditions.yml",
        "acl_policies.yml",
        "props_multi_rules_entities.yml",
        "ops_multi.yml",
    ],
)
def test_prefilter_matches_dense(fixture_name):
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    dense = DecisionKernel(compiled)
    pre = force_active(PrefilteredKernel(compiled))

    batch = encode_requests(grid_requests(n=120, seed=53), compiled)
    dd, dc, ds = dense.evaluate(batch)
    pd_, pc, ps = pre.evaluate(batch)
    el = batch.eligible
    assert np.array_equal(dd[el], pd_[el])
    assert np.array_equal(dc[el], pc[el])
    assert np.array_equal(ds[el], ps[el])


def _stress_doc(n_policies=6, per_policy=120, n_entities=16):
    urns = Urns()
    entities = [
        f"urn:restorecommerce:acs:model:stress{k}.Stress{k}"
        for k in range(n_entities)
    ]
    actions = [urns["read"], urns["modify"], urns["create"], urns["delete"]]
    policies = []
    rid = 0
    for p in range(n_policies):
        rules = []
        for q in range(per_policy):
            rules.append({
                "id": f"r{rid}",
                "target": {
                    "subjects": [
                        {"id": urns["role"], "value": f"role-{rid % 23}"}
                    ],
                    "resources": [
                        {"id": urns["entity"],
                         "value": entities[(p * 31 + q) % n_entities]}
                    ],
                    "actions": [
                        {"id": urns["actionID"],
                         "value": actions[rid % len(actions)]}
                    ],
                },
                "effect": "PERMIT" if rid % 3 else "DENY",
            })
            rid += 1
        policies.append(
            {"id": f"p{p}", "combining_algorithm": PO, "rules": rules}
        )
    return {"policy_sets": [
        {"id": "stress", "combining_algorithm": DO, "policies": policies}
    ]}, entities, actions


def test_prefilter_stress_differential():
    """Large synthetic tree (~720 rules, above MIN_RULES): prefiltered
    decisions equal dense kernel AND the scalar oracle."""
    urns = Urns()
    doc, entities, actions = _stress_doc()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    dense = DecisionKernel(compiled)
    pre = PrefilteredKernel(compiled)
    assert pre.active  # above MIN_RULES

    rng = random.Random(5)
    requests = []
    for i in range(200):
        ent = rng.choice(entities)
        requests.append(Request(
            target=Target(
                subjects=[
                    Attribute(id=urns["role"], value=f"role-{i % 29}"),
                    Attribute(id=urns["subjectID"], value=f"u{i}"),
                ],
                resources=[
                    Attribute(id=urns["entity"], value=ent),
                    Attribute(id=urns["resourceID"], value=f"id-{i}"),
                ],
                actions=[Attribute(id=urns["actionID"],
                                   value=rng.choice(actions))],
            ),
            context={
                "resources": [],
                "subject": {
                    "id": f"u{i}",
                    "role_associations": [
                        {"role": f"role-{i % 29}", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                },
            },
        ))
    batch = encode_requests(requests, compiled)
    assert batch.eligible.all()
    dd, dc, ds = dense.evaluate(batch)
    pd_, pc, ps = pre.evaluate(batch)
    assert np.array_equal(dd, pd_)
    assert np.array_equal(dc, pc)
    assert np.array_equal(ds, ps)
    for b in (0, 7, 63, 199):  # spot-check the oracle on a few rows
        assert pd_[b] == DEC_CODE[engine.is_allowed(requests[b]).decision]
    # compaction really happened: per-entity subtrees are much smaller
    sub = next(iter(pre._subs.values()))
    assert sub.KR < compiled.KR / 2
    assert sub.T < compiled.T / 2


def test_prefilter_cache_reuse():
    doc, entities, actions = _stress_doc(n_policies=5, per_policy=110)
    urns = Urns()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    pre = PrefilteredKernel(compiled)

    def mk(ent):
        return Request(
            target=Target(
                subjects=[Attribute(id=urns["subjectID"], value="u")],
                resources=[Attribute(id=urns["entity"], value=ent)],
                actions=[Attribute(id=urns["actionID"], value=urns["read"])],
            ),
            context={"resources": [], "subject": {"id": "u"}},
        )

    b1 = encode_requests([mk(entities[0]), mk(entities[1])], compiled)
    pre.evaluate(b1)
    n = len(pre._subs)
    assert n == 2  # one subtree per signature
    b2 = encode_requests([mk(entities[1]), mk(entities[0])], compiled)
    pre.evaluate(b2)
    assert len(pre._subs) == n  # second batch reuses the cache


def test_prefilter_batch_larger_than_cache():
    """One batch with more signatures than cache_size must not orphan its
    own subtrees (the eviction KeyError found in round-3 review)."""
    doc, entities, actions = _stress_doc(n_policies=5, per_policy=110)
    urns = Urns()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    pre = PrefilteredKernel(compiled, cache_size=2)
    dense = DecisionKernel(compiled)

    def mk(ent, act):
        return Request(
            target=Target(
                subjects=[Attribute(id=urns["subjectID"], value="u")],
                resources=[Attribute(id=urns["entity"], value=ent)],
                actions=[Attribute(id=urns["actionID"], value=act)],
            ),
            context={"resources": [], "subject": {"id": "u"}},
        )

    reqs = [mk(entities[i % 8], actions[i % 4]) for i in range(32)]
    batch = encode_requests(reqs, compiled)
    pd_, pc, ps_ = pre.evaluate(batch)  # 8x4 signatures > cache_size=2
    dd, dc, ds = dense.evaluate(batch)
    assert np.array_equal(pd_, dd)
    assert len(pre._subs) <= 2


def test_evaluator_serves_large_trees_prefiltered():
    """The serving shell's batch path uses the prefiltered kernel for
    trees above MIN_RULES (drop-in; dense below)."""
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    doc, entities, actions = _stress_doc()  # ~720 rules
    urns = Urns()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    ev = HybridEvaluator(engine)
    assert isinstance(ev._kernel, PrefilteredKernel) and ev._kernel.active

    def mk(i):
        return Request(
            target=Target(
                subjects=[
                    Attribute(id=urns["role"], value=f"role-{i % 23}"),
                    Attribute(id=urns["subjectID"], value=f"u{i}"),
                ],
                resources=[Attribute(id=urns["entity"],
                                     value=entities[i % len(entities)])],
                actions=[Attribute(id=urns["actionID"],
                                   value=actions[i % len(actions)])],
            ),
            context={"resources": [],
                     "subject": {"id": f"u{i}",
                                 "role_associations": [
                                     {"role": f"role-{i % 23}",
                                      "attributes": []}],
                                 "hierarchical_scopes": []}},
        )

    reqs = [mk(i) for i in range(40)]
    responses = ev.is_allowed_batch(reqs)
    for req, resp in zip(reqs, responses):
        assert resp.decision == engine.is_allowed(req).decision


def test_prefilter_sharded_over_mesh():
    """Prefiltered kernel with a data-parallel mesh: identical decisions
    to the single-device dispatch (8 virtual CPU devices)."""
    import jax

    from access_control_srv_tpu.parallel import make_mesh

    doc, entities, actions = _stress_doc()
    urns = Urns()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    single = PrefilteredKernel(compiled)
    n = min(8, len(jax.devices()))
    sharded = PrefilteredKernel(compiled, mesh=make_mesh(n))
    assert single.active and sharded.active

    rng = random.Random(17)
    reqs = []
    for i in range(64):
        reqs.append(Request(
            target=Target(
                subjects=[
                    Attribute(id=urns["role"], value=f"role-{i % 23}"),
                    Attribute(id=urns["subjectID"], value=f"u{i}"),
                ],
                resources=[Attribute(id=urns["entity"],
                                     value=rng.choice(entities))],
                actions=[Attribute(id=urns["actionID"],
                                   value=rng.choice(actions))],
            ),
            context={"resources": [],
                     "subject": {"id": f"u{i}",
                                 "role_associations": [
                                     {"role": f"role-{i % 23}",
                                      "attributes": []}],
                                 "hierarchical_scopes": []}},
        ))
    batch = encode_requests(reqs, compiled)
    d1, c1, s1 = single.evaluate(batch)
    d2, c2, s2 = sharded.evaluate(batch)
    assert np.array_equal(d1, d2)
    assert np.array_equal(c1, c2)
    assert np.array_equal(s1, s2)


def test_native_wire_path_through_prefiltered_kernel():
    """The raw-bytes wire fast path (C++ encoder) composes with the
    prefiltered kernel on trees above MIN_RULES: eligible rows served on
    device, decisions equal to the oracle."""
    from access_control_srv_tpu import native

    if not native.available():
        import pytest
        pytest.skip(f"native encoder unavailable: {native.build_error()}")

    from access_control_srv_tpu.srv.evaluator import HybridEvaluator
    from access_control_srv_tpu.srv.transport_grpc import request_to_pb

    doc, entities, actions = _stress_doc()  # ~720 rules, no conditions
    urns = Urns()
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    ev = HybridEvaluator(engine)
    assert ev.native_active and ev._kernel.active

    def mk(i):
        return Request(
            target=Target(
                subjects=[
                    Attribute(id=urns["role"], value=f"role-{i % 23}"),
                    Attribute(id=urns["subjectID"], value=f"u{i}"),
                ],
                resources=[Attribute(id=urns["entity"],
                                     value=entities[i % len(entities)])],
                actions=[Attribute(id=urns["actionID"],
                                   value=actions[i % len(actions)])],
            ),
            context={"resources": [],
                     "subject": {"id": f"u{i}",
                                 "role_associations": [
                                     {"role": f"role-{i % 23}",
                                      "attributes": []}],
                                 "hierarchical_scopes": []}},
        )

    reqs = [mk(i) for i in range(24)]
    messages = [request_to_pb(r).SerializeToString() for r in reqs]
    out = ev.is_allowed_batch_wire(messages)
    assert out is not None
    batch, decision, cacheable, status = out
    assert batch.eligible.all()
    DEC = {"INDETERMINATE": 0, "PERMIT": 1, "DENY": 2}
    for b, req in enumerate(reqs):
        assert decision[b] == DEC[engine.is_allowed(req).decision], b
