"""Explain-mode + shadow-evaluation differential suite.

Explain half: every kernel variant's packed provenance output, decoded
through srv/explain.ExplainDecoder, must match the host oracle's
``EffectEvaluation.source`` bit-for-bit — deciding rule id on rule-decided
rows, policy id on no-rules-policy rows, None on no-contribution rows,
and None (with the aborting rule still named in the richer dict) on
condition-abort rows.  The oracle is normative; the kernel output is
property-tested against it on fixture-matched requests, randomized
grids, and sharded/tenant variants, mirroring
tests/test_kernel_differential.py.

Shadow half: oracle tests for the diff report — an identical candidate
tree yields zero diffs; a candidate with exactly one flipped rule diffs
on exactly the rows whose oracle decision changes; and the honesty
invariants (never blocks, never alters production responses, never
caches, bounded queue drops are counted)."""

import copy
import json
import random
import tempfile
import time

import numpy as np
import pytest
import yaml

from access_control_srv_tpu.core import AccessController, populate
from access_control_srv_tpu.models import Attribute, Request, Target
from access_control_srv_tpu.ops import (
    DecisionKernel,
    PrefilteredKernel,
    compile_policies,
    encode_requests,
)
from access_control_srv_tpu.srv.evaluator import HybridEvaluator
from access_control_srv_tpu.srv.explain import (
    KIND_ABORT,
    KIND_NONE,
    KIND_POLICY,
    KIND_RULE,
    ExplainDecoder,
    explain_capacity_ok,
)
from access_control_srv_tpu.srv.shadow import (
    ShadowEvaluator,
    ShadowSizeClassError,
)
from access_control_srv_tpu.srv.telemetry import Telemetry

from .test_kernel_differential import DEC_CODE, grid_requests
from .test_prefilter import force_active
from .utils import URNS, build_request, fixture, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
LOC = "urn:restorecommerce:acs:model:location.Location"
USER = "urn:restorecommerce:acs:model:user.User"

FIXTURES = [
    "basic_policies.yml",
    "policy_targets.yml",
    "policy_set_targets.yml",
    "role_scopes.yml",
    "conditions.yml",
    "acl_policies.yml",
    "props_multi_rules_entities.yml",
    "ops_multi.yml",
]


# --------------------------------------------------------------- requests


def _member(**kwargs):
    defaults = dict(
        subject_id="ada",
        subject_role="member",
        role_scoping_entity=ORG,
        role_scoping_instance="Org1",
        owner_indicatory_entity=ORG,
        owner_instance="Org1",
        action_type=URNS["read"],
    )
    defaults.update(kwargs)
    return build_request(**defaults)


def _abort_request():
    """Matches conditions.yml r_self_modify's target while its context
    lacks ``subject``, so the condition raises — the guaranteed
    condition-abort row (same shape as tests/test_sig_kernel.py)."""
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value="member")],
            resources=[Attribute(id=URNS["entity"], value=USER)],
            actions=[Attribute(id=URNS["actionID"], value=URNS["modify"])],
        ),
        context={
            "resources": [{"id": "someone-else"}],
            "subject": {
                "role_associations": [{"role": "member", "attributes": []}],
                "hierarchical_scopes": [],
            },
        },
    )


def matched_requests(fixture_name):
    """Fixture-matched rows guaranteeing non-vacuous provenance coverage
    (the generic grid alone leaves some fixtures all-INDETERMINATE)."""
    props = [LOC + "#id", LOC + "#name"], [ORG + "#id", ORG + "#name"]
    if fixture_name == "props_multi_rules_entities.yml":
        return [
            _member(resource_type=[LOC, ORG], resource_id=["L1", "O1"],
                    owner_instance=["Org1", "Org1"],
                    resource_property=list(props)),
            _member(resource_type=[LOC, ORG], resource_id=["L1", "O1"],
                    owner_instance=["Org1", "Org1"],
                    resource_property=[props[0],
                                       props[1] + [ORG + "#description"]]),
            _member(resource_type=[LOC, ORG], resource_id=["L1", "O1"],
                    owner_instance=["Org1", "Org1"]),
        ]
    if fixture_name == "role_scopes.yml":
        return [
            _member(resource_type=LOC, resource_id="L1"),
            _member(resource_type=LOC, resource_id="L1",
                    action_type=URNS["modify"]),
            _member(resource_type=LOC, resource_id="L1",
                    subject_role="manager",
                    role_scoping_instance="SuperOrg1",
                    action_type=URNS["modify"]),
            _member(resource_type=LOC, resource_id="L1",
                    owner_instance="otherOrg"),
        ]
    if fixture_name == "conditions.yml":
        return [_abort_request()]
    return []


def fixture_requests(fixture_name, n=96, seed=53):
    return grid_requests(n=n, seed=seed) + matched_requests(fixture_name)


# ------------------------------------------------------------ parity core


def assert_explain_parity(engine, requests, kernel, policy_sets=None):
    """Kernel explain output == oracle provenance, row for row.  Returns
    the number of rows that carried a non-None source (non-vacuity is the
    caller's assertion — it knows the fixture)."""
    compiled = kernel.compiled
    decoder = ExplainDecoder(
        policy_sets if policy_sets is not None else engine.policy_sets,
        kernel.explain_strides,
    )
    batch = encode_requests(requests, compiled)
    outputs = kernel.evaluate(batch)
    assert len(outputs) == 4, "explain kernel must emit the 4th output"
    decision, _cacheable, status, expl = outputs
    n_source = 0
    for b, request in enumerate(requests):
        if not batch.eligible[b]:
            continue
        expected = engine.is_allowed(copy.deepcopy(request))
        code = int(expl[b])
        source = decoder.source(code)
        info = decoder.decode(code)
        if int(status[b]) != 200:
            # condition abort: bare DENY + error status, NO _rule_id on
            # either side — but the explain dict names the aborting rule
            assert int(decision[b]) == DEC_CODE["DENY"]
            assert int(status[b]) == expected.operation_status.code
            assert source is None
            assert getattr(expected, "_rule_id", None) is None
            assert info is not None and info["kind"] == "condition_abort"
            assert info["rule"] is not None
            continue
        assert int(decision[b]) == DEC_CODE[expected.decision], (
            f"request {b}: decision kernel={decision[b]} "
            f"oracle={expected.decision}"
        )
        assert source == getattr(expected, "_rule_id", None), (
            f"request {b}: source kernel={source!r} "
            f"oracle={getattr(expected, '_rule_id', None)!r} "
            f"(code={code}, kind={code & 3})"
        )
        if source is not None:
            n_source += 1
            assert info is not None
            if info["kind"] == "rule":
                assert info["rule"] == source
            else:
                assert info["kind"] == "policy"
                assert info["policy"] == source
                assert info["rule"] is None
        else:
            assert info is None or info["kind"] == "condition_abort"
    return n_source


# ------------------------------------------------------- dense + sig path


@pytest.mark.parametrize("fixture_name", FIXTURES)
def test_explain_dense_matches_oracle(fixture_name):
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported, compiled.unsupported_reason
    kernel = DecisionKernel(compiled, explain=True)
    n = assert_explain_parity(
        engine, fixture_requests(fixture_name), kernel
    )
    assert n > 0, "no row carried provenance — the test proved nothing"


@pytest.mark.parametrize("fixture_name", FIXTURES)
def test_explain_prefilter_matches_oracle(fixture_name):
    """The sig-path kernel maps compacted rule slots back to ORIGINAL
    flat positions (rule_orig_flat), so the same decoder applies."""
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kernel = force_active(PrefilteredKernel(compiled, explain=True))
    n = assert_explain_parity(
        engine, fixture_requests(fixture_name, seed=11), kernel
    )
    assert n > 0


def test_explain_off_keeps_three_outputs():
    """explain=False kernels emit exactly the pre-explain output tuple
    (the byte-identity of the lowered program is tpu_compat_audit.py's
    explain-shadow-program-identity row)."""
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    batch = encode_requests(grid_requests(n=16), compiled)
    assert len(DecisionKernel(compiled).evaluate(batch)) == 3
    assert len(DecisionKernel(compiled, explain=True).evaluate(batch)) == 4


def test_explain_capacity_bound():
    assert explain_capacity_ok(2, 4, 8)
    assert explain_capacity_ok(1024, 64, 64)  # ~4M slots
    assert not explain_capacity_ok(1 << 14, 1 << 7, 1 << 7)  # 2^28 slots


def test_decoder_defensive_on_garbage():
    """Corrupt codes must decode to None, never raise (serving path)."""
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    decoder = ExplainDecoder(engine.policy_sets,
                             (compiled.KP, compiled.KR))
    for code in (0, -1, (1 << 30) | KIND_RULE, (1 << 30) | KIND_POLICY,
                 (997 << 2) | KIND_ABORT):
        decoder.decode(code)  # must not raise
        decoder.source(code)
    assert decoder.decode(0) is None
    assert decoder.source((1 << 30) | KIND_RULE) is None


# --------------------------------------------------------------- sharded


def _make_2d_mesh(data, model):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[: data * model]).reshape(data, model)
    return Mesh(devices, ("data", "model"))


@pytest.mark.parametrize(
    "fixture_name",
    ["role_scopes.yml", "props_multi_rules_entities.yml", "conditions.yml"],
)
def test_explain_rule_shard_matches_oracle(fixture_name):
    from access_control_srv_tpu.parallel.rule_shard import RuleShardedKernel

    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kernel = RuleShardedKernel(compiled, _make_2d_mesh(2, 4), explain=True)
    n = assert_explain_parity(
        engine, fixture_requests(fixture_name, n=64, seed=29), kernel
    )
    assert n > 0


@pytest.mark.parametrize(
    "fixture_name",
    ["role_scopes.yml", "props_multi_rules_entities.yml", "conditions.yml"],
)
def test_explain_pod_shard_matches_oracle(fixture_name):
    from access_control_srv_tpu.parallel.pod_shard import PodShardedKernel

    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kernel = PodShardedKernel(compiled, _make_2d_mesh(2, 4),
                              explain=True)
    n = assert_explain_parity(
        engine, fixture_requests(fixture_name, n=64, seed=31), kernel
    )
    assert n > 0


# ---------------------------------------------------------- serving path


@pytest.mark.parametrize(
    "fixture_name",
    ["role_scopes.yml", "basic_policies.yml", "conditions.yml"],
)
def test_explain_serving_path_matches_oracle(fixture_name):
    """Through HybridEvaluator.is_allowed_batch: every served row's
    ``_rule_id`` — kernel rows via the explain decode, fallback rows via
    the oracle walk — equals the oracle's, and the richer ``_explain``
    dict is consistent with it."""
    engine = make_engine(fixture_name)
    evaluator = HybridEvaluator(engine, backend="kernel", explain=True)
    try:
        assert evaluator.kernel_active
        requests = fixture_requests(fixture_name, n=64, seed=17)
        responses = evaluator.is_allowed_batch(requests)
        n_source = 0
        for request, response in zip(requests, responses):
            expected = engine.is_allowed(copy.deepcopy(request))
            assert response.decision == expected.decision
            got = getattr(response, "_rule_id", None)
            assert got == getattr(expected, "_rule_id", None)
            if got is not None:
                n_source += 1
                info = getattr(response, "_explain", None)
                if info is not None:  # kernel rows carry the rich dict
                    assert got in (info.get("rule"), info.get("policy"))
        assert n_source > 0
    finally:
        evaluator.shutdown()


def test_explain_tenant_class_shared_jits():
    """Two same-class tenant evaluators on ONE shared jit registry, both
    with explain on: per-tenant provenance stays oracle-exact and the
    second tenant's build registers no new device programs (the explain
    variant lives in the same class-shared registry)."""
    import access_control_srv_tpu.ops.delta as delta_mod

    shared = {}
    engines, evaluators = [], []
    fixtures = ["role_scopes.yml", "role_scopes.yml"]
    tree0 = make_engine(fixtures[0]).policy_sets
    _, caps, _ = delta_mod.full_bucketed_compile(
        tree0, make_engine().urns, version=0
    )
    try:
        for i, fixture_name in enumerate(fixtures):
            engine = make_engine(fixture_name)
            evaluator = HybridEvaluator(
                engine, backend="kernel", explain=True,
                shared_jits=shared, fixed_caps=caps,
                tenant=f"t{i}",
            )
            engines.append(engine)
            evaluators.append(evaluator)
        keys_after_first = None
        requests = fixture_requests("role_scopes.yml", n=32, seed=5)
        for engine, evaluator in zip(engines, evaluators):
            if keys_after_first is None:
                keys_after_first = set(shared)
            responses = evaluator.is_allowed_batch(requests)
            for request, response in zip(requests, responses):
                expected = engine.is_allowed(copy.deepcopy(request))
                assert response.decision == expected.decision
                assert getattr(response, "_rule_id", None) == getattr(
                    expected, "_rule_id", None
                )
        assert set(shared) == keys_after_first, (
            "second same-class tenant registered new device programs"
        )
    finally:
        for evaluator in evaluators:
            evaluator.shutdown()


# -------------------------------------------------------------- fuzzing


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_explain_fuzz_random_grids(seed):
    """Randomized request sweeps across fixtures; the explain source must
    track the oracle on every eligible row, whatever the mix."""
    rng = random.Random(seed)
    for fixture_name in rng.sample(FIXTURES, 3):
        engine = make_engine(fixture_name)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        assert compiled.supported
        kernel = DecisionKernel(compiled, explain=True)
        assert_explain_parity(
            engine,
            fixture_requests(fixture_name, n=48, seed=rng.randrange(1 << 16)),
            kernel,
        )


# ------------------------------------------------------------ shadow half


def _shadow_requests():
    return [
        _member(resource_type=LOC, resource_id="L1"),
        _member(resource_type=LOC, resource_id="L1",
                action_type=URNS["modify"]),
        _member(resource_type=LOC, resource_id="L1",
                subject_role="manager", role_scoping_instance="SuperOrg1",
                action_type=URNS["modify"]),
        _member(resource_type=LOC, resource_id="L1",
                owner_instance="otherOrg"),
    ] + grid_requests(n=28, seed=77)


def _flipped_fixture(tmp_path, rule_id="r_member_read_loc"):
    """role_scopes.yml with one rule's effect flipped PERMIT->DENY."""
    with open(fixture("role_scopes.yml")) as fh:
        doc = yaml.safe_load(fh)
    found = False
    for ps in doc["policy_sets"]:
        for pol in ps.get("policies", []):
            for rule in pol.get("rules", []):
                if rule["id"] == rule_id:
                    assert rule["effect"] == "PERMIT"
                    rule["effect"] = "DENY"
                    found = True
    assert found
    path = str(tmp_path / "candidate.yml")
    with open(path, "w") as fh:
        yaml.safe_dump(doc, fh)
    return path


@pytest.fixture()
def production():
    engine = make_engine("role_scopes.yml")
    evaluator = HybridEvaluator(engine, backend="kernel", explain=True)
    yield evaluator
    evaluator.shutdown()


def _drained_status(shadow):
    assert shadow.drain(15.0), "shadow queue failed to drain"
    # the worker may still be inside _evaluate on the popped batch
    for _ in range(200):
        status = shadow.status()
        if status["queue_depth"] == 0 and status["evaluated"] > 0:
            return status
        time.sleep(0.02)
    return shadow.status()


class TestShadow:
    def test_identical_candidate_zero_diffs(self, production):
        telemetry = Telemetry()
        shadow = ShadowEvaluator(
            production, [fixture("role_scopes.yml")], telemetry=telemetry
        )
        try:
            assert shadow.new_program_keys == [], (
                "same-size-class candidate must reuse production programs"
            )
            requests = _shadow_requests()
            responses = production.is_allowed_batch(requests)
            shadow.submit(requests, responses)
            status = _drained_status(shadow)
            assert status["evaluated"] == len(requests)
            assert status["diffs"] == 0
            assert status["samples"] == []
            assert telemetry.snapshot()["shadow"]["evaluated"] == len(
                requests
            )
        finally:
            shadow.stop()

    def test_flipped_rule_diffs_exactly_affected_rows(
        self, production, tmp_path
    ):
        candidate_path = _flipped_fixture(tmp_path)
        telemetry = Telemetry()
        shadow = ShadowEvaluator(
            production, [candidate_path], telemetry=telemetry
        )
        try:
            requests = _shadow_requests()
            responses = production.is_allowed_batch(requests)

            # the oracle knows exactly which rows must diff
            candidate_engine = AccessController()
            populate(candidate_engine, candidate_path)
            expected = [
                (req, resp.decision,
                 candidate_engine.is_allowed(copy.deepcopy(req)).decision)
                for req, resp in zip(requests, responses)
            ]
            expected_diffs = [
                (p, c) for _, p, c in expected if p != c
            ]
            assert expected_diffs, "flip must affect at least one row"

            shadow.submit(requests, responses)
            status = _drained_status(shadow)
            assert status["diffs"] == len(expected_diffs)
            transitions = {}
            for p, c in expected_diffs:
                key = f"{p}->{c}"
                transitions[key] = transitions.get(key, 0) + 1
            assert status["diffs_by_transition"] == transitions
            assert telemetry.shadow_diffs.snapshot() == transitions
            # sampled records carry provenance on BOTH sides
            assert status["samples"]
            sample = status["samples"][0]
            assert sample["production"]["decision"] != (
                sample["candidate"]["decision"]
            )
            assert sample["production"]["rule_id"] is not None
        finally:
            shadow.stop()

    def test_shadow_never_alters_production(self, production):
        """The mirror point is post-decision: the served objects are
        byte-for-byte what production computed, shadow on or off."""
        requests = _shadow_requests()
        baseline = production.is_allowed_batch(requests)
        shadow = ShadowEvaluator(production, [fixture("role_scopes.yml")])
        try:
            responses = production.is_allowed_batch(requests)
            shadow.submit(requests, responses)
            for base, resp in zip(baseline, responses):
                assert base.decision == resp.decision
                assert base.operation_status.code == (
                    resp.operation_status.code
                )
                assert getattr(base, "_rule_id", None) == getattr(
                    resp, "_rule_id", None
                )
            # and the shadow's evaluator can never cache a decision
            assert shadow.evaluator.decision_cache is None
            _drained_status(shadow)
        finally:
            shadow.stop()

    def test_queue_overflow_drops_counted(self, production):
        telemetry = Telemetry()
        shadow = ShadowEvaluator(
            production, [fixture("role_scopes.yml")],
            telemetry=telemetry, queue_batches=0,  # every submit overflows
        )
        try:
            requests = _shadow_requests()[:4]
            responses = production.is_allowed_batch(requests)
            t0 = time.perf_counter()
            shadow.submit(requests, responses)
            assert time.perf_counter() - t0 < 1.0, "submit must not block"
            status = shadow.status()
            assert status["dropped"] == len(requests)
            assert status["evaluated"] == 0
            assert telemetry.shadow.get("dropped") == len(requests)
        finally:
            shadow.stop()

    def test_sheds_and_expired_deadlines_not_mirrored(self, production):
        """Admission sheds (429/503/504 + INDETERMINATE) were never
        evaluated — mirroring one would fabricate an INDETERMINATE->X
        diff against a candidate that DID evaluate the row.  And the
        serving ``_deadline`` stamp (long expired by replay time) must
        not make the candidate path shed the row as deadline-expired:
        the caller was already answered, so the replay strips the stamp
        on a copy without ever mutating the shared request.  Both found
        live by the bench_all.py shadow-diff row."""
        from access_control_srv_tpu.srv.admission import (
            OVERLOAD_CODE,
            overload_response,
        )

        shadow = ShadowEvaluator(production, [fixture("role_scopes.yml")])
        try:
            requests = _shadow_requests()
            responses = production.is_allowed_batch(requests)
            for request in requests:
                request._deadline = time.monotonic() - 5.0
            shed = overload_response(OVERLOAD_CODE, "shed under overload")
            shadow.submit(requests + [requests[0]], responses + [shed])
            status = _drained_status(shadow)
            assert status["evaluated"] == len(requests), (
                "shed rows must not be mirrored"
            )
            assert status["diffs"] == 0, (
                "identical candidate: any diff here is fabricated "
                "(expired-deadline shed or shed mirroring)"
            )
            assert requests[0]._deadline is not None, (
                "the shared request must never be mutated by the replay"
            )
        finally:
            shadow.stop()

    def test_tenant_filter(self, production):
        shadow = ShadowEvaluator(
            production, [fixture("role_scopes.yml")], tenant="acme"
        )
        try:
            requests = _shadow_requests()[:4]
            responses = production.is_allowed_batch(requests)
            for i, request in enumerate(requests):
                request._tenant = "acme" if i % 2 == 0 else "globex"
            shadow.submit(requests, responses)
            status = _drained_status(shadow)
            assert status["evaluated"] == 2
        finally:
            shadow.stop()

    def test_reload_bumps_shadow_epoch_only(self, production, tmp_path):
        candidate_path = _flipped_fixture(tmp_path)
        shadow = ShadowEvaluator(production, [fixture("role_scopes.yml")])
        try:
            production_version = production._version
            assert shadow.epoch == 0
            shadow.reload([candidate_path])
            assert shadow.epoch == 1
            assert production._version == production_version, (
                "candidate reload must not touch production"
            )
            requests = _shadow_requests()[:4]
            responses = production.is_allowed_batch(requests)
            shadow.submit(requests, responses)
            status = _drained_status(shadow)
            assert status["diffs"] >= 1  # the flip now reports
        finally:
            shadow.stop()

    def test_size_class_overflow_refused(self, production, tmp_path):
        """A candidate overflowing the production size class would need a
        second compiled program — the shadow refuses it outright."""
        with open(fixture("role_scopes.yml")) as fh:
            doc = yaml.safe_load(fh)
        pol = doc["policy_sets"][0]["policies"][0]
        template = copy.deepcopy(pol["rules"][0])
        for i in range(64):  # blow past the production KR bucket
            clone = copy.deepcopy(template)
            clone["id"] = f"r_pad_{i}"
            pol["rules"].append(clone)
        path = str(tmp_path / "oversized.yml")
        with open(path, "w") as fh:
            yaml.safe_dump(doc, fh)
        assert production._caps is not None
        with pytest.raises(ShadowSizeClassError):
            shadow = ShadowEvaluator(production, [path])
            shadow.stop()  # unreachable; belt for the raises-miss case


def test_shadow_through_worker_and_command(tmp_path):
    """Product-path lifecycle: Worker wires the shadow from config, the
    facade mirrors served decisions, ``shadow_status`` and health expose
    it, and teardown joins the shadow worker."""
    from access_control_srv_tpu.srv import Worker

    candidate_path = _flipped_fixture(tmp_path)
    worker = Worker().start(
        {
            "policies": {"type": "local",
                         "paths": [fixture("role_scopes.yml")]},
            "explain": {"enabled": True},
            "shadow": {"enabled": True,
                       "candidate_paths": [candidate_path]},
        }
    )
    try:
        assert worker.shadow is not None
        assert worker.service.shadow is worker.shadow
        requests = _shadow_requests()[:4]
        responses = [worker.service.is_allowed(r) for r in requests]
        assert responses[0].decision == "PERMIT"
        assert getattr(responses[0], "_rule_id", None) == (
            "r_member_read_loc"
        )
        status = worker.command_interface.command(
            "shadow_status", {"drain": True}
        )
        assert status["enabled"] and status["evaluated"] >= 4
        assert status["diffs"] >= 1
        health = worker.command_interface.command("health_check", {})
        assert health["shadow"]["diffs"] >= 1
        assert "samples" not in health["shadow"]
    finally:
        worker.stop()
    assert worker.shadow is None


def test_shadow_disabled_by_default():
    from access_control_srv_tpu.srv import Worker

    worker = Worker().start(
        {"policies": {"type": "local",
                      "paths": [fixture("role_scopes.yml")]}}
    )
    try:
        assert worker.shadow is None
        assert worker.service.shadow is None
        status = worker.command_interface.command("shadow_status", {})
        assert status == {"enabled": False}
        health = worker.command_interface.command("health_check", {})
        assert "shadow" not in health
    finally:
        worker.stop()


@pytest.mark.parametrize("explain_enabled", [True, False])
def test_explain_grpc_trailer(explain_enabled):
    """Wire surface: the io.restorecommerce Response proto has no
    provenance field, so explain rides the ``x-acs-explain`` trailing
    metadata as compact JSON — present with the deciding rule when
    explain is on, entirely absent (and response bytes identical) when
    off."""
    import grpc

    from access_control_srv_tpu.srv import Worker
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import (
        EXPLAIN_METADATA_KEY,
        GrpcServer,
        request_to_pb,
    )

    worker = Worker().start(
        {
            "policies": {"type": "local",
                         "paths": [fixture("role_scopes.yml")]},
            "explain": {"enabled": explain_enabled},
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    channel = grpc.insecure_channel(server.addr)
    try:
        fn = channel.unary_unary(
            "/acstpu.AccessControlService/IsAllowed",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.Response.FromString,
        )
        msg = request_to_pb(_member(resource_type=LOC, resource_id="L1"))
        response, call = fn.with_call(msg)
        assert response.decision == pb.PERMIT
        trailing = dict(call.trailing_metadata() or ())
        if explain_enabled:
            info = json.loads(trailing[EXPLAIN_METADATA_KEY])
            assert info["kind"] == "rule"
            assert info["rule"] == "r_member_read_loc"
        else:
            assert EXPLAIN_METADATA_KEY not in trailing
    finally:
        channel.close()
        server.stop()
        worker.stop()
