"""Depth-N device pipeline tests: the staging buffer pool's aliasing
discipline, byte-identity of depth-N serving vs depth-1 vs the oracle
(admission on and off), per-stream response ordering under out-of-order
completion on the streaming endpoint, the unified admission/batcher
pipeline-depth config, and a slow-marked CRUD-churn soak with delta
patches landing mid-pipeline."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from access_control_srv_tpu.models import Request
from access_control_srv_tpu.ops.staging import HostBufferPool
from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.admission import (
    INTERACTIVE,
    AdmissionController,
)
from access_control_srv_tpu.srv.config import Config

from .test_srv import admin_request, seed_cfg
from .utils import URNS, build_request

ORG = "urn:restorecommerce:acs:model:organization.Organization"


def pipe_cfg(depth, admission=False, cache=True, **overrides):
    cfg = seed_cfg(**overrides)
    cfg["evaluator"] = {
        "pipeline_depth": depth,
        # wide window + small cap: concurrent submits aggregate into
        # kernel-sized batches deterministically
        "micro_batch_window_ms": 20,
        "micro_batch_max": 64,
    }
    if not cache:
        cfg["decision_cache"] = {"enabled": False}
    if admission:
        cfg["admission"] = {"enabled": True}
    return cfg


def mixed_request(i: int) -> Request:
    """Mixed eligible/ineligible traffic: plain kernel rows, novel-role
    rows, and token-bearing rows whose resolution FAILS (no identity
    registration) so they degrade per-row to the oracle."""
    if i % 5 == 4:
        request = admin_request()
        request.context["subject"] = {"token": f"unknown-tok-{i % 3}"}
        return request
    return build_request(
        subject_id=f"user-{i}",
        subject_role=(
            "superadministrator-r-id" if i % 2 else f"role-{i % 7}"
        ),
        role_scoping_entity=ORG,
        role_scoping_instance="system",
        resource_type=ORG,
        resource_id=f"O-{i % 11}",
        action_type=URNS["read"] if i % 3 else URNS["modify"],
    )


def response_key(response):
    return (
        str(response.decision),
        response.evaluation_cacheable,
        response.operation_status.code,
    )


# ------------------------------------------------------------ buffer pool


class TestHostBufferPool:
    def test_recycles_by_shape_and_dtype(self):
        pool = HostBufferPool()
        a = pool.acquire((4, 8), np.int32)
        pool.release(a)
        b = pool.acquire((4, 8), np.int32)
        assert b is a
        assert pool.stats()["hits"] == 1

    def test_leased_buffers_are_never_handed_out_twice(self):
        pool = HostBufferPool()
        a = pool.acquire((16,), np.int32)
        b = pool.acquire((16,), np.int32)
        assert a is not b  # a is still leased
        pool.release(a)
        c = pool.acquire((16,), np.int32)
        assert c is a
        assert c is not b

    def test_double_release_raises(self):
        pool = HostBufferPool()
        a = pool.acquire((8,), np.int32)
        pool.release(a)
        with pytest.raises(ValueError):
            pool.release(a)

    def test_foreign_buffer_release_raises(self):
        pool = HostBufferPool()
        with pytest.raises(ValueError):
            pool.release(np.zeros(8, np.int32))

    def test_distinct_dtypes_do_not_alias(self):
        pool = HostBufferPool()
        a = pool.acquire((8,), np.int32)
        pool.release(a)
        b = pool.acquire((8,), np.int64)
        assert b is not a
        assert b.dtype == np.int64

    def test_bounded_free_list(self):
        pool = HostBufferPool(max_per_key=2)
        bufs = [pool.acquire((4,), np.int32) for _ in range(5)]
        pool.release_all(bufs)
        assert pool.stats()["free"] == 2


# -------------------------------------------- prefilter staging aliasing


class TestPrefilterStagingAliasing:
    """Two batches in flight simultaneously (depth-style overlap) must
    never share a staging buffer, and results must equal the
    sequential (depth-1) evaluation."""

    @pytest.fixture(scope="class")
    def stress(self):
        import bench_all

        from access_control_srv_tpu.ops.compile import compile_policies
        from access_control_srv_tpu.ops.encode import encode_requests
        from access_control_srv_tpu.ops.prefilter import PrefilteredKernel

        engine, _ = bench_all._stress_engine(600)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        kernel = PrefilteredKernel(compiled, staging=HostBufferPool())
        assert kernel.active  # >= MIN_RULES: the pooled sig path engages

        def batch_for(seed):
            rng = np.random.default_rng(seed)
            reqs = []
            for i in range(32):
                k = int(rng.integers(64))
                reqs.append(build_request(
                    subject_id=f"u{i}-{seed}",
                    subject_role=f"role-{int(rng.integers(97))}",
                    resource_type=(
                        f"urn:restorecommerce:acs:model:stress{k}.Stress{k}"
                    ),
                    resource_id=f"res-{i}",
                    action_type=URNS["read"],
                ))
            return encode_requests(reqs, compiled)

        return kernel, batch_for

    def test_overlapped_dispatch_matches_sequential(self, stress):
        kernel, batch_for = stress
        b1, b2 = batch_for(1), batch_for(2)
        ref1 = kernel.evaluate(b1)
        ref2 = kernel.evaluate(b2)
        # dispatch BOTH before materializing EITHER: the pool must hand
        # each batch distinct buffers (the first is still leased)
        m1 = kernel.evaluate_async(b1)
        m2 = kernel.evaluate_async(b2)
        out1, out2 = m1(), m2()
        for ref, out in ((ref1, out1), (ref2, out2)):
            for r, o in zip(ref, out):
                np.testing.assert_array_equal(np.asarray(r), np.asarray(o))

    def test_leases_return_after_materialize(self, stress):
        kernel, batch_for = stress
        pool = kernel.staging
        m = kernel.evaluate_async(batch_for(3))
        assert pool.leased_count() > 0
        m()
        assert pool.leased_count() == 0

    def test_recycled_buffer_cannot_leak_rows(self, stress):
        """A buffer recycled from a PERMIT-heavy batch must not leak
        rows into a later differently-shaped-content batch: evaluate a
        batch, then re-evaluate a second batch that reuses the same
        staging slots, and compare against a fresh pool."""
        kernel, batch_for = stress
        b = batch_for(4)
        warm = kernel.evaluate(b)          # leaves recycled buffers behind
        again = kernel.evaluate(batch_for(5))
        fresh_kernel_pool = kernel.staging
        kernel.staging = HostBufferPool()  # cold pool: fresh allocations
        try:
            cold = kernel.evaluate(batch_for(5))
        finally:
            kernel.staging = fresh_kernel_pool
        for r, o in zip(again, cold):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(o))
        # and the original batch's results were not disturbed
        for r, o in zip(warm, kernel.evaluate(b)):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(o))


# ------------------------------------------------ native arena aliasing


class TestNativeArenaAliasing:
    def _encoder(self):
        import bench_all

        from access_control_srv_tpu import native
        from access_control_srv_tpu.ops.compile import compile_policies

        if not native.available():
            pytest.skip(f"native encoder unavailable: {native.build_error()}")
        engine, _ = bench_all._stress_engine(600, scoped=True)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        return native.NativeBatchEncoder(compiled)

    def _messages(self, n, seed=0):
        from access_control_srv_tpu.srv.transport_grpc import request_to_pb

        orgs = [f"org-{j}" for j in range(4)]
        rng = np.random.default_rng(seed)
        out = []
        for i in range(n):
            k = int(rng.integers(64))
            tree = [{"id": orgs[0], "role": f"role-{i % 97}",
                     "children": [{"id": o} for o in orgs[1:]]}]
            out.append(request_to_pb(build_request(
                subject_id=f"u{i}", subject_role=f"role-{i % 97}",
                role_scoping_entity=ORG, role_scoping_instance=orgs[0],
                resource_type=(
                    f"urn:restorecommerce:acs:model:stress{k}.Stress{k}"
                ),
                resource_id=f"res-{i}", action_type=URNS["read"],
                owner_indicatory_entity=ORG,
                owner_instance=orgs[1 + i % 3],
                hierarchical_scopes=tree,
            )).SerializeToString())
        return out

    def test_unreleased_batches_share_nothing(self):
        enc = self._encoder()
        msgs = self._messages(16)
        b1 = enc.encode_wire(msgs, reuse=True)
        b2 = enc.encode_wire(self._messages(16, seed=1), reuse=True)
        ids1 = {id(v) for v in b1.arrays.values()}
        ids2 = {id(v) for v in b2.arrays.values()}
        assert not ids1 & ids2
        assert id(b1.eligible.base if b1.eligible.base is not None
                  else b1.eligible) not in ids2
        b1.release_staging()
        b2.release_staging()
        # released: the next encode recycles (arena hit, no fresh numpy)
        misses_before = enc._pool.stats()["misses"]
        b3 = enc.encode_wire(msgs, reuse=True)
        assert enc.arena_stats()["hits"] >= 1
        assert enc._pool.stats()["misses"] == misses_before
        # ...and the recycled buffers carry the same content as b1 did
        ref = enc.encode_wire(msgs)
        for name, arr in ref.arrays.items():
            np.testing.assert_array_equal(arr, b3.arrays[name], err_msg=name)
        b3.release_staging()

    def test_release_is_idempotent(self):
        enc = self._encoder()
        batch = enc.encode_wire(self._messages(4), reuse=True)
        batch.release_staging()
        batch.release_staging()  # second call is a no-op, not a crash


# ------------------------------------------- depth-N byte differential


class TestDepthDifferential:
    """Depth-4 (async dispatch/finalize split), depth-2 (legacy), and
    depth-1 serving must produce byte-identical responses on mixed
    eligible/ineligible traffic, admission on and off — and match the
    scalar oracle backend."""

    def _serve(self, cfg):
        from access_control_srv_tpu.srv.transport_grpc import response_to_pb

        worker = Worker().start(cfg)
        try:
            # batcher path: concurrent single submits aggregate into
            # kernel batches (the depth>2 async split engages here)
            requests = [mixed_request(i) for i in range(48)]
            with ThreadPoolExecutor(max_workers=16) as pool:
                batcher_responses = list(pool.map(
                    worker.service.is_allowed, requests
                ))
            # direct batch path (evaluator async split called sync)
            direct = worker.service.is_allowed_batch(
                [mixed_request(i) for i in range(48)]
            )
        finally:
            worker.stop()
        return (
            [response_to_pb(r).SerializeToString()
             for r in batcher_responses],
            [response_to_pb(r).SerializeToString() for r in direct],
        )

    @pytest.mark.parametrize("admission", [False, True])
    def test_depths_byte_identical(self, admission):
        ref = None
        for depth in (1, 2, 4):
            got = self._serve(pipe_cfg(depth, admission=admission))
            if ref is None:
                ref = got
            else:
                assert got == ref, f"depth {depth} diverged"

    def test_depth4_matches_oracle_backend(self):
        kernel = self._serve(pipe_cfg(4))
        # same depth config, backend forced to the scalar oracle
        cfg = pipe_cfg(4)
        cfg["evaluator"]["backend"] = "oracle"
        oracle = self._serve(cfg)
        assert kernel == oracle

    def test_default_depth_is_legacy(self):
        worker = Worker().start(seed_cfg())
        try:
            assert worker.batcher.pipeline_depth == 2
            assert not worker.batcher._async_pipeline
            assert worker.wire_pipeline.depth == 2
        finally:
            worker.stop()


# -------------------------------------------------- streaming ordering


class TestStreamingOrdering:
    def _worker(self, depth=4):
        from access_control_srv_tpu.srv.transport_grpc import (
            GrpcClient,
            GrpcServer,
        )

        worker = Worker().start(pipe_cfg(depth))
        server = GrpcServer(worker, "127.0.0.1:0").start()
        client = GrpcClient(server.addr)
        return worker, server, client

    def _frames(self, sizes):
        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
        from access_control_srv_tpu.srv.transport_grpc import request_to_pb

        frames = []
        for n in sizes:
            frame = pb.BatchRequest()
            for i in range(n):
                frame.requests.add().CopyFrom(
                    request_to_pb(mixed_request(i))
                )
            frames.append(frame)
        return frames

    def test_frames_answered_in_order_with_sizes(self):
        worker, server, client = self._worker()
        try:
            sizes = [8, 12, 9, 16, 10]
            responses = list(client.is_allowed_stream(
                iter(self._frames(sizes)), timeout=60
            ))
            assert [len(r.responses) for r in responses] == sizes
        finally:
            client.close()
            server.stop()
            worker.stop()

    def test_slow_first_frame_cannot_reorder_responses(self):
        """Delay the FIRST frame's finalize so later frames complete
        device evaluation first: response frames must still arrive in
        frame order, each with its own rows."""
        worker, server, client = self._worker()
        try:
            assert worker.evaluator.native_active
            evaluator = worker.evaluator
            real = evaluator.is_allowed_batch_wire_async
            state = {"calls": 0}

            def delayed(messages, span=None, reuse=False):
                fin = real(messages, span=span, reuse=reuse)
                state["calls"] += 1
                if fin is None or state["calls"] > 1:
                    return fin

                def slow_finalize():
                    time.sleep(0.4)
                    return fin()

                return slow_finalize

            evaluator.is_allowed_batch_wire_async = delayed
            try:
                sizes = [8, 12, 9]
                responses = list(client.is_allowed_stream(
                    iter(self._frames(sizes)), timeout=60
                ))
            finally:
                evaluator.is_allowed_batch_wire_async = real
            assert state["calls"] >= 1
            assert [len(r.responses) for r in responses] == sizes
        finally:
            client.close()
            server.stop()
            worker.stop()

    def test_concurrent_streams_share_one_pipeline(self):
        worker, server, client = self._worker()
        try:
            sizes_a = [8, 9, 10]
            sizes_b = [11, 12]
            out = {}

            def run(name, sizes):
                out[name] = [
                    len(r.responses)
                    for r in client.is_allowed_stream(
                        iter(self._frames(sizes)), timeout=60
                    )
                ]

            ta = threading.Thread(target=run, args=("a", sizes_a))
            tb = threading.Thread(target=run, args=("b", sizes_b))
            ta.start()
            tb.start()
            ta.join(60)
            tb.join(60)
            assert out["a"] == sizes_a
            assert out["b"] == sizes_b
        finally:
            client.close()
            server.stop()
            worker.stop()

    def test_aborted_streams_release_permits_and_leases(self):
        """Client disconnect mid-IsAllowedStream: after N aborted streams
        (each cancelled right after its first response frame, with more
        frames still queued against the pipeline's backpressure), every
        backpressure permit must be reacquirable and the pooled staging
        buffers must show zero live leases — a leak here would brick the
        shared pipeline for every later stream."""
        from access_control_srv_tpu.ops.staging import default_pool
        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

        worker, server, client = self._worker(depth=2)
        try:
            stub = client.channel.stream_stream(
                "/acstpu.AccessControlService/IsAllowedStream",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.BatchResponse.FromString,
            )
            frame = self._frames([8])[0]

            def endless():
                while True:  # keeps feeding until the cancel lands
                    yield frame

            for _ in range(6):
                call = stub(endless(), timeout=30)
                first = next(call)
                assert len(first.responses) == 8
                call.cancel()

            pipeline = worker.wire_pipeline
            deadline = time.monotonic() + 15

            def permits_free() -> int:
                held = 0
                for _ in range(pipeline.depth):
                    if pipeline._slots.acquire(blocking=False):
                        held += 1
                for _ in range(held):
                    pipeline._slots.release()
                return held

            while time.monotonic() < deadline:
                if (permits_free() == pipeline.depth
                        and default_pool().stats()["leased"] == 0):
                    break
                time.sleep(0.05)
            assert permits_free() == pipeline.depth
            assert default_pool().stats()["leased"] == 0
            # the pipeline still serves a fresh, well-behaved stream
            sizes = [8, 12]
            responses = list(client.is_allowed_stream(
                iter(self._frames(sizes)), timeout=60
            ))
            assert [len(r.responses) for r in responses] == sizes
        finally:
            client.close()
            server.stop()
            worker.stop()

    def test_stream_matches_unary_byte_identical(self):
        worker, server, client = self._worker()
        try:
            frames = self._frames([8, 12])
            unary = [
                client.is_allowed_batch(f).SerializeToString()
                for f in frames
            ]
            streamed = [
                r.SerializeToString()
                for r in client.is_allowed_stream(iter(frames), timeout=60)
            ]
            assert unary == streamed
        finally:
            client.close()
            server.stop()
            worker.stop()


# --------------------------------------------- unified pipeline depth


class TestUnifiedPipelineDepth:
    def test_feasibility_estimate_tracks_configured_depth(self):
        for depth in (2, 6):
            controller = AdmissionController(
                enabled=True, pipeline_depth=depth, ewma_alpha=1.0
            )
            assert controller.pipeline_batches == depth + 1
            controller.observe_batch(INTERACTIVE, 0.010, 64)
            need = (depth + 1) * 0.010 * controller.deadline_headroom
            ok = controller.admit(
                INTERACTIVE, time.monotonic() + need * 1.5
            )
            assert ok is None
            controller.release(INTERACTIVE, 1)
            shed = controller.admit(
                INTERACTIVE, time.monotonic() + need * 0.8
            )
            assert shed is not None
            assert shed.operation_status.code == 429
            assert "deadline infeasible" in shed.operation_status.message

    def test_same_budget_feasible_shallow_infeasible_deep(self):
        """The regression PIPELINE_BATCHES hardcoding would hide: one
        budget that clears a depth-2 pipeline must be rejected by a
        depth-6 one."""
        budget_s = 3.3 * 0.010 * 1.2
        outcomes = {}
        for depth in (2, 6):
            controller = AdmissionController(
                enabled=True, pipeline_depth=depth, ewma_alpha=1.0
            )
            controller.observe_batch(INTERACTIVE, 0.010, 64)
            outcomes[depth] = controller.admit(
                INTERACTIVE, time.monotonic() + budget_s
            )
        assert outcomes[2] is None
        assert outcomes[6] is not None

    def test_from_config_reads_evaluator_pipeline_depth(self):
        controller = AdmissionController.from_config(Config({
            "evaluator": {"pipeline_depth": 5},
            "admission": {"enabled": True},
        }))
        assert controller.pipeline_batches == 6
        # plain-dict config (tests/bench call sites) defaults safely
        controller = AdmissionController.from_config(
            {"admission": {"enabled": True}}
        )
        assert controller.pipeline_batches == 3

    def test_worker_wires_one_depth_everywhere(self):
        cfg = pipe_cfg(4, admission=True)
        worker = Worker().start(cfg)
        try:
            assert worker.batcher.pipeline_depth == 4
            assert worker.batcher._async_pipeline
            assert worker.wire_pipeline.depth == 4
            assert worker.admission.pipeline_batches == 5
            assert worker.admission.stats()["pipeline_batches"] == 5
        finally:
            worker.stop()


# ------------------------------------------------------- churn soak


@pytest.mark.slow
class TestChurnMidPipeline:
    def test_delta_patches_landing_mid_pipeline_stay_correct(self):
        """CRUD delta patches swap the kernel while depth-4 frames are in
        flight (PR 4's swap-stable jit registry): every response stays a
        valid decision, and after quiescing the served decisions match
        the post-churn oracle."""
        from access_control_srv_tpu.srv.transport_grpc import (
            GrpcClient,
            GrpcServer,
        )

        worker = Worker().start(pipe_cfg(4))
        server = GrpcServer(worker, "127.0.0.1:0").start()
        client = GrpcClient(server.addr)
        rule_service = worker.store.get_resource_service("rule")
        stop_churn = threading.Event()

        def churn():
            flip = 0
            while not stop_churn.is_set():
                flip += 1
                rule_service.update([{
                    "id": "super_admin_rule",
                    "name": f"churn-{flip}",
                    "target": {
                        "subjects": [{
                            "id": URNS["role"],
                            "value": "superadministrator-r-id",
                        }],
                        "resources": [{"id": URNS["entity"], "value": ORG}],
                        "actions": [{"id": URNS["actionID"],
                                     "value": URNS["read"]}],
                    },
                    "effect": "PERMIT" if flip % 2 else "DENY",
                }])
                time.sleep(0.01)

        churner = threading.Thread(target=churn, daemon=True)
        try:
            from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
            from access_control_srv_tpu.srv.transport_grpc import (
                request_to_pb,
            )

            def frames(n_frames):
                for _ in range(n_frames):
                    frame = pb.BatchRequest()
                    for i in range(16):
                        frame.requests.add().CopyFrom(
                            request_to_pb(mixed_request(i))
                        )
                    yield frame

            churner.start()
            responses = list(client.is_allowed_stream(
                frames(30), timeout=120
            ))
            stop_churn.set()
            churner.join(5)
            assert len(responses) == 30
            for frame in responses:
                assert len(frame.responses) == 16
                for row in frame.responses:
                    assert row.decision in (pb.PERMIT, pb.DENY,
                                            pb.INDETERMINATE)
            # quiesced: a fresh frame must match the oracle exactly
            reqs = [mixed_request(i) for i in range(16)]
            served = worker.service.is_allowed_batch(
                [mixed_request(i) for i in range(16)]
            )
            oracle = [
                worker.evaluator._oracle_is_allowed(r) for r in reqs
            ]
            for s, o in zip(served, oracle):
                assert s.decision == o.decision
        finally:
            stop_churn.set()
            client.close()
            server.stop()
            worker.stop()


# -------------------------------------------------- lock-order soak


@pytest.mark.slow
class TestLockOrderUnderPipelineSoak:
    """Runtime complement of acs-lint's static lock discipline (see
    access_control_srv_tpu/analysis/locktrace.py): every Lock/RLock the
    serving stack CREATES during the soak is tracked, each acquisition
    with locks held records a held->acquiring edge, and a cycle in that
    graph is a deadlock the scheduler merely hasn't dealt yet."""

    def test_no_lock_order_cycles_in_churned_pipeline(self):
        from access_control_srv_tpu.analysis.locktrace import (
            lock_order_watch,
        )

        with lock_order_watch() as watch:
            worker = Worker().start(pipe_cfg(4, admission=True))
            rule_service = worker.store.get_resource_service("rule")
            stop_churn = threading.Event()

            def churn():
                flip = 0
                while not stop_churn.is_set():
                    flip += 1
                    rule_service.update([{
                        "id": "super_admin_rule",
                        "name": f"lockorder-churn-{flip}",
                        "target": {
                            "subjects": [{
                                "id": URNS["role"],
                                "value": "superadministrator-r-id",
                            }],
                            "resources": [{"id": URNS["entity"],
                                           "value": ORG}],
                            "actions": [{"id": URNS["actionID"],
                                         "value": URNS["read"]}],
                        },
                        "effect": "PERMIT" if flip % 2 else "DENY",
                    }])
                    time.sleep(0.01)

            churner = threading.Thread(target=churn, daemon=True)
            try:
                churner.start()

                def serve(seed):
                    for frame in range(20):
                        worker.service.is_allowed_batch([
                            mixed_request(seed * 31 + frame * 7 + i)
                            for i in range(16)
                        ])

                with ThreadPoolExecutor(max_workers=6) as pool:
                    futures = [pool.submit(serve, n) for n in range(6)]
                    for future in futures:
                        future.result(timeout=120)
            finally:
                stop_churn.set()
                churner.join(timeout=5)
                worker.stop()
        watch.assert_acyclic()
        # the soak must have exercised real nested acquisitions — an
        # empty graph would mean the watch missed the system entirely
        assert watch.edges(), "no lock-order edges recorded during soak"
