"""Tier-1 CI gate: the full acs-lint run over the shipped package must
be clean against the checked-in baseline — no new findings, no stale or
unjustified baseline entries, no parse errors — and fast enough to run
on every commit.

This is the test expression of ``python -m access_control_srv_tpu.
analysis`` exiting 0, plus the audit-surface claims the baseline makes
(every entry justified) and the invariants the host-only markers carry.
"""

from __future__ import annotations

import time

from access_control_srv_tpu.analysis import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    load_baseline,
    run_analysis,
)


def test_package_tree_clean_under_budget():
    t0 = time.monotonic()
    report = run_analysis(PACKAGE_ROOT, baseline=DEFAULT_BASELINE)
    elapsed = time.monotonic() - t0
    diff = report.diff
    assert not report.errors, report.errors
    assert diff is not None
    detail = {
        "new": [f.key for f in diff.new],
        "stale": [e.key for e in diff.stale],
        "unjustified": [e.key for e in diff.unjustified],
    }
    assert report.ok, detail
    # the gate must stay cheap enough for every-commit CI: well under
    # the 10 s budget on any development machine
    assert elapsed < 10.0, f"acs-lint took {elapsed:.1f}s"
    # sanity: the analyzer actually walked the package, not an empty dir
    assert report.modules > 40


def test_baseline_entries_all_justified():
    entries = load_baseline(DEFAULT_BASELINE)
    assert entries, "shipped baseline should carry the accepted findings"
    for entry in entries:
        assert entry.justification.strip(), (
            f"baseline entry {entry.key} has no justification — every "
            "accepted finding needs a recorded reason"
        )


def test_host_only_modules_declare_the_marker():
    """The modules TPU_COMPAT.md claims are host-only must carry the
    self-declaring marker — the claim is machine-checked, not prose."""
    for name in ("srv/tracing.py", "srv/admission.py",
                 "srv/decision_cache.py", "srv/router.py"):
        source = (PACKAGE_ROOT / name).read_text()
        assert "acs-lint: host-only" in source, (
            f"{name} lost its host-only declaration"
        )
