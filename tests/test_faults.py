"""Failpoint framework (srv/faults.py) and device watchdog
(srv/watchdog.py) unit tests: deterministic schedules, action semantics,
hang release, and the timeout -> quarantine -> probe -> restore cycle
against a scripted fake evaluator."""

import threading
import time

import pytest

from access_control_srv_tpu.srv.faults import (
    FailpointRegistry,
    Failpoint,
    FaultError,
    configure_from,
)
from access_control_srv_tpu.srv.watchdog import (
    DeviceTimeoutError,
    DeviceWatchdog,
)


# ------------------------------------------------------------ schedules


class TestFailpointSchedule:
    def _hits(self, spec, n, seed=0):
        point = Failpoint(spec, seed=seed)
        return [i for i in range(n) if point.evaluate()]

    def test_default_hits_every_call(self):
        assert self._hits({"site": "s"}, 5) == [0, 1, 2, 3, 4]

    def test_after_skips_prefix(self):
        assert self._hits({"site": "s", "after": 3}, 6) == [3, 4, 5]

    def test_every_strides(self):
        assert self._hits({"site": "s", "every": 3}, 9) == [0, 3, 6]

    def test_after_plus_every(self):
        assert self._hits({"site": "s", "after": 2, "every": 2}, 8) == \
            [2, 4, 6]

    def test_count_caps_hits(self):
        assert self._hits({"site": "s", "count": 2}, 10) == [0, 1]

    def test_p_is_deterministic_per_seed(self):
        spec = {"site": "s", "p": 0.5}
        a = self._hits(spec, 50, seed=7)
        b = self._hits(spec, 50, seed=7)
        c = self._hits(spec, 50, seed=8)
        assert a == b
        assert a != c  # a different seed draws a different stream
        assert 0 < len(a) < 50

    def test_p_stream_is_per_site(self):
        # the schedule of one site must not depend on another's call rate
        a = Failpoint({"site": "a", "p": 0.5}, seed=3)
        hits_alone = [i for i in range(30) if a.evaluate()]
        a2 = Failpoint({"site": "a", "p": 0.5}, seed=3)
        b = Failpoint({"site": "b", "p": 0.5}, seed=3)
        hits_interleaved = []
        for i in range(30):
            b.evaluate()
            if a2.evaluate():
                hits_interleaved.append(i)
        assert hits_alone == hits_interleaved

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            Failpoint({"site": "s", "action": "explode"})


# ------------------------------------------------------------- registry


class TestFailpointRegistry:
    def test_disarmed_fire_is_noop(self):
        reg = FailpointRegistry()
        assert reg.enabled is False
        assert reg.fire("anything") is None

    def test_error_action_raises_fault_error(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "error"}])
        with pytest.raises(FaultError) as err:
            reg.fire("s")
        assert err.value.site == "s"
        assert "fault injected at s" in str(err.value)

    def test_error_action_uses_exc_factory(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "error"}])

        class Domain(Exception):
            pass

        with pytest.raises(Domain):
            reg.fire("s", exc=Domain)

    def test_unarmed_site_misses(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s"}])
        assert reg.fire("other") is None

    def test_delay_action_sleeps(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "delay", "delay_s": 0.05}])
        t0 = time.monotonic()
        hit = reg.fire("s")
        assert hit is not None and hit.action == "delay"
        assert time.monotonic() - t0 >= 0.04

    def test_hang_bounded_by_hang_s(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "hang", "hang_s": 0.05}])
        t0 = time.monotonic()
        reg.fire("s")
        assert 0.04 <= time.monotonic() - t0 < 5.0

    def test_clear_releases_hangers(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "hang", "hang_s": 30.0}])
        released = threading.Event()

        def hanger():
            reg.fire("s")
            released.set()

        thread = threading.Thread(target=hanger, daemon=True)
        thread.start()
        time.sleep(0.05)
        assert not released.is_set()
        reg.clear()
        assert released.wait(5.0)
        thread.join(5.0)

    def test_tear_truncates_bytes(self):
        reg = FailpointRegistry()
        reg.configure([{"site": "s", "action": "torn", "torn_frac": 0.5}])
        data = b"x" * 100
        assert reg.tear("s", data) == b"x" * 50

    def test_tear_passthrough_when_disarmed(self):
        reg = FailpointRegistry()
        data = b"record\n"
        assert reg.tear("s", data) == data

    def test_stats_and_hits(self):
        reg = FailpointRegistry()
        reg.configure(
            [{"site": "s", "action": "delay", "delay_s": 0.0, "every": 2}],
            seed=9,
        )
        for _ in range(4):
            reg.fire("s")
        stats = reg.stats()
        assert stats["enabled"] is True
        assert stats["seed"] == 9
        assert stats["hits_by_site"] == {"s": 2}
        assert reg.hits("s") == 2
        assert reg.hits("other") == 0
        (point,) = stats["points"]
        assert point["calls"] == 4 and point["hits"] == 2

    def test_arm_context_manager_clears_on_exit(self):
        reg = FailpointRegistry()
        with reg.arm([{"site": "s"}]):
            assert reg.enabled
            with pytest.raises(FaultError):
                reg.fire("s")
        assert not reg.enabled
        assert reg.fire("s") is None

    def test_on_hit_hook_counts_and_never_injects(self):
        reg = FailpointRegistry()
        seen = []
        reg.on_hit = seen.append
        reg.configure([{"site": "s", "action": "delay", "delay_s": 0.0}])
        reg.fire("s")
        assert seen == ["s"]

        def broken(site):
            raise RuntimeError("metrics down")

        reg.on_hit = broken
        assert reg.fire("s") is not None  # hook errors are swallowed

    def test_configure_from_block(self):
        reg_points = [{"site": "s", "action": "delay"}]
        assert configure_from(None) is False
        assert configure_from({"enabled": False,
                               "points": reg_points}) is False
        from access_control_srv_tpu.srv.faults import REGISTRY

        try:
            assert configure_from({"enabled": True, "seed": 3,
                                   "points": reg_points}) is True
            assert REGISTRY.stats()["seed"] == 3
        finally:
            REGISTRY.clear()


# ------------------------------------------------------------- watchdog


class FakeEvaluator:
    """Scripted evaluator facade: the watchdog only needs
    attach_watchdog / set_quarantined / refresh / kernel_probe."""

    def __init__(self):
        self.quarantined_calls = []
        self.refreshes = 0
        self.probes = 0
        self.probe_ok = True
        self.refresh_ok = True

    def attach_watchdog(self, watchdog):
        self.watchdog = watchdog

    def set_quarantined(self, flag):
        self.quarantined_calls.append(bool(flag))

    def refresh(self, wait=False):
        self.refreshes += 1
        if not self.refresh_ok:
            raise RuntimeError("refresh failed")

    def kernel_probe(self):
        self.probes += 1
        if not self.probe_ok:
            raise RuntimeError("probe failed")
        return True


def _watchdog(ev, **over):
    cfg = {"window_s": 30.0, "min_volume": 1, "failure_ratio": 0.5,
           "open_s": 0.05, "half_open_probes": 1}
    kw = {"materialize_timeout_s": 0.1, "probe_interval_s": 0.05,
          "breaker_cfg": cfg}
    kw.update(over)
    return DeviceWatchdog(ev, **kw)


class TestDeviceWatchdog:
    def test_run_passes_through_result(self):
        ev = FakeEvaluator()
        wd = _watchdog(ev)
        try:
            assert wd.run(lambda: ("d", "c", "s")) == ("d", "c", "s")
            assert wd.status()["timeouts"] == 0
        finally:
            wd.close()

    def test_run_relays_callable_errors(self):
        ev = FakeEvaluator()
        wd = _watchdog(ev)
        try:
            with pytest.raises(ValueError):
                wd.run(lambda: (_ for _ in ()).throw(ValueError("bad")))
        finally:
            wd.close()

    def test_timeout_raises_and_quarantines(self):
        ev = FakeEvaluator()
        ev.probe_ok = False  # keep the probe failing: stay quarantined
        wd = _watchdog(ev)
        try:
            wedge = threading.Event()
            with pytest.raises(DeviceTimeoutError):
                wd.run(lambda: wedge.wait(10.0))
            wedge.set()
            status = wd.status()
            assert status["timeouts"] == 1
            assert status["quarantined"] is True
            assert ev.quarantined_calls[:1] == [True]
        finally:
            wd.close()

    def test_probe_restores_kernel_path(self):
        ev = FakeEvaluator()
        wd = _watchdog(ev)
        try:
            wedge = threading.Event()
            with pytest.raises(DeviceTimeoutError):
                wd.run(lambda: wedge.wait(10.0))
            wedge.set()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                status = wd.status()
                if not status["quarantined"]:
                    break
                time.sleep(0.02)
            status = wd.status()
            assert status["quarantined"] is False
            assert status["restores"] == 1
            assert status["degraded_seconds"] > 0.0
            assert ev.refreshes >= 1 and ev.probes >= 1
            # quarantine toggled on, then off
            assert ev.quarantined_calls[0] is True
            assert ev.quarantined_calls[-1] is False
            # healthy serving again records breaker successes
            assert wd.run(lambda: 42) == 42
        finally:
            wd.close()
