"""Soak test for the HR-scope rendezvous under heavy concurrency.

1k concurrent token-miss requests park on HRScopeProvider's SHARED
condition variable (srv/cache.py) while a small responder pool answers the
auth topic: the server must neither exhaust threads (one kernel wait
object total, not one Event per request) nor blow tail latency — the
reference parks promises on an event loop
(reference: src/core/accessController.ts:753-767); the per-thread-Event
design VERDICT r5 item 6 flagged would allocate 1k kernel objects here
and leak bookkeeping under churn.

Marked ``slow``: excluded from the tier-1 run (`-m 'not slow'`).
"""

import queue
import threading
import time

import pytest

from access_control_srv_tpu.srv.cache import HRScopeProvider, SubjectCache

N_WAITERS = 1000
N_RESPONDERS = 4


class _QueueTopic:
    """Auth-topic stub: requests land on a queue the responder pool
    drains (emission never blocks the caller, like the broker)."""

    def __init__(self):
        self.requests: "queue.Queue[dict]" = queue.Queue()

    def emit(self, event: str, message: dict):
        assert event == "hierarchicalScopesRequest"
        self.requests.put(message)


@pytest.mark.slow
def test_thousand_concurrent_token_miss_rendezvous():
    topic = _QueueTopic()
    provider = HRScopeProvider(
        SubjectCache(), auth_topic=topic, timeout_ms=60_000
    )

    release_responders = threading.Event()
    peak_parked = [0]
    latencies: list[float] = []
    results: list = [None] * N_WAITERS
    lat_lock = threading.Lock()

    def waiter(i: int):
        token = f"tok-{i}"
        context = {"subject": {
            "id": f"user-{i}",
            "token": token,
            "tokens": [{"token": token, "interactive": False}],
        }}
        t0 = time.perf_counter()
        out = provider.create_hr_scope(context)
        elapsed = time.perf_counter() - t0
        with lat_lock:
            latencies.append(elapsed)
            results[i] = out["subject"].get("hierarchical_scopes")

    def responder():
        release_responders.wait(30)
        while True:
            try:
                message = topic.requests.get(timeout=2)
            except queue.Empty:
                return
            token_date = message["token"]
            token = token_date.split(":", 1)[0]
            idx = token.split("-", 1)[1]
            provider.handle_hr_scopes_response({
                "token": token_date,
                "subject_id": f"user-{idx}",
                "interactive": False,
                "hierarchical_scopes": [{"id": f"org-{idx}"}],
            })

    threads = [
        threading.Thread(target=waiter, args=(i,), daemon=True)
        for i in range(N_WAITERS)
    ]
    responders = [
        threading.Thread(target=responder, daemon=True)
        for _ in range(N_RESPONDERS)
    ]
    for t in responders:
        t.start()
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
    # hold the responses until nearly every waiter is parked: the peak
    # below then proves 1k simultaneous waiters share ONE condition
    deadline = time.time() + 20
    while time.time() < deadline:
        with provider._cond:
            parked = sum(provider.waiting.values())
        peak_parked[0] = max(peak_parked[0], parked)
        if parked >= int(N_WAITERS * 0.9):
            break
        time.sleep(0.01)
    release_responders.set()
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "waiter failed to drain"
    wall = time.perf_counter() - wall0

    # every waiter released with its scopes — nobody timed out
    assert all(r == [{"id": f"org-{i}"}] for i, r in enumerate(results))
    assert peak_parked[0] >= int(N_WAITERS * 0.9), (
        f"only {peak_parked[0]} waiters parked concurrently"
    )
    # bookkeeping fully drained: neither the waiting map nor the released
    # set may leak entries after the soak
    assert not provider.waiting
    assert not provider._released
    # tail latency: release is a broadcast on one condition — p99 must sit
    # within a small multiple of the responder drain time, not the
    # rendezvous timeout
    latencies.sort()
    p99 = latencies[int(len(latencies) * 0.99)]
    assert p99 < 30.0, f"p99 {p99:.1f}s: rendezvous wakeup degraded"
    assert wall < 60.0, f"soak took {wall:.1f}s"
