"""Chaos matrix (PR 11 tentpole): deterministic fault classes driven
through the 2-replica cluster tier, re-running the PR 9 journal-exact
stale-decision oracle after every soak.

Fault classes covered here:

1. replica kill      — SIGKILL mid-churn + journal-replay convergence
2. identity outage   — ``identity.grpc`` failpoint inside live replicas;
                       token rows fail closed, never PERMIT, and recover
3. device hang       — ``device.materialize`` hang inside a replica; the
                       watchdog bounds it, trips quarantine, and the
                       probe restores the kernel path (verified via
                       ``program_identity``)
4. journal torn-tail — crash-interrupted broker append; reboot recovers
                       the consistent prefix, zero real frames lost
5. mid-file corruption — flipped byte in a CRC'd journal record; reboot
                       truncates to the consistent prefix and replicas
                       converge on the journal-exact state
6. adapter flap      — ``adapter.http`` failpoint under a live GraphQL
                       endpoint; per-row transport errors only, no
                       fabricated payloads, full recovery on clear

Classes 1-3 share one cluster soak (records feed the journal-exact
oracle); 4-5 share one broker-tamper reboot sequence that also proves
the snapshot+tail cold boot converges to the same ``table_fingerprint``
as the full-journal state it snapshotted."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import grpc
import pytest

from access_control_srv_tpu.parallel.cluster import LocalCluster
from access_control_srv_tpu.srv.broker import SocketEventBus
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.router import POLICY_EPOCH_METADATA_KEY

from .cluster_util import (
    command_over,
    create_reader_policy_tree,
    reader_rule_doc,
    seed_paths,
    upsert_rule,
    wait_converged,
    wire_request,
)
from .utils import URNS

SHED_CODES = (429, 503, 504)
RULE_ID = "r_matrix"
ORG = "urn:restorecommerce:acs:model:organization.Organization"
RULES_TOPIC = "io.restorecommerce.rules.resource"

WATCHDOG_CFG = {
    "enabled": True,
    "materialize_timeout_s": 0.5,
    "probe_interval_s": 0.3,
    "breaker": {"window_s": 8.0, "min_volume": 2, "failure_ratio": 0.3,
                "open_s": 0.5, "half_open_probes": 1},
}


def _replica_command(addr: str, name: str, payload=None) -> dict:
    channel = grpc.insecure_channel(addr)
    try:
        return command_over(channel, name, payload)
    finally:
        channel.close()


def _arm(addr: str, points: list, seed: int = 11) -> dict:
    out = _replica_command(addr, "faults", {
        "action": "configure", "points": points, "seed": seed,
    })
    assert out.get("status") == "configured", out
    return out


def _clear(addr: str) -> None:
    out = _replica_command(addr, "faults", {"action": "clear"})
    assert out.get("status") == "cleared", out


def _token_request(token: str) -> pb.Request:
    msg = pb.Request()
    msg.target.subjects.add(id=URNS["role"], value="superadministrator-r-id")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.resources.add(id=URNS["resourceID"], value="O1")
    msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
    msg.context.subject.value = json.dumps({"token": token}).encode()
    return msg


def _run_oracle(records, flip_acks, broker_addr):
    """The PR 9 journal-exact stale-decision oracle (see
    tests/test_cluster_chaos.py for the derivation)."""
    bus = SocketEventBus(broker_addr)
    try:
        rule_frames = bus.topic(RULES_TOPIC).read(0)
        other = sum(
            len(bus.topic(
                f"io.restorecommerce.{kind}s.resource"
            ).read(0))
            for kind in ("policy", "policy_set")
        )
    finally:
        bus.close()
    effect_at: list = []
    current = None
    for _event, message in rule_frames:
        doc = (message or {}).get("payload") or {}
        if doc.get("id") == RULE_ID:
            current = doc.get("effect")
        effect_at.append(current)
    expected = {"PERMIT": pb.PERMIT, "DENY": pb.DENY, None: None}

    def ok_at(epoch: int, decision) -> bool:
        k = epoch - other
        if k < 1 or k > len(effect_at):
            return False
        want = expected[effect_at[k - 1]]
        return want is not None and decision == want

    stale = []
    for t_send, t_recv, code, decision, epoch in records:
        if code != 200:
            continue
        assert epoch >= 0, "decision response missing epoch stamp"
        if ok_at(epoch, decision):
            continue
        in_flight = any(
            t_before <= t_recv + 0.25 and t_ack >= t_send - 1.0
            for t_before, t_ack in flip_acks
        )
        if in_flight and (
            ok_at(epoch - 1, decision) or ok_at(epoch + 1, decision)
        ):
            continue
        stale.append((t_send, code, decision, epoch))
    assert not stale, (
        f"{len(stale)} stale decisions, e.g. {stale[:5]}; "
        f"{len(rule_frames)} rule frames, other={other}"
    )


@pytest.mark.chaos(timeout=280)
def test_chaos_matrix_cluster_soak(tmp_path):
    """Replica kill + identity outage + device hang through one live
    2-replica cluster under CRUD churn, with the journal-exact oracle
    over every routed decision."""
    from access_control_srv_tpu.srv.identity import MockIdentityServer
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient

    ids = MockIdentityServer()
    for name in ("base", "out") + tuple(f"rec{i}" for i in range(10)):
        ids.register(f"chaos-tok-{name}", {
            "id": "chaos-ada",
            "tokens": [{"token": f"chaos-tok-{name}", "interactive": True}],
            "role_associations": [
                {"role": "superadministrator-r-id", "attributes": []}
            ],
        })
    cluster = LocalCluster(
        n_replicas=2,
        seed_cfg=seed_paths(),
        router_cfg={"health_interval_s": 0.3, "max_retries": 1},
        cfg_extra={
            "evaluator": {"watchdog": dict(WATCHDOG_CFG)},
            "client": {"identity": {"address": ids.address,
                                    "timeout": 2.0}},
        },
        base_dir=str(tmp_path),
        broker_snapshot_every=40,
    ).start()
    channel = grpc.insecure_channel(cluster.router.addr)
    hr_bus = SocketEventBus(cluster.broker_addr)
    try:
        create_reader_policy_tree(channel, RULE_ID)
        wait_converged([r.addr for r in cluster.replicas], timeout_s=45.0,
                       min_epoch=1)

        # HR rendezvous responder for token-resolved subjects (the
        # identity phase): replies over the cluster's own broker topic
        auth_topic = hr_bus.topic("io.restorecommerce.authentication")

        def hr_responder(event_name, message, ctx):
            if event_name != "hierarchicalScopesRequest":
                return
            threading.Thread(target=lambda: auth_topic.emit(
                "hierarchicalScopesResponse",
                {"token": message["token"], "subject_id": "chaos-ada",
                 "interactive": True, "hierarchical_scopes": []},
            ), daemon=True).start()

        auth_topic.on(hr_responder)

        is_allowed = channel.unary_unary(
            "/acstpu.AccessControlService/IsAllowed",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.Response.FromString,
        )
        stop = threading.Event()
        records: list = []
        transport_errors: list = []

        def client_loop():
            msg = wire_request(role="reader-role")
            while not stop.is_set():
                t_send = time.monotonic()
                try:
                    resp, call = is_allowed.with_call(msg, timeout=10)
                except grpc.RpcError as err:
                    transport_errors.append(
                        (time.monotonic(), err.code(), err.details())
                    )
                    time.sleep(0.02)
                    continue
                trailers = dict(call.trailing_metadata() or ())
                records.append((
                    t_send, time.monotonic(),
                    resp.operation_status.code, resp.decision,
                    int(trailers.get(POLICY_EPOCH_METADATA_KEY, -1)),
                ))
                time.sleep(0.004)

        flip_acks: list = []
        state = {"effect": "PERMIT"}

        def churn_loop():
            while not stop.is_set():
                effect = "DENY" if state["effect"] == "PERMIT" else "PERMIT"
                t_before = time.monotonic()
                try:
                    code = upsert_rule(
                        channel, reader_rule_doc(RULE_ID, effect=effect)
                    )
                except grpc.RpcError:
                    time.sleep(0.05)
                    continue
                if code == 200:
                    flip_acks.append((t_before, time.monotonic()))
                    state["effect"] = effect
                time.sleep(0.12)

        client = threading.Thread(target=client_loop, daemon=True)
        churn = threading.Thread(target=churn_loop, daemon=True)
        client.start()
        churn.start()

        # ---- class 1: replica SIGKILL mid-churn ----------------------
        time.sleep(1.5)
        cluster.replicas[1].kill()
        time.sleep(2.0)
        restarted = cluster.restart_replica(1)
        wait_converged(
            [cluster.replicas[0].addr, restarted.addr], timeout_s=60.0,
        )

        # ---- class 2: identity-service outage ------------------------
        # baseline: token -> findByToken -> HR rendezvous -> PERMIT
        # through the router (retry while channels settle post-restart)
        baseline = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                baseline = is_allowed(
                    _token_request("chaos-tok-base"), timeout=10
                )
            except grpc.RpcError:
                time.sleep(0.2)
                continue
            if baseline.decision == pb.PERMIT:
                break
            time.sleep(0.2)
        assert baseline is not None and baseline.decision == pb.PERMIT, (
            baseline and (baseline.decision,
                          baseline.operation_status.code)
        )
        addrs = [r.addr for r in cluster.replicas]
        for addr in addrs:
            _arm(addr, [{"site": "identity.grpc", "action": "error"}])
        try:
            for _ in range(3):  # fresh token: no cache to hide behind
                try:
                    resp = is_allowed(
                        _token_request("chaos-tok-out"), timeout=10
                    )
                except grpc.RpcError:
                    continue
                # fail closed: an unresolvable subject is NEVER a PERMIT
                assert resp.decision != pb.PERMIT, (
                    resp.decision, resp.operation_status.code
                )
        finally:
            for addr in addrs:
                _clear(addr)
        recovered = False
        deadline = time.monotonic() + 30.0
        attempt = 0
        while time.monotonic() < deadline and not recovered:
            try:
                resp = is_allowed(
                    _token_request(f"chaos-tok-rec{attempt % 10}"),
                    timeout=10,
                )
                recovered = resp.decision == pb.PERMIT
            except grpc.RpcError:
                pass
            attempt += 1
            time.sleep(0.3)
        assert recovered, "identity resolution did not recover"

        # ---- class 3: device hang -> quarantine -> restore ----------
        # BATCH requests: the unary path serves oracle-first by design
        # (srv/evaluator.py is_allowed) and never dispatches the device;
        # only batches reach kernel.evaluate_async and hit the hang
        victim_addr = cluster.replicas[0].addr
        _arm(victim_addr, [{"site": "device.materialize",
                            "action": "hang", "hang_s": 20.0}])
        direct = GrpcClient(victim_addr)
        try:
            deadline = time.monotonic() + 45.0
            i = 0
            quarantined = False
            while time.monotonic() < deadline and not quarantined:
                # unique resources force decision-cache misses so rows
                # actually dispatch the kernel (and hit the hang)
                batch = pb.BatchRequest(requests=[
                    wire_request(role="reader-role",
                                 resource_id=f"hang-{i}-{j}")
                    for j in range(2)
                ])
                out = direct.is_allowed_batch(batch)
                # honest resolution: bounded timeout -> oracle row or
                # shed; never a transport black hole, never fabricated
                for resp in out.responses:
                    assert resp.operation_status.code in \
                        (200,) + SHED_CODES, resp.operation_status
                ident = _replica_command(victim_addr, "program_identity")
                quarantined = bool(ident.get("quarantined"))
                i += 1
            assert quarantined, (
                "device hang never tripped quarantine: "
                f"{_replica_command(victim_addr, 'faults')}"
            )
            status = _replica_command(victim_addr, "faults")
            assert status["hits_by_site"].get("device.materialize", 0) > 0
            # quarantined serving stays honest AND fast (oracle path)
            out = direct.is_allowed_batch(pb.BatchRequest(requests=[
                wire_request(role="reader-role", resource_id="quar-0")
            ]))
            assert out.responses[0].operation_status.code in \
                (200,) + SHED_CODES
        finally:
            _clear(victim_addr)
        # bounded recovery window: probe re-initializes, kernel returns
        deadline = time.monotonic() + 45.0
        restored = False
        while time.monotonic() < deadline and not restored:
            ident = _replica_command(victim_addr, "program_identity")
            restored = (not ident.get("quarantined")
                        and bool(ident.get("kernel_active")))
            time.sleep(0.3)
        assert restored, f"kernel path not restored: {ident}"
        wd = _replica_command(victim_addr, "health_check").get(
            "device_watchdog") or {}
        assert wd.get("restores", 0) >= 1, wd
        assert wd.get("degraded_seconds", 0) > 0, wd

        # ---- wind down + journal-exact oracle ------------------------
        time.sleep(0.5)
        stop.set()
        client.join(timeout=15)
        churn.join(timeout=15)
        assert not client.is_alive() and not churn.is_alive()
        assert not transport_errors, transport_errors[:5]
        bad = {code for _, _, code, _, _ in records
               if code != 200 and code not in SHED_CODES}
        assert not bad, bad
        assert len(records) > 100
        assert len(flip_acks) >= 5
        _run_oracle(records, flip_acks, cluster.broker_addr)
    finally:
        hr_bus.close()
        channel.close()
        cluster.stop()
        ids.stop()


# ------------------------------------------------- journal tampering


def _journal_path(base_dir: str) -> str:
    return os.path.join(base_dir, "broker", "broker.journal")


def _snapshot_rule_effects(base_dir: str, rule_id: str):
    """Ordered effects of ``rule_id`` frames inside the broker
    snapshot's rules topic."""
    path = os.path.join(base_dir, "broker", "broker.snapshot")
    blob = json.load(open(path))
    state = json.loads(blob["state"])
    out = []
    for _event, message in state.get("topics", {}).get(RULES_TOPIC, []):
        doc = (message or {}).get("payload") or {}
        if doc.get("id") == rule_id:
            out.append(doc.get("effect"))
    return out


def _tail_rule_lines(path: str, rule_id: str):
    """(line_index, effect) for every ``rule_id`` emit in the journal
    tail (CRC-framed lines)."""
    out = []
    for i, line in enumerate(open(path).read().splitlines()):
        body = line[10:] if line.startswith("C") else line
        try:
            rec = json.loads(body)
        except ValueError:
            continue
        if rec.get("k") != "emit" or rec.get("t") != RULES_TOPIC:
            continue
        doc = ((rec.get("m") or {}).get("payload")) or {}
        if doc.get("id") == rule_id:
            out.append((i, doc.get("effect")))
    return out


def _direct_decision(addr: str):
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient

    client = GrpcClient(addr)
    try:
        resp = client.is_allowed(wire_request(role="reader-role"))
        return resp.decision, resp.operation_status.code
    finally:
        client.close()


@pytest.mark.chaos(timeout=280)
def test_journal_tamper_reboot_recovery(tmp_path):
    """Torn-tail + mid-file corruption classes over cluster reboots on
    one base_dir, with the snapshot-bounded recovery acceptance: a cold
    boot from snapshot + tail converges to the same table_fingerprint
    the full-journal state had before the reboot."""
    base_dir = str(tmp_path)
    expected_pb = {"PERMIT": pb.PERMIT, "DENY": pb.DENY}

    def boot():
        return LocalCluster(
            n_replicas=2, seed_cfg=seed_paths(), base_dir=base_dir,
            router_cfg={"health_interval_s": 0.3},
        ).start()

    # ---- phase A: churn, forced snapshot, known tail ----------------
    cluster = boot()
    channel = grpc.insecure_channel(cluster.router.addr)
    try:
        create_reader_policy_tree(channel, RULE_ID)
        effects = ["DENY", "PERMIT", "DENY", "PERMIT", "DENY", "PERMIT"]
        for effect in effects[:3]:
            assert upsert_rule(
                channel, reader_rule_doc(RULE_ID, effect=effect)
            ) == 200
        bus = SocketEventBus(cluster.broker_addr)
        try:
            status = bus.snapshot()  # compaction point: journal restarts
            assert status["exists"] and status["tail_records"] == 0
        finally:
            bus.close()
        for effect in effects[3:]:
            assert upsert_rule(
                channel, reader_rule_doc(RULE_ID, effect=effect)
            ) == 200
        ids = wait_converged([r.addr for r in cluster.replicas],
                             timeout_s=45.0)
        identity_a = (ids[0]["policy_epoch"], ids[0]["table_fingerprint"])
    finally:
        channel.close()
        cluster.stop()
    assert _snapshot_rule_effects(base_dir, RULE_ID)[-1] == effects[2]
    tail_rules = _tail_rule_lines(_journal_path(base_dir), RULE_ID)
    assert [e for _, e in tail_rules] == effects[3:]

    # ---- class 4: torn tail (crash mid-append) ----------------------
    with open(_journal_path(base_dir), "a") as fh:
        fh.write('C00000000 {"k": "emit", "t": "x"')  # no newline, bad CRC
    cluster = boot()
    try:
        ids = wait_converged([r.addr for r in cluster.replicas],
                             timeout_s=60.0)
        # snapshot + tail replay reproduces the pre-reboot program
        # byte-identically: the torn garbage cost nothing
        assert (ids[0]["policy_epoch"],
                ids[0]["table_fingerprint"]) == identity_a
        bus = SocketEventBus(cluster.broker_addr)
        try:
            recovered = bus.snapshot_status()["recovered"]
        finally:
            bus.close()
        assert recovered and recovered.get("dropped_bytes", 0) > 0
        decision, code = _direct_decision(cluster.replicas[0].addr)
        assert code == 200 and decision == expected_pb[effects[-1]]
    finally:
        cluster.stop()

    # ---- class 5: mid-file corruption -------------------------------
    # flip bytes inside the LAST chaos-rule record of the tail: replay
    # must truncate there, landing on the previous flip's effect
    tail_rules = _tail_rule_lines(_journal_path(base_dir), RULE_ID)
    assert len(tail_rules) >= 2
    corrupt_line, _ = tail_rules[-1]
    _, surviving_effect = tail_rules[-2]
    lines = open(_journal_path(base_dir)).read().splitlines(keepends=True)
    assert f'"{tail_rules[-1][1]}"' in lines[corrupt_line]
    lines[corrupt_line] = lines[corrupt_line].replace(
        f'"{tail_rules[-1][1]}"', f'"{tail_rules[-1][1][::-1]}"', 1
    )
    open(_journal_path(base_dir), "w").writelines(lines)
    cluster = boot()
    try:
        ids = wait_converged([r.addr for r in cluster.replicas],
                             timeout_s=60.0)
        # both replicas converge on the journal-exact truncated state
        bus = SocketEventBus(cluster.broker_addr)
        try:
            recovered = bus.snapshot_status()["recovered"]
        finally:
            bus.close()
        assert recovered and recovered.get("dropped_bytes", 0) > 0
        decision, code = _direct_decision(cluster.replicas[0].addr)
        assert code == 200 and decision == expected_pb[surviving_effect]
        decision, code = _direct_decision(cluster.replicas[1].addr)
        assert code == 200 and decision == expected_pb[surviving_effect]
    finally:
        cluster.stop()


# --------------------------------------------------- adapter flapping


GQL_BODY = json.dumps({
    "data": {"op": {"details": [{"payload": {"id": "res-1"}}]}}
}).encode()


class _GqlHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(GQL_BODY)))
        self.end_headers()
        self.wfile.write(GQL_BODY)

    def log_message(self, *args):
        pass


def test_adapter_flap_per_row_honest_and_recovers():
    """Class 6: a flapping context-query upstream (``adapter.http``
    armed with a Bernoulli schedule) yields per-row transport errors
    only — every successful row carries the true payload, no row is
    fabricated — and the adapter fully recovers once the flap clears."""
    from access_control_srv_tpu.core.errors import (
        ContextQueryTransportError,
    )
    from access_control_srv_tpu.srv.adapters import GraphQLAdapter
    from access_control_srv_tpu.srv.faults import REGISTRY

    from access_control_srv_tpu.models import Request, Target

    server = ThreadingHTTPServer(("127.0.0.1", 0), _GqlHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}/graphql"
    adapter = GraphQLAdapter(url)
    cq = SimpleNamespace(query="query q { all { id } }", filters=[])
    req = Request(target=Target(subjects=[], resources=[], actions=[]),
                  context={"resources": []})
    try:
        with REGISTRY.arm([{"site": "adapter.http", "action": "error",
                            "p": 0.6}], seed=5):
            results = adapter.query_many([(cq, req) for _ in range(12)])
            failed = [r for r in results
                      if isinstance(r, ContextQueryTransportError)]
            served = [r for r in results if not isinstance(r, Exception)]
            assert REGISTRY.hits("adapter.http") > 0
            # per-row honesty: a row either fails as a transport error
            # or carries the TRUE upstream payload — nothing in between
            assert len(failed) + len(served) == 12, results
            for row in served:
                assert row == [{"id": "res-1"}]
        # flap cleared: every row serves
        results = adapter.query_many([(cq, req) for _ in range(6)])
        assert results == [[{"id": "res-1"}]] * 6
    finally:
        adapter.close()
        server.shutdown()
        server.server_close()


# ------------------------------------- relation-tuple journal torn tail


@pytest.mark.chaos(timeout=240)
def test_relation_tuple_journal_torn_tail(tmp_path):
    """ReBAC chaos class: relation-tuple churn over the broker-journaled
    tuple topic, broker killed mid-churn with a torn partial record left
    on disk (crash mid-append).  A cold reboot must truncate the torn
    tail, and a store booting by snapshot + tail replay must converge to
    the survivor's exact tuple fingerprint — the same snapshot-bounded
    recovery acceptance the policy CRUD topics get, now for tuples."""
    from access_control_srv_tpu.srv.broker import BrokerServer
    from access_control_srv_tpu.srv.relations import RelationTupleStore

    data_dir = str(tmp_path)
    doc = "urn:restorecommerce:acs:model:document.Document"

    def boot():
        return BrokerServer(data_dir=data_dir, snapshot_every=1000).start()

    # ---- phase A: churn, forced compaction, tail churn, kill ---------
    broker = boot()
    bus_a = SocketEventBus(broker.address)
    store_a = RelationTupleStore(bus=bus_a)
    store_a.set_rewrite(doc, "viewer",
                        [("this",), ("computed_userset", "owner")])
    for i in range(40):
        store_a.create([(doc, f"doc{i % 8}", "viewer", f"u{i % 5}")])
    ctl = SocketEventBus(broker.address)
    try:
        status = ctl.snapshot()  # compaction point: journal restarts
        assert status["exists"] and status["tail_records"] == 0
    finally:
        ctl.close()
    # tail after the snapshot: deletes, creates and a rewrite flip all
    # live ONLY in the journal tail when the broker dies
    store_a.delete([(doc, "doc1", "viewer", "u1")])
    store_a.set_rewrite(doc, "viewer", [("this",)])
    for i in range(10):
        store_a.create([(doc, f"doc{i % 4}", "owner", f"o{i}")])
    fp_survivor = store_a.fingerprint()
    store_a.stop()
    bus_a.close()
    broker.stop()

    # the crash: a partial record appended mid-write (no newline, CRC
    # cannot match) — exactly what a SIGKILL between write and newline
    # leaves on disk
    with open(os.path.join(data_dir, "broker.journal"), "a") as fh:
        fh.write('C00000000 {"k": "emit", "t": "io.restorecomm')  # torn

    # ---- phase B: reboot; late store replays snapshot + tail ---------
    broker = boot()
    try:
        assert broker.recovered
        assert broker.recovered.get("dropped_bytes", 0) > 0
        bus_b = SocketEventBus(broker.address)
        try:
            late = RelationTupleStore(bus=bus_b)
            late.replay()
            assert late.fingerprint() == fp_survivor
            # spot-check semantics, not just the hash: the tail's
            # delete and rewrite-narrowing both survived the reboot
            assert not late.check("viewer", doc, "doc1", "u1")
            assert not late.check("viewer", doc, "doc1", "o1")  # no owner->viewer
            assert late.check("owner", doc, "doc1", "o1")
            late.stop()
        finally:
            bus_b.close()
    finally:
        broker.stop()
