"""Pod-sharded (set-axis) kernel suite: differential vs the dense kernel,
the prefiltered kernel and the scalar oracle; combining-algorithm mixes
across shard boundaries; shard-local delta patching (patched-sharded ==
from-scratch-sharded after every mutation, zero new XLA compiles on
unaffected shards); the shared shard_map version probe; and a
chaos-marker cluster test killing one replica of a sharded pod
mid-churn."""

import random
import threading
import time

import numpy as np
import pytest
from jax.sharding import Mesh

from access_control_srv_tpu.core.engine import AccessController
from access_control_srv_tpu.ops import (
    DecisionKernel,
    compile_policies,
    encode_requests,
)
from access_control_srv_tpu.ops.prefilter import PrefilteredKernel
from access_control_srv_tpu.parallel.pod_shard import (
    PodShardedKernel,
    partition_sets,
)
from access_control_srv_tpu.srv.decision_cache import DecisionCache
from access_control_srv_tpu.srv.evaluator import HybridEvaluator
from access_control_srv_tpu.srv.store import PolicyStore

from .test_delta import (
    DO,
    FA,
    PO,
    _apply_random_op,
    assert_decisions_match_oracle,
    assert_tables_match_full_compile,
    make_request,
    rule_doc,
)
from .test_kernel_differential import DEC_CODE, grid_requests
from .test_prefilter import force_active
from .utils import make_engine


def make_2d_mesh(data: int, model: int) -> Mesh:
    import jax

    devices = np.array(jax.devices()[: data * model]).reshape(data, model)
    return Mesh(devices, ("data", "model"))


# ------------------------------------------------- shard_map probe helper


def test_resolve_shard_map_prefers_jax_attr(monkeypatch):
    """jax >= 0.5 path: jax.shard_map wins when present."""
    import jax

    from access_control_srv_tpu.parallel.mesh import resolve_shard_map

    sentinel = object()
    monkeypatch.setattr(jax, "shard_map", sentinel, raising=False)
    assert resolve_shard_map() is sentinel


def test_resolve_shard_map_experimental_fallback(monkeypatch):
    """jax < 0.5 path: jax.experimental.shard_map.shard_map backs the
    probe when the top-level attribute is absent."""
    import jax
    from jax.experimental.shard_map import shard_map as experimental

    from access_control_srv_tpu.parallel.mesh import resolve_shard_map

    monkeypatch.delattr(jax, "shard_map", raising=False)
    assert resolve_shard_map() is experimental


# ------------------------------------------------------ partition invariants


def test_partition_covers_all_sets():
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    a = compiled.arrays
    for n in (2, 4, 8):
        shards, s_local = partition_sets(compiled, n)
        assert len(shards) == n
        assert n * s_local >= a["set_valid"].shape[0]
        covered = 0
        for sh in shards:
            # every owned slot's set-axis planes are byte-identical to
            # the pod-level tables (the target indirection is remapped,
            # so compare a representative non-target plane)
            hi = min(sh.s_lo + s_local, a["set_valid"].shape[0])
            assert np.array_equal(
                sh.arrays["set_valid"][: hi - sh.s_lo],
                a["set_valid"][sh.s_lo:hi],
            )
            # compacted subtable decodes back to the original rows
            local_rows = sh.arrays["rule_target"][
                sh.arrays["rule_has_target"]
            ]
            pod_rows = a["rule_target"][sh.s_lo:hi][
                a["rule_has_target"][sh.s_lo:hi]
            ]
            for name in ("t_role", "t_scoping", "t_sub_vals"):
                assert np.array_equal(
                    sh.arrays[name][local_rows],
                    a[name][pod_rows],
                )
            covered += int(a["set_valid"][sh.s_lo:hi].sum())
        assert covered == int(a["set_valid"].sum())


# ----------------------------------------------------------- differential


@pytest.mark.parametrize("data,model", [(4, 2), (2, 4), (1, 8)])
@pytest.mark.parametrize(
    "fixture_name", ["role_scopes.yml", "props_multi_rules_entities.yml",
                     "conditions.yml"]
)
def test_pod_shard_differential(fixture_name, data, model):
    """Sharded decisions bit-identical to the dense kernel on HR-scoped,
    property-heavy and conditioned trees, for three mesh layouts; oracle
    spot-checks ride along."""
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    mesh = make_2d_mesh(data, model)
    sharded = PodShardedKernel(compiled, mesh)
    kernel = DecisionKernel(compiled)

    requests = grid_requests(n=96, seed=53)
    batch = encode_requests(requests, compiled)
    d_ref, c_ref, s_ref = kernel.evaluate(batch)
    d_sh, c_sh, s_sh = sharded.evaluate(batch)

    eligible = batch.eligible
    assert np.array_equal(d_sh[eligible], d_ref[eligible])
    assert np.array_equal(c_sh[eligible], c_ref[eligible])
    assert np.array_equal(s_sh[eligible], s_ref[eligible])

    for b in range(0, len(requests), 7):
        if not eligible[b]:
            continue
        expected = engine.is_allowed(requests[b])
        assert d_sh[b] == DEC_CODE[expected.decision], b


@pytest.mark.parametrize(
    "fixture_name", ["role_scopes.yml", "conditions.yml"]
)
def test_pod_shard_matches_prefiltered(fixture_name):
    """Prefilter-on differential: the signature-compacted kernel and the
    pod-sharded kernel reach the same decisions (both are proven against
    the dense kernel; this pins the transitive pair directly)."""
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    pre = force_active(PrefilteredKernel(compiled))
    sharded = PodShardedKernel(compiled, make_2d_mesh(2, 4))

    batch = encode_requests(grid_requests(n=96, seed=19), compiled)
    d_p, c_p, s_p = pre.evaluate(batch)
    d_sh, c_sh, s_sh = sharded.evaluate(batch)
    eligible = batch.eligible
    assert np.array_equal(d_sh[eligible], d_p[eligible])
    assert np.array_equal(c_sh[eligible], c_p[eligible])
    assert np.array_equal(s_sh[eligible], s_p[eligible])


def _mixed_ca_stack(n_sets=6, pols_per_set=2, rules_per_pol=3):
    """Synthetic tree whose combining algorithms cycle per set AND per
    policy, so every shard boundary of a 2/4/8-way split separates sets
    with different algorithms — the cross-shard last-set-wins reduce must
    still match the sequential oracle."""
    engine = AccessController()
    evaluator = HybridEvaluator(engine)
    store = PolicyStore(engine, evaluator=evaluator)
    cas = [DO, PO, FA]
    rules, pols, sets_ = [], [], []
    rid = 0
    for s in range(n_sets):
        pol_ids = []
        for p in range(pols_per_set):
            r_ids = []
            for _ in range(rules_per_pol):
                effect = "DENY" if (rid % 3 == 0) else "PERMIT"
                rules.append(rule_doc(f"r{rid}", rid % 8, effect=effect,
                                      cacheable=bool(rid % 2)))
                r_ids.append(f"r{rid}")
                rid += 1
            pid = f"p{s}_{p}"
            pols.append({"id": pid,
                         "combining_algorithm": cas[(s + p) % 3],
                         "rules": r_ids})
            pol_ids.append(pid)
        sets_.append({"id": f"s{s}", "combining_algorithm": cas[s % 3],
                      "policies": pol_ids})
    store.seed(sets_, pols, rules)
    return engine, evaluator, store


@pytest.mark.parametrize("model", [2, 4, 8])
def test_combining_mix_across_shard_boundaries(model):
    engine, _evaluator, _store = _mixed_ca_stack()
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    mesh = make_2d_mesh(8 // model, model)
    sharded = PodShardedKernel(compiled, mesh)
    dense = DecisionKernel(compiled)

    requests = [make_request(k, who) for k in range(8)
                for who in ("u1", "u2")]
    batch = encode_requests(requests, compiled)
    d_ref, c_ref, s_ref = dense.evaluate(batch)
    d_sh, c_sh, s_sh = sharded.evaluate(batch)
    assert np.array_equal(d_sh, d_ref)
    assert np.array_equal(c_sh, c_ref)
    assert np.array_equal(s_sh, s_ref)
    for req, d in zip(requests, d_sh):
        assert d == DEC_CODE[engine.is_allowed(req).decision]


# ------------------------------------------------- shard-local delta patch


def _pod_stack(n_sets=3, pols_per_set=2, rules_per_pol=4,
               data=2, model=4):
    """Evaluator + store wired for the pod-sharded delta path."""
    mesh = make_2d_mesh(data, model)
    engine = AccessController()
    evaluator = HybridEvaluator(
        engine, decision_cache=DecisionCache(), mesh=mesh,
        model_axis="model", pod_shards=model,
    )
    store = PolicyStore(engine, evaluator=evaluator)
    rules, pols, sets_ = [], [], []
    rid = 0
    for s in range(n_sets):
        pol_ids = []
        for p in range(pols_per_set):
            r_ids = []
            for _ in range(rules_per_pol):
                rules.append(rule_doc(f"r{rid}", rid % 16))
                r_ids.append(f"r{rid}")
                rid += 1
            pid = f"p{s}_{p}"
            pols.append({"id": pid, "combining_algorithm": PO,
                         "rules": r_ids})
            pol_ids.append(pid)
        sets_.append({"id": f"s{s}", "combining_algorithm": DO,
                      "policies": pol_ids})
    store.seed(sets_, pols, rules)
    return engine, evaluator, store, rid


def test_single_rule_patch_relowers_exactly_one_shard():
    """The tentpole acceptance bar, off-chip: one CRUD event re-slices
    one shard (all other per-shard fingerprints unchanged, reused by
    reference), zero new XLA compiles anywhere, tables equal a
    from-scratch compile, decisions equal the oracle."""
    engine, ev, store, n_rules = _pod_stack()
    assert isinstance(ev._kernel, PodShardedKernel)
    assert ev.delta_enabled

    ident0 = ev.shard_identity()
    fp0 = [s["fingerprint"] for s in ident0["shards"]]
    assert ident0["n_shards"] == 4
    assert ident0["pod_fingerprint"]

    sizes_before = {k: f._cache_size()
                    for k, f in ev._shared_jits.items()}
    store.get_resource_service("rule").update(
        [rule_doc("r2", 2, effect="DENY")]
    )
    assert ev._delta_counts["patches"] == 1, ev._delta_counts

    ident1 = ev.shard_identity()
    fp1 = [s["fingerprint"] for s in ident1["shards"]]
    changed = [i for i in range(len(fp0)) if fp0[i] != fp1[i]]
    assert len(changed) == 1, changed  # exactly one shard relowered
    applied = [s["applied_patches"] for s in ident1["shards"]]
    assert applied[changed[0]] == 1 and sum(applied) == 1
    assert ident1["pod_fingerprint"] != ident0["pod_fingerprint"]

    # unaffected shards reuse the SAME host arrays (by reference, not a
    # re-slice that happens to match)
    for i in range(ident0["n_shards"]):
        if i == changed[0]:
            continue
        assert ev._kernel.shards[i].arrays is not None
    sizes_after = {k: f._cache_size()
                   for k, f in ev._shared_jits.items()}
    assert sizes_after == sizes_before  # zero new XLA compiles

    assert_tables_match_full_compile(engine, ev)
    assert_decisions_match_oracle(engine, ev, range(n_rules))


def test_patch_visibility_surfaces():
    """delta_stats/table_fingerprint integrate the sharding tier: patch
    counters advance, the pod fingerprint folds into the table
    fingerprint, and health surfaces carry the watermarks."""
    _engine, ev, store, _n = _pod_stack()
    tf0 = ev.table_fingerprint()
    store.get_resource_service("rule").update(
        [rule_doc("r0", 0, effect="DENY")]
    )
    stats = ev.delta_stats()
    assert stats["patches"] == 1
    assert stats["sharding"]["n_shards"] == 4
    assert sum(stats["sharding"]["applied_patches"]) == 1
    assert ev.table_fingerprint() != tf0  # pod fp folded in


@pytest.mark.parametrize("seed", [13, 37])
def test_churn_fuzz_patched_sharded_equals_from_scratch(seed):
    """Random CRUD churn: after EVERY mutation the incrementally
    maintained shard tables must byte-match a from-scratch partition of
    the published pod tables, and decisions must match the oracle.
    In-capacity mutations must never add XLA compiles."""
    engine, ev, store, n_rules = _pod_stack(n_sets=2, pols_per_set=3)
    rng = random.Random(seed)
    next_id = [1000]
    for step in range(12):
        full_before = ev._delta_counts["full_compiles"]
        t_cap_before = ev._kernel.t_cap
        sizes_before = {k: f._cache_size()
                        for k, f in ev._shared_jits.items()}
        _apply_random_op(rng, store, next_id)

        kernel = ev._kernel
        assert isinstance(kernel, PodShardedKernel)
        fresh, _s_local = partition_sets(ev._compiled, kernel.n_shards)
        assert [sh.fingerprint for sh in kernel.shards] == \
            [sh.fingerprint for sh in fresh], f"step {step}"
        if (ev._delta_counts["full_compiles"] == full_before
                and kernel.t_cap == t_cap_before):
            sizes_after = {k: f._cache_size()
                           for k, f in ev._shared_jits.items()}
            assert sizes_after == sizes_before, f"step {step}"
        if step % 4 == 3:
            assert_tables_match_full_compile(engine, ev)
            assert_decisions_match_oracle(engine, ev, range(16))
    assert ev._delta_counts["patches"] >= 3  # the delta path really ran


# ------------------------------------------------------ chaos-marker test


@pytest.mark.cluster(timeout=240)
def test_sharded_pod_replica_kill_mid_churn(tmp_path):
    """Kill one replica of a POD-SHARDED cluster mid-churn: the survivor
    keeps serving through the router, the restarted replica replays the
    journal through the shard-local patch path, and both report the same
    pod fingerprint (per-shard tables byte-identical across processes)."""
    import grpc

    from access_control_srv_tpu.parallel.cluster import LocalCluster
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

    from .cluster_util import (
        create_reader_policy_tree,
        program_identities,
        reader_rule_doc,
        seed_paths,
        upsert_rule,
        wait_converged,
        wire_request,
    )

    cluster = LocalCluster(
        n_replicas=2,
        seed_cfg=seed_paths(),
        cfg_extra={"parallel": {"pod_shards": 2, "data_devices": 2}},
        router_cfg={"health_interval_s": 0.3, "max_retries": 1},
        base_dir=str(tmp_path),
    ).start()
    channel = grpc.insecure_channel(cluster.router.addr)
    try:
        create_reader_policy_tree(channel, "r_pod")
        addrs = [r.addr for r in cluster.replicas]
        wait_converged(addrs, timeout_s=30.0, min_epoch=1)

        # both replicas actually run the sharded kernel
        for ident in program_identities(addrs):
            assert ident.get("sharding"), ident
            assert ident["sharding"]["n_shards"] == 2

        is_allowed = channel.unary_unary(
            "/acstpu.AccessControlService/IsAllowed",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.Response.FromString,
        )
        stop = threading.Event()
        codes: list = []

        def client_loop():
            msg = wire_request(role="reader-role")
            while not stop.is_set():
                try:
                    resp = is_allowed(msg, timeout=10)
                    codes.append(resp.operation_status.code)
                except grpc.RpcError:
                    pass
                time.sleep(0.01)

        def churn_loop():
            flip = 0
            while not stop.is_set():
                flip += 1
                effect = "PERMIT" if flip % 2 else "DENY"
                try:
                    upsert_rule(channel,
                                reader_rule_doc("r_pod", effect=effect))
                except grpc.RpcError:
                    pass
                time.sleep(0.12)

        client = threading.Thread(target=client_loop, daemon=True)
        churn = threading.Thread(target=churn_loop, daemon=True)
        client.start()
        churn.start()

        time.sleep(1.5)
        cluster.replicas[1].kill()          # SIGKILL mid-churn
        time.sleep(2.0)
        restarted = cluster.restart_replica(1)
        ids = wait_converged(
            [cluster.replicas[0].addr, restarted.addr], timeout_s=60.0,
        )
        stop.set()
        client.join(timeout=15)
        churn.join(timeout=15)
        assert not client.is_alive() and not churn.is_alive()

        # served through the kill window
        assert sum(1 for c in codes if c == 200) > 50

        # byte-identical sharded convergence: same pod fingerprint AND
        # same per-shard fingerprints on both processes
        pods = [i.get("sharding") for i in ids]
        assert all(p for p in pods), ids
        assert len({p["pod_fingerprint"] for p in pods}) == 1, pods
        assert (
            [s["fingerprint"] for s in pods[0]["shards"]]
            == [s["fingerprint"] for s in pods[1]["shards"]]
        )
    finally:
        channel.close()
        cluster.stop()
