"""Admission-control subsystem tests (srv/admission.py + the batcher /
service / adapter / identity integration): queue-bound shedding, deadline
rejection vs admission around the EWMA estimate, two-class fairness under
saturation, circuit-breaker state transitions (adapter and identity),
drain-on-shutdown semantics, and the differential check that admitted
requests produce byte-identical decisions to a no-admission run."""

import threading
import time

import pytest

from access_control_srv_tpu.core.errors import ContextQueryTransportError
from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.models.model import (
    OperationStatus,
    Request,
    Response,
    ReverseQuery,
    Target,
)
from access_control_srv_tpu.srv.admission import (
    BULK,
    DEADLINE_CODE,
    INTERACTIVE,
    OVERLOAD_CODE,
    PIPELINE_BATCHES,
    SHUTDOWN_CODE,
    AdmissionController,
    CircuitBreaker,
    LatencyEwma,
    deadline_from_context,
)
from access_control_srv_tpu.srv.adapters import GraphQLAdapter
from access_control_srv_tpu.srv.batcher import MicroBatcher
from access_control_srv_tpu.srv.identity import (
    CachingIdentityClient,
    StaticIdentityClient,
)

from .test_srv import admin_request, seed_cfg


# --------------------------------------------------------------- fixtures


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class StubEvaluator:
    """Deterministic evaluator double: PERMIT everything after an optional
    per-batch delay (models device/oracle latency)."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.decision_cache = None
        self.engine = None
        self.batches: list[int] = []
        self.bulk_batches: list[int] = []

    def prepare_batch(self, requests):
        pass

    def _response(self):
        return Response(
            decision=Decision.PERMIT, obligations=[],
            evaluation_cacheable=False,
            operation_status=OperationStatus(),
        )

    def is_allowed(self, request):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches.append(1)
        return self._response()

    def is_allowed_batch(self, requests):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches.append(len(requests))
        return [self._response() for _ in requests]

    def what_is_allowed(self, request):
        return ReverseQuery(policy_sets=[], obligations=[],
                            operation_status=OperationStatus())

    def what_is_allowed_batch(self, requests):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.bulk_batches.append(len(requests))
        return [self.what_is_allowed(r) for r in requests]


def make_request(i: int = 0) -> Request:
    return Request(target=Target(), context={"resources": []})


def controller(**kwargs) -> AdmissionController:
    kwargs.setdefault("enabled", True)
    return AdmissionController(**kwargs)


# --------------------------------------------------------- circuit breaker


class TestCircuitBreaker:
    def _breaker(self, **kwargs):
        clock = FakeClock()
        kwargs.setdefault("window_s", 10.0)
        kwargs.setdefault("min_volume", 4)
        kwargs.setdefault("failure_ratio", 0.5)
        kwargs.setdefault("open_s", 2.0)
        kwargs.setdefault("half_open_probes", 2)
        breaker = CircuitBreaker("test", time_fn=clock, **kwargs)
        return breaker, clock

    def _trip(self, breaker):
        for _ in range(4):
            assert breaker.allow()
            breaker.record_failure()

    def test_starts_closed_and_allows(self):
        breaker, _ = self._breaker()
        assert breaker.state == CircuitBreaker.CLOSED
        assert breaker.allow()

    def test_opens_at_failure_ratio_with_min_volume(self):
        breaker, _ = self._breaker()
        # below min_volume: never opens even at 100% failures
        for _ in range(3):
            breaker.record_failure()
            assert breaker.state == CircuitBreaker.CLOSED
        breaker.record_failure()  # 4th failure reaches min_volume
        assert breaker.state == CircuitBreaker.OPEN
        assert not breaker.allow()

    def test_successes_keep_ratio_below_threshold(self):
        breaker, _ = self._breaker()
        for _ in range(6):
            breaker.record_success()
        for _ in range(4):
            breaker.record_failure()
        # 4 failures / 10 calls = 0.4 < 0.5
        assert breaker.state == CircuitBreaker.CLOSED

    def test_half_open_after_jittered_cooldown(self):
        breaker, clock = self._breaker()
        self._trip(breaker)
        clock.advance(1.0)  # still inside the minimum cooldown
        assert not breaker.allow()
        clock.advance(2.1)  # past open_s * 1.5 (max jitter)
        assert breaker.state == CircuitBreaker.HALF_OPEN
        assert breaker.allow()  # probe slot

    def test_half_open_probe_success_closes(self):
        breaker, clock = self._breaker()
        self._trip(breaker)
        clock.advance(3.1)
        assert breaker.allow()
        breaker.record_success()
        assert breaker.state == CircuitBreaker.CLOSED
        # window restarted: old failures cannot re-trip it
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.CLOSED

    def test_half_open_probe_failure_reopens(self):
        breaker, clock = self._breaker()
        self._trip(breaker)
        clock.advance(3.1)
        assert breaker.allow()
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        assert not breaker.allow()

    def test_half_open_probe_slots_bounded(self):
        breaker, clock = self._breaker(half_open_probes=2)
        self._trip(breaker)
        clock.advance(3.1)
        assert breaker.allow()
        assert breaker.allow()
        assert not breaker.allow()  # both probe slots taken

    def test_stats_shape(self):
        breaker, _ = self._breaker()
        self._trip(breaker)
        stats = breaker.stats()
        assert stats["state"] == CircuitBreaker.OPEN
        assert stats["opens"] == 1


# ------------------------------------------------------------- controller


class TestAdmissionController:
    def test_disabled_admits_everything(self):
        ctl = AdmissionController(enabled=False, max_queue_interactive=0)
        for _ in range(100):
            assert ctl.admit(INTERACTIVE) is None
        # disabled controllers do not track depth either
        assert ctl.depth(INTERACTIVE) == 0

    def test_queue_bound_sheds_with_overload_status(self):
        ctl = controller(max_queue_interactive=4)
        for _ in range(4):
            assert ctl.admit(INTERACTIVE) is None
        shed = ctl.admit(INTERACTIVE)
        assert shed is not None
        assert shed.decision == Decision.INDETERMINATE
        assert shed.operation_status.code == OVERLOAD_CODE
        assert ctl.stats()["shed_queue_full"] == 1

    def test_release_frees_slots(self):
        ctl = controller(max_queue_interactive=2)
        assert ctl.admit(INTERACTIVE) is None
        assert ctl.admit(INTERACTIVE) is None
        assert ctl.admit(INTERACTIVE) is not None
        ctl.release(INTERACTIVE, 2)
        assert ctl.admit(INTERACTIVE) is None

    def test_classes_have_independent_bounds(self):
        ctl = controller(max_queue_interactive=1, max_queue_bulk=1)
        assert ctl.admit(INTERACTIVE) is None
        assert ctl.admit(BULK) is None
        assert ctl.admit(INTERACTIVE) is not None
        assert ctl.admit(BULK) is not None

    def test_deadline_rejection_around_ewma_estimate(self):
        """The admit/reject boundary must track the batch-latency EWMA:
        budgets below PIPELINE_BATCHES * estimate * headroom reject,
        comfortable budgets admit."""
        clock = FakeClock()
        ctl = controller(deadline_headroom=1.2, time_fn=clock)
        # seed the EWMA at a stable 50 ms per batch
        for _ in range(50):
            ctl.observe_batch(INTERACTIVE, 0.050, 10)
        est = ctl.estimate(INTERACTIVE)
        assert est == pytest.approx(0.050, rel=0.05)
        infeasible = est * PIPELINE_BATCHES * 1.2 * 0.9
        shed = ctl.admit(INTERACTIVE, deadline=clock() + infeasible)
        assert shed is not None
        assert shed.operation_status.code == OVERLOAD_CODE
        assert "deadline infeasible" in shed.operation_status.message
        assert ctl.stats()["deadline_rejected"] == 1
        feasible = est * PIPELINE_BATCHES * 1.2 * 1.5
        assert ctl.admit(INTERACTIVE, deadline=clock() + feasible) is None

    def test_queue_depth_tightens_the_deadline_check(self):
        """A deep queue adds per-row wait to the estimate: the same
        budget that admits at depth 0 rejects behind a long queue."""
        clock = FakeClock()
        ctl = controller(max_queue_interactive=10_000, time_fn=clock)
        for _ in range(50):
            ctl.observe_batch(INTERACTIVE, 0.010, 10)  # 1 ms per row
        budget = 0.010 * PIPELINE_BATCHES * 1.2 + 0.020
        assert ctl.admit(INTERACTIVE, deadline=clock() + budget) is None
        for _ in range(1000):  # 1000 queued rows ~ 1 s of wait
            ctl.admit(INTERACTIVE)
        shed = ctl.admit(INTERACTIVE, deadline=clock() + budget)
        assert shed is not None
        assert "queued ahead" in shed.operation_status.message

    def test_draining_sheds_with_shutdown_status(self):
        ctl = controller()
        ctl.begin_drain()
        shed = ctl.admit(INTERACTIVE)
        assert shed is not None
        assert shed.operation_status.code == SHUTDOWN_CODE

    def test_adaptive_max_batch_slow_start_grow_and_shrink(self):
        ctl = controller(deadline_bound_ms=40.0, min_batch=8,
                         adaptive_max_batch=True)
        # slow start at the floor
        assert ctl.suggest_max_batch(4096) == 8
        # comfortable FULL batches double the cap
        target = 0.040 / (PIPELINE_BATCHES + 1)
        ctl.observe_batch(INTERACTIVE, target / 4, 8)
        assert ctl.suggest_max_batch(4096) == 16
        ctl.observe_batch(INTERACTIVE, target / 4, 16)
        assert ctl.suggest_max_batch(4096) == 32
        # an overshooting batch halves it
        ctl.observe_batch(INTERACTIVE, target * 2, 32)
        assert ctl.suggest_max_batch(4096) == 16
        # the cap never exceeds the configured max
        for _ in range(20):
            ctl.observe_batch(INTERACTIVE, target / 4,
                              ctl.suggest_max_batch(64))
        assert ctl.suggest_max_batch(64) == 64

    def test_ewma_estimate_high_tracks_jitter(self):
        ewma = LatencyEwma(alpha=0.2, default_s=0.005)
        assert ewma.estimate() == 0.005
        for _ in range(100):
            ewma.observe(0.010, 10)
        # steady stream: deviation decays toward zero
        assert ewma.estimate_high() < 0.012
        for seconds in (0.002, 0.030) * 10:
            ewma.observe(seconds, 10)
        # jittery stream: the pessimistic bound spreads well above the mean
        assert ewma.estimate_high() > ewma.estimate() * 1.5


class TestDeadlineFromContext:
    class Ctx:
        def __init__(self, remaining=None, metadata=()):
            self._remaining = remaining
            self._metadata = metadata

        def time_remaining(self):
            return self._remaining

        def invocation_metadata(self):
            return self._metadata

    def test_native_grpc_deadline(self):
        deadline = deadline_from_context(self.Ctx(remaining=1.5))
        assert deadline is not None
        assert 1.0 < deadline - time.monotonic() <= 1.5

    def test_timeout_metadata_fallback(self):
        ctx = self.Ctx(metadata=(("x-acs-timeout-ms", "250"),))
        deadline = deadline_from_context(ctx)
        assert deadline is not None
        assert 0.1 < deadline - time.monotonic() <= 0.25

    def test_no_budget_stated(self):
        assert deadline_from_context(self.Ctx()) is None

    def test_int64_max_sentinel_means_no_deadline(self):
        """grpc-python reports ~int64-max SECONDS (not None) on a call
        with no client deadline — that must read as unbounded, and must
        not mask the metadata fallback."""
        assert deadline_from_context(self.Ctx(remaining=9.2e18)) is None
        ctx = self.Ctx(remaining=9.2e18,
                       metadata=(("x-acs-timeout-ms", "250"),))
        deadline = deadline_from_context(ctx)
        assert deadline is not None
        assert 0.1 < deadline - time.monotonic() <= 0.25


# ------------------------------------------------- batcher integration


def make_batcher(evaluator, admission, **kwargs):
    kwargs.setdefault("window_ms", 1.0)
    kwargs.setdefault("min_kernel_batch", 2)
    batcher = MicroBatcher(evaluator, admission=admission, **kwargs)
    batcher.start()
    return batcher


class TestBatcherAdmission:
    def test_queue_bound_shedding_under_slow_evaluator(self):
        """A saturated batcher sheds excess submits with the overload
        status instead of queueing unboundedly; every admitted request
        still resolves with a real decision."""
        ctl = controller(max_queue_interactive=8, adaptive_max_batch=False)
        batcher = make_batcher(StubEvaluator(delay_s=0.05), ctl)
        try:
            futures = [batcher.submit(make_request(i)) for i in range(64)]
            results = [f.result(timeout=30) for f in futures]
        finally:
            batcher.stop()
        shed = [r for r in results
                if r.operation_status.code == OVERLOAD_CODE]
        served = [r for r in results if r.operation_status.code == 200]
        assert shed, "saturation never shed"
        assert served, "nothing served"
        assert len(shed) + len(served) == 64
        for r in shed:  # never a fabricated PERMIT/DENY
            assert r.decision == Decision.INDETERMINATE
        for r in served:
            assert r.decision == Decision.PERMIT

    def test_deadline_expired_rows_dropped_at_dispatch(self):
        """Rows whose deadline passes while queued resolve with the
        deadline status instead of being evaluated after abandonment."""
        ctl = controller()
        evaluator = StubEvaluator(delay_s=0.15)
        batcher = make_batcher(evaluator, ctl)
        try:
            # the first submit occupies the eval worker; the deadlined one
            # expires while waiting behind it
            blocker = batcher.submit(make_request(0))
            time.sleep(0.02)  # let the first batch dispatch
            doomed = batcher.submit(
                make_request(1), deadline=time.monotonic() + 0.03
            )
            response = doomed.result(timeout=10)
            blocker.result(timeout=10)
        finally:
            batcher.stop()
        assert response.decision == Decision.INDETERMINATE
        assert response.operation_status.code == DEADLINE_CODE
        assert ctl.stats()["deadline_expired"] >= 1

    def test_two_class_fairness_under_interactive_saturation(self):
        """Bulk (whatIsAllowed) work keeps progressing while interactive
        traffic saturates the collector: the fairness interval guarantees
        a bulk round every bulk_interval interactive rounds."""
        ctl = controller(bulk_interval=4, adaptive_max_batch=False)
        evaluator = StubEvaluator(delay_s=0.005)
        batcher = make_batcher(evaluator, ctl, max_batch=16)
        stop_pump = threading.Event()

        def pump_interactive():
            while not stop_pump.is_set():
                batcher.submit(make_request())
                time.sleep(0.0005)

        pump = threading.Thread(target=pump_interactive)
        pump.start()
        try:
            time.sleep(0.05)  # interactive saturation established
            bulk = [batcher.submit_reverse(make_request(i))
                    for i in range(8)]
            results = [f.result(timeout=15) for f in bulk]
        finally:
            stop_pump.set()
            pump.join()
            batcher.stop()
        assert all(isinstance(rq, ReverseQuery) for rq in results)
        assert all(rq.operation_status.code == 200 for rq in results)
        assert evaluator.bulk_batches, "bulk starved"

    def test_bulk_sheds_when_bulk_queue_full(self):
        ctl = controller(max_queue_bulk=2)
        batcher = make_batcher(StubEvaluator(delay_s=0.05), ctl)
        try:
            futures = [batcher.submit_reverse(make_request(i))
                       for i in range(16)]
            results = [f.result(timeout=15) for f in futures]
        finally:
            batcher.stop()
        assert any(rq.operation_status.code == OVERLOAD_CODE
                   for rq in results)
        assert any(rq.operation_status.code == 200 for rq in results)

    def test_drain_on_shutdown_flushes_admitted_then_fails_queued(self):
        """stop(): admitted work is flushed to completion within the
        drain deadline; what cannot flush resolves with the distinct
        shutdown status — no future is ever left hanging."""
        ctl = controller(adaptive_max_batch=False)
        evaluator = StubEvaluator(delay_s=0.3)
        batcher = make_batcher(evaluator, ctl, max_batch=4)
        futures = [batcher.submit(make_request(i)) for i in range(32)]
        time.sleep(0.05)  # first batches in flight
        batcher.stop(drain_s=0.5)
        # every future resolved — served, or failed with shutdown status
        codes = [f.result(timeout=1).operation_status.code
                 for f in futures]
        assert all(code in (200, SHUTDOWN_CODE) for code in codes)
        assert 200 in codes, "nothing flushed during drain"
        assert SHUTDOWN_CODE in codes, "drain deadline never cut anything"
        # post-stop submits shed immediately with the shutdown status
        late = batcher.submit(make_request()).result(timeout=1)
        assert late.operation_status.code == SHUTDOWN_CODE

    def test_admission_disabled_preserves_legacy_paths(self):
        """With no controller the batcher behaves exactly as before:
        unbounded queue, no deadline logic on the hot path."""
        evaluator = StubEvaluator()
        batcher = MicroBatcher(evaluator, window_ms=1.0, min_kernel_batch=2)
        batcher.start()
        try:
            futures = [batcher.submit(make_request(i)) for i in range(32)]
            assert all(
                f.result(timeout=10).decision == Decision.PERMIT
                for f in futures
            )
        finally:
            batcher.stop()


# ------------------------------------------------- breaker integration


class TestAdapterBreaker:
    def _adapter(self, breaker, fail: dict):
        calls = []

        def transport(url, body, headers):
            calls.append(1)
            if fail["on"]:
                raise ContextQueryTransportError(503, "down")
            return b'{"data": {"op": {"details": [{"payload": {"id": 1}}]}}}'

        adapter = GraphQLAdapter(
            "http://example/graphql", transport=transport,
            retry_transient=False, breaker=breaker,
        )
        cq = type("CQ", (), {"query": "query q", "filters": []})()
        request = Request(target=Target(), context={"resources": []})
        return adapter, cq, request, calls

    def test_breaker_opens_and_fails_fast_then_recovers(self):
        clock = FakeClock()
        breaker = CircuitBreaker("adapter", min_volume=4, open_s=1.0,
                                 time_fn=clock)
        fail = {"on": True}
        adapter, cq, request, calls = self._adapter(breaker, fail)
        for _ in range(4):
            with pytest.raises(ContextQueryTransportError):
                adapter.query(cq, request)
        assert breaker.state == CircuitBreaker.OPEN
        n_transport = len(calls)
        # open circuit: transport is never touched — the row fails fast
        # down the existing deny/oracle degradation ladder
        with pytest.raises(ContextQueryTransportError) as err:
            adapter.query(cq, request)
        assert len(calls) == n_transport
        assert err.value.code == 503
        # recovery: the upstream heals, the jittered cooldown elapses,
        # one probe closes the circuit
        fail["on"] = False
        clock.advance(2.0)
        assert adapter.query(cq, request) == [{"id": 1}]
        assert breaker.state == CircuitBreaker.CLOSED

    def test_definitive_4xx_counts_as_breaker_success(self):
        breaker = CircuitBreaker("adapter", min_volume=2,
                                 time_fn=FakeClock())

        def transport(url, body, headers):
            raise ContextQueryTransportError(404, "no such resource")

        adapter = GraphQLAdapter(
            "http://example/graphql", transport=transport,
            retry_transient=False, breaker=breaker,
        )
        cq = type("CQ", (), {"query": "query q", "filters": []})()
        request = Request(target=Target(), context={"resources": []})
        for _ in range(8):
            with pytest.raises(ContextQueryTransportError):
                adapter.query(cq, request)
        # the upstream IS answering: 4xx must never trip the breaker
        assert breaker.state == CircuitBreaker.CLOSED


class TestIdentityBreaker:
    class FlakyInner:
        def __init__(self):
            self.fail = True
            self.calls = 0

        def find_by_token(self, token):
            self.calls += 1
            if self.fail:
                raise ConnectionError("identity down")
            return {"payload": {"id": "u1"},
                    "status": {"code": 200, "message": "ok"}}

    def test_breaker_opens_and_resolution_degrades_per_row(self):
        clock = FakeClock()
        breaker = CircuitBreaker("identity", min_volume=4, open_s=1.0,
                                 time_fn=clock)
        inner = self.FlakyInner()
        client = CachingIdentityClient(inner, breaker=breaker)
        for _ in range(4):
            with pytest.raises(ConnectionError):
                client.find_by_token("tok")
        assert breaker.state == CircuitBreaker.OPEN
        # open circuit: fast 5xx envelope, no inner call — the row
        # degrades to token-unresolved, and 5xx is never cached so
        # recovery is immediate
        n_calls = inner.calls
        out = client.find_by_token("tok")
        assert inner.calls == n_calls
        assert out["payload"] is None
        assert out["status"]["code"] == 503
        assert "circuit open" in out["status"]["message"]
        # recovery closes through one healthy probe
        inner.fail = False
        clock.advance(2.0)
        out = client.find_by_token("tok")
        assert out["payload"] == {"id": "u1"}
        assert breaker.state == CircuitBreaker.CLOSED

    def test_definitive_404_counts_as_breaker_success(self):
        breaker = CircuitBreaker("identity", min_volume=2,
                                 time_fn=FakeClock())
        client = CachingIdentityClient(StaticIdentityClient(),
                                       breaker=breaker)
        for i in range(8):
            out = client.find_by_token(f"unknown-{i}")
            assert out["payload"] is None
        assert breaker.state == CircuitBreaker.CLOSED


# ------------------------------------------ worker-level differential


class TestWorkerDifferential:
    """Admitted requests must produce BYTE-identical decisions to a
    no-admission run — admission decides WHETHER a request is evaluated,
    never WHAT the decision is."""

    def _responses(self, admission_enabled, faults_block=None):
        from access_control_srv_tpu.srv import Worker
        from access_control_srv_tpu.srv.transport_grpc import (
            response_to_pb,
            reverse_query_to_pb,
        )

        cfg = seed_cfg()
        cfg["admission"] = {"enabled": admission_enabled}
        if faults_block is not None:
            cfg["faults"] = faults_block
        worker = Worker().start(cfg)
        try:
            requests = [admin_request(), admin_request(role="nobody"),
                        admin_request()]
            single = [
                response_to_pb(
                    worker.service.is_allowed(r)
                ).SerializeToString()
                for r in requests
            ]
            batch = [
                response_to_pb(r).SerializeToString()
                for r in worker.service.is_allowed_batch(
                    [admin_request(), admin_request(role="nobody")]
                )
            ]
            reverse = reverse_query_to_pb(
                worker.service.what_is_allowed(admin_request())
            ).SerializeToString()
        finally:
            worker.stop()
        return single, batch, reverse

    def test_admitted_decisions_byte_identical_to_no_admission(self):
        with_admission = self._responses(True)
        without = self._responses(False)
        assert with_admission == without

    def test_disabled_failpoints_leave_serving_byte_identical(self):
        """A faults block that is present but disabled must not perturb
        a single response byte — the failpoint framework is OFF by
        default and configure_from leaves the registry disarmed."""
        from access_control_srv_tpu.srv.faults import REGISTRY

        armed = self._responses(True, faults_block={
            "enabled": False,
            "seed": 7,
            "points": [
                {"site": "device.dispatch", "action": "error"},
                {"site": "broker.journal.write", "action": "torn"},
            ],
        })
        assert REGISTRY.enabled is False
        assert armed == self._responses(True)


# --------------------------------------------------- degraded envelope


class TestDegradedStatus:
    """The device-health envelope (admission.degraded_response) is a
    distinct honest 503: INDETERMINATE decision, never cacheable, never
    a fabricated PERMIT/DENY — and separable from the load-shed and
    drain envelopes that share the 5xx band."""

    def test_envelope_shape_and_distinct_from_shed(self):
        from access_control_srv_tpu.srv.admission import (
            DEGRADED_CODE,
            degraded_response,
            overload_response,
        )

        resp = degraded_response("device materialize timed out")
        assert resp.operation_status.code == DEGRADED_CODE == 503
        assert resp.decision == Decision.INDETERMINATE
        assert resp.evaluation_cacheable is False
        assert resp.operation_status.message.startswith("degraded")
        # shed and drain envelopes never carry the degraded marker: an
        # operator (or the router's retry policy) can tell device-health
        # 503s from load 503s by message
        for code, msg in ((OVERLOAD_CODE, "queue full"),
                          (SHUTDOWN_CODE, "draining")):
            shed = overload_response(code, msg)
            assert "degraded" not in shed.operation_status.message

    def test_degraded_rows_are_never_cached(self):
        from access_control_srv_tpu.srv.admission import degraded_response
        from access_control_srv_tpu.srv.decision_cache import DecisionCache

        cache = DecisionCache(ttl_s=60.0, max_entries=16)
        stored = cache.put("k-degraded", degraded_response("quarantined"),
                           epoch=cache.epoch)
        assert stored is False
        assert cache.get("k-degraded") is None

    def test_hang_fallback_ladder_is_honest(self):
        """Per-row resolution after a device timeout: expired rows shed
        504, oracle-answerable rows get a REAL evaluation, rows the
        oracle cannot answer get the degraded envelope — no row is ever
        a fabricated PERMIT/DENY."""
        from access_control_srv_tpu.srv.admission import DEGRADED_CODE
        from access_control_srv_tpu.srv.evaluator import HybridEvaluator

        permit = Response(
            decision=Decision.PERMIT, obligations=[],
            evaluation_cacheable=True,
            operation_status=OperationStatus(code=200, message=""),
        )
        counted = {}

        class Shim:
            _hang_fallback = HybridEvaluator._hang_fallback

            def _expired_rows(self, requests):
                return {0}

            def _oracle_is_allowed(self, request):
                if getattr(request, "broken", False):
                    raise RuntimeError("oracle cannot resolve")
                return permit

            def _count_path(self, path, rows):
                counted[path] = counted.get(path, 0) + rows

            class _slog:
                @staticmethod
                def warning(*args, **kwargs):
                    pass

        class Row:
            def __init__(self, broken=False):
                self.broken = broken

        rows = [Row(), Row(), Row(broken=True)]
        out = Shim()._hang_fallback(rows)
        assert out[0].operation_status.code == DEADLINE_CODE
        assert out[1] is permit
        assert out[2].operation_status.code == DEGRADED_CODE
        assert out[2].decision == Decision.INDETERMINATE
        assert out[2].evaluation_cacheable is False
        assert "degraded" in out[2].operation_status.message
        assert counted == {"hang-fallback-oracle": 1,
                           "hang-fallback-degraded": 1,
                           "deadline-expired": 1}


class TestBrokerFsyncInterval:
    def test_fsync_every_record_preserves_journal_semantics(self, tmp_path):
        """fsync_interval_s=0 (fsync per record) must keep journal replay
        byte-for-byte equivalent to the flush-only default."""
        from access_control_srv_tpu.srv.broker import (
            BrokerServer,
            SocketEventBus,
        )

        data_dir = str(tmp_path / "broker-data")
        server = BrokerServer(data_dir=data_dir, fsync_interval_s=0)
        server.start()
        bus = SocketEventBus(server.address)
        topic = bus.topic("t.fsync")
        for i in range(5):
            topic.emit("evt", {"i": i})
        bus.close()
        server.stop()
        # cold restart replays the fsynced journal
        server2 = BrokerServer(data_dir=data_dir).start()
        bus2 = SocketEventBus(server2.address)
        events = bus2.topic("t.fsync").read(0)
        bus2.close()
        server2.stop()
        assert [m["i"] for _, m in events] == list(range(5))

    def test_default_is_flush_only(self, tmp_path):
        from access_control_srv_tpu.srv.broker import BrokerServer

        server = BrokerServer(data_dir=str(tmp_path / "d"))
        assert server.fsync_interval_s is None
        server.start()
        server.stop()


# ------------------------------------------------- audit-sweep starvation


class TestAuditSweepStarvation:
    """Two-class fairness exercised by a REAL audit sweep job (the
    bulk-class producer from srv/audit_sweep.py), not synthetic wia
    singles — both starvation directions."""

    def _manager(self, batcher, tmp_path, **kw):
        from access_control_srv_tpu.srv.audit_sweep import AuditSweepManager

        kw.setdefault("out_dir", str(tmp_path))
        return AuditSweepManager(batcher.evaluator, batcher=batcher, **kw)

    def _spec(self, n):
        from access_control_srv_tpu.ops.lattice import LatticeSpec

        return LatticeSpec.stress(n, n, actions=("read",))

    def test_saturating_sweep_cannot_starve_interactive(self, tmp_path):
        """While a full-lattice sweep saturates the bulk queue, every
        admitted interactive request still resolves 200 with p99 well
        inside the interactive deadline bound (BASELINE.md
        audit-fairness: p99 <= 500 ms with a 5 ms device step)."""
        ctl = controller(bulk_interval=4, adaptive_max_batch=False)
        evaluator = StubEvaluator(delay_s=0.005)
        batcher = make_batcher(evaluator, ctl, max_batch=64)
        manager = self._manager(batcher, tmp_path, chunk_size=64)
        try:
            job = manager.start_sweep(spec=self._spec(48))  # 2304 cells
            deadline = time.monotonic() + 10
            while not evaluator.bulk_batches:
                assert time.monotonic() < deadline, "sweep never dispatched"
                time.sleep(0.002)
            latencies = []
            for i in range(40):
                t0 = time.monotonic()
                response = batcher.submit(make_request(i)).result(timeout=15)
                latencies.append(time.monotonic() - t0)
                assert response.operation_status.code == 200
                assert response.decision == Decision.PERMIT
            assert job.state in ("running", "done")
            latencies.sort()
            p99 = latencies[int(len(latencies) * 0.99) - 1]
            assert p99 <= 0.5, (
                f"interactive p99 {p99 * 1e3:.0f}ms blew the fairness "
                "bound while the sweep ran"
            )
            # the sweep genuinely saturated bulk during the measurement
            assert sum(evaluator.bulk_batches) >= 64
        finally:
            manager.stop()
            batcher.stop()

    def test_interactive_flood_cannot_starve_sweep(self, tmp_path):
        """The reverse direction: an interactive flood saturates the
        collector, yet bulk_interval still guarantees sweep progress —
        the job runs to completion under sustained interactive load."""
        ctl = controller(bulk_interval=4, adaptive_max_batch=False)
        evaluator = StubEvaluator(delay_s=0.002)
        batcher = make_batcher(evaluator, ctl, max_batch=16)
        manager = self._manager(batcher, tmp_path, chunk_size=16)
        stop_pump = threading.Event()

        def pump_interactive():
            while not stop_pump.is_set():
                batcher.submit(make_request())
                time.sleep(0.0005)

        pump = threading.Thread(target=pump_interactive)
        pump.start()
        try:
            time.sleep(0.05)  # saturation established before the sweep
            job = manager.start_sweep(spec=self._spec(8))  # 64 cells
            assert job.wait(30), "sweep starved under interactive flood"
            assert job.state == "done"
            assert job.cells_done == 64
            assert job.sheds == 0, "fairness must not rely on shedding"
            assert evaluator.bulk_batches, "bulk never dispatched"
        finally:
            stop_pump.set()
            pump.join()
            manager.stop()
            batcher.stop()
