"""Cross-process event/cache backend (VERDICT r2 missing #2 / item 8).

The reference's HR-scope rendezvous is genuinely inter-process: the PDP
parks a promise on a Kafka request and a DIFFERENT process produces the
response (accessController.ts:753-767, worker.ts:252-299), with Redis as
the shared cache.  These tests run that shape for real: a TCP broker
(srv/broker.py), a Worker wired to it, and a separate OS process
(subprocess) acting as the authentication responder."""

import json
import os
import subprocess
import sys
import time

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.srv.broker import (
    BrokerServer,
    SocketEventBus,
    SocketOffsetStore,
    SocketSubjectCache,
)
from access_control_srv_tpu.srv.worker import Worker

from .utils import URNS, build_request

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RESPONDER = """
import json, sys
sys.path.insert(0, {repo!r})
from access_control_srv_tpu.srv.broker import SocketEventBus

bus = SocketEventBus({address!r})
auth = bus.topic("io.restorecommerce.authentication")

def respond(event_name, message, ctx):
    if event_name != "hierarchicalScopesRequest":
        return
    auth.emit("hierarchicalScopesResponse", {{
        "token": message["token"],
        "subject_id": "ada",
        "interactive": True,
        "hierarchical_scopes": [{{"id": "OrgX"}}],
    }})
    print("responded", flush=True)

auth.on(respond)
print("ready", flush=True)
import time
time.sleep(30)
"""


@pytest.fixture()
def broker():
    server = BrokerServer().start()
    yield server
    server.stop()


def test_bus_roundtrip_across_connections(broker):
    a = SocketEventBus(broker.address)
    b = SocketEventBus(broker.address)
    got = []
    b.topic("t1").on(lambda e, m, ctx: got.append((e, m, ctx["offset"])))
    time.sleep(0.1)
    off = a.topic("t1").emit("ping", {"x": 1})
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    assert got == [("ping", {"x": 1}, off)]
    assert a.topic("t1").read() == [("ping", {"x": 1})]
    a.close()
    b.close()


def test_replay_from_offset(broker):
    a = SocketEventBus(broker.address)
    t = a.topic("t2")
    for i in range(5):
        t.emit("e", i)
    got = []
    b = SocketEventBus(broker.address)
    b.topic("t2").on(lambda e, m, ctx: got.append(m), starting_offset=3)
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert got == [3, 4]
    a.close()
    b.close()


def test_shared_cache_and_offsets(broker):
    c1 = SocketSubjectCache(broker.address)
    c2 = SocketSubjectCache(broker.address)
    c1.set("cache:ada:hrScopes", [{"id": "Org1"}])
    assert c2.get("cache:ada:hrScopes") == [{"id": "Org1"}]
    assert c2.exists("cache:ada:hrScopes")
    assert c2.evict_prefix("cache:ada:") == 1
    assert not c1.exists("cache:ada:hrScopes")

    o1 = SocketOffsetStore(broker.address)
    o2 = SocketOffsetStore(broker.address)
    o1.commit("topic-a", 41)
    assert o2.get("topic-a") == 41
    assert o2.get("missing") is None
    for x in (c1, c2, o1, o2):
        x.close()


def test_hr_rendezvous_across_os_processes(broker):
    """The suite-3 rendezvous with the responder in a REAL child process:
    PDP parks on the broker-backed auth topic; the child consumes the
    request over TCP and produces the response; the decision resolves."""
    responder = subprocess.Popen(
        [sys.executable, "-c",
         RESPONDER.format(repo=REPO, address=broker.address)],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert responder.stdout.readline().strip() == "ready"

        worker = Worker().start(
            {
                "policies": {"type": "database"},
                "seed_data": {
                    "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                    "policies": os.path.join(SEED, "policies.yaml"),
                    "rules": os.path.join(SEED, "rules.yaml"),
                },
                "events": {"broker": {"address": broker.address}},
                "authorization": {"hrReqTimeout": 10_000},
            }
        )
        try:
            worker.identity_client.register(
                "xp-tok-1",
                {
                    "id": "ada",
                    "tokens": [{"token": "xp-tok-1", "interactive": True}],
                    "role_associations": [
                        {"role": "superadministrator-r-id", "attributes": []}
                    ],
                },
            )
            request = build_request(
                subject_id="ada", subject_role="superadministrator-r-id",
                resource_type=ORG, resource_id="O1",
                action_type=URNS["read"],
            )
            request.context["subject"] = {"token": "xp-tok-1"}
            response = worker.service.is_allowed(request)
            assert response.decision == Decision.PERMIT
            # the scopes were written to the SHARED cache by this process's
            # response handler after the child produced them
            assert worker.subject_cache.get("cache:ada:hrScopes") == [
                {"id": "OrgX"}
            ]
        finally:
            worker.stop()
    finally:
        responder.kill()
        responder.wait()


def test_broker_survives_bad_frames_and_disconnects(broker):
    """Malformed frames get an error reply; abrupt disconnects of RPC and
    subscription connections leave the broker serving."""
    import socket as socketlib

    host, port = broker.address.rsplit(":", 1)
    raw = socketlib.create_connection((host, int(port)))
    raw.sendall(b"not json\n")
    assert b"error" in raw.makefile("rb").readline()
    raw.close()  # abrupt close mid-connection

    sub = SocketEventBus(broker.address)
    sub.topic("t_err").on(lambda e, m, c: None)
    time.sleep(0.05)
    sub.close()  # kills the subscription stream abruptly

    bus = SocketEventBus(broker.address)
    assert bus.topic("t_err").emit("still-alive", 1) == 0
    assert bus.topic("t_err").read() == [("still-alive", 1)]
    bus.close()


def test_worker_serving_under_broker_and_hot_mutation(broker):
    """Bounded soak: gRPC decision traffic races policy CRUD while the
    worker runs on the cross-process broker backend — every response is
    a valid old-tree/new-tree decision, never an error."""
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
            "events": {"broker": {"address": broker.address}},
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        import threading

        from .utils import URNS as U

        errors = []
        stop = False

        def msg():
            m = pb.Request()
            m.target.subjects.add(id=U["role"],
                                  value="superadministrator-r-id")
            m.target.resources.add(id=U["entity"], value=ORG)
            m.target.actions.add(id=U["actionID"], value=U["read"])
            m.context.subject.value = json.dumps({
                "id": "root",
                "role_associations": [
                    {"role": "superadministrator-r-id", "attributes": []}
                ],
                "hierarchical_scopes": [],
            }).encode()
            return m

        def serve():
            while not stop:
                resp = client.is_allowed(msg())
                if resp.decision != pb.PERMIT:
                    errors.append(resp)
                    return

        threads = [threading.Thread(target=serve) for _ in range(3)]
        for t in threads:
            t.start()
        rules = worker.store.get_resource_service("rule")
        for i in range(15):
            rules.create([{"id": f"soak{i}", "name": f"soak{i}",
                           "effect": "PERMIT",
                           "target": {"subjects": [
                               {"id": U["role"], "value": f"soak-role-{i}"}
                           ]}}])
            rules.delete(ids=[f"soak{i}"])
        stop = True
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:1]
    finally:
        client.close()
        server.stop()
        worker.stop()


def test_dead_subscriber_reaped_on_idle_topic(broker):
    """A subscriber that disconnects while its topic is idle must be
    reaped by the stream heartbeat — not pinned in q.get() until the next
    emit (dead queues+threads would otherwise accumulate forever)."""
    import access_control_srv_tpu.srv.broker as brokermod
    from access_control_srv_tpu.srv.broker import SocketEventBus

    old = brokermod.HEARTBEAT_INTERVAL
    brokermod.HEARTBEAT_INTERVAL = 0.2
    try:
        bus = SocketEventBus(broker.address)
        bus.topic("idle-topic").on(lambda e, m, c: None)
        deadline = time.time() + 5
        while time.time() < deadline and not broker._subscribers.get("idle-topic"):
            time.sleep(0.05)
        assert len(broker._subscribers.get("idle-topic", [])) == 1
        bus.close()  # shutdown() actually tears the stream connection
        deadline = time.time() + 10
        while time.time() < deadline and broker._subscribers.get("idle-topic"):
            time.sleep(0.1)
        assert not broker._subscribers.get("idle-topic")
    finally:
        brokermod.HEARTBEAT_INTERVAL = old
