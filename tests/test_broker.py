"""Cross-process event/cache backend (VERDICT r2 missing #2 / item 8).

The reference's HR-scope rendezvous is genuinely inter-process: the PDP
parks a promise on a Kafka request and a DIFFERENT process produces the
response (accessController.ts:753-767, worker.ts:252-299), with Redis as
the shared cache.  These tests run that shape for real: a TCP broker
(srv/broker.py), a Worker wired to it, and a separate OS process
(subprocess) acting as the authentication responder."""

import json
import os
import subprocess
import sys
import time

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.srv.broker import (
    BrokerServer,
    SocketEventBus,
    SocketOffsetStore,
    SocketSubjectCache,
)
from access_control_srv_tpu.srv.worker import Worker

from .utils import URNS, build_request

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RESPONDER = """
import json, sys
sys.path.insert(0, {repo!r})
from access_control_srv_tpu.srv.broker import SocketEventBus

bus = SocketEventBus({address!r})
auth = bus.topic("io.restorecommerce.authentication")

def respond(event_name, message, ctx):
    if event_name != "hierarchicalScopesRequest":
        return
    auth.emit("hierarchicalScopesResponse", {{
        "token": message["token"],
        "subject_id": "ada",
        "interactive": True,
        "hierarchical_scopes": [{{"id": "OrgX"}}],
    }})
    print("responded", flush=True)

auth.on(respond)
print("ready", flush=True)
import time
time.sleep(30)
"""


@pytest.fixture()
def broker():
    server = BrokerServer().start()
    yield server
    server.stop()


def test_bus_roundtrip_across_connections(broker):
    a = SocketEventBus(broker.address)
    b = SocketEventBus(broker.address)
    got = []
    b.topic("t1").on(lambda e, m, ctx: got.append((e, m, ctx["offset"])))
    time.sleep(0.1)
    off = a.topic("t1").emit("ping", {"x": 1})
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    assert got == [("ping", {"x": 1}, off)]
    assert a.topic("t1").read() == [("ping", {"x": 1})]
    a.close()
    b.close()


def test_replay_from_offset(broker):
    a = SocketEventBus(broker.address)
    t = a.topic("t2")
    for i in range(5):
        t.emit("e", i)
    got = []
    b = SocketEventBus(broker.address)
    b.topic("t2").on(lambda e, m, ctx: got.append(m), starting_offset=3)
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert got == [3, 4]
    a.close()
    b.close()


def test_shared_cache_and_offsets(broker):
    c1 = SocketSubjectCache(broker.address)
    c2 = SocketSubjectCache(broker.address)
    c1.set("cache:ada:hrScopes", [{"id": "Org1"}])
    assert c2.get("cache:ada:hrScopes") == [{"id": "Org1"}]
    assert c2.exists("cache:ada:hrScopes")
    assert c2.evict_prefix("cache:ada:") == 1
    assert not c1.exists("cache:ada:hrScopes")

    o1 = SocketOffsetStore(broker.address)
    o2 = SocketOffsetStore(broker.address)
    o1.commit("topic-a", 41)
    assert o2.get("topic-a") == 41
    assert o2.get("missing") is None
    for x in (c1, c2, o1, o2):
        x.close()


def test_hr_rendezvous_across_os_processes(broker):
    """The suite-3 rendezvous with the responder in a REAL child process:
    PDP parks on the broker-backed auth topic; the child consumes the
    request over TCP and produces the response; the decision resolves."""
    responder = subprocess.Popen(
        [sys.executable, "-c",
         RESPONDER.format(repo=REPO, address=broker.address)],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert responder.stdout.readline().strip() == "ready"

        worker = Worker().start(
            {
                "policies": {"type": "database"},
                "seed_data": {
                    "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                    "policies": os.path.join(SEED, "policies.yaml"),
                    "rules": os.path.join(SEED, "rules.yaml"),
                },
                "events": {"broker": {"address": broker.address}},
                "authorization": {"hrReqTimeout": 10_000},
            }
        )
        try:
            worker.identity_client.register(
                "xp-tok-1",
                {
                    "id": "ada",
                    "tokens": [{"token": "xp-tok-1", "interactive": True}],
                    "role_associations": [
                        {"role": "superadministrator-r-id", "attributes": []}
                    ],
                },
            )
            request = build_request(
                subject_id="ada", subject_role="superadministrator-r-id",
                resource_type=ORG, resource_id="O1",
                action_type=URNS["read"],
            )
            request.context["subject"] = {"token": "xp-tok-1"}
            response = worker.service.is_allowed(request)
            assert response.decision == Decision.PERMIT
            # the scopes were written to the SHARED cache by this process's
            # response handler after the child produced them
            assert worker.subject_cache.get("cache:ada:hrScopes") == [
                {"id": "OrgX"}
            ]
        finally:
            worker.stop()
    finally:
        responder.kill()
        responder.wait()


def test_broker_survives_bad_frames_and_disconnects(broker):
    """Malformed frames get an error reply; abrupt disconnects of RPC and
    subscription connections leave the broker serving."""
    import socket as socketlib

    host, port = broker.address.rsplit(":", 1)
    raw = socketlib.create_connection((host, int(port)))
    raw.sendall(b"not json\n")
    assert b"error" in raw.makefile("rb").readline()
    raw.close()  # abrupt close mid-connection

    sub = SocketEventBus(broker.address)
    sub.topic("t_err").on(lambda e, m, c: None)
    time.sleep(0.05)
    sub.close()  # kills the subscription stream abruptly

    bus = SocketEventBus(broker.address)
    assert bus.topic("t_err").emit("still-alive", 1) == 0
    assert bus.topic("t_err").read() == [("still-alive", 1)]
    bus.close()


def test_worker_serving_under_broker_and_hot_mutation(broker):
    """Bounded soak: gRPC decision traffic races policy CRUD while the
    worker runs on the cross-process broker backend — every response is
    a valid old-tree/new-tree decision, never an error."""
    from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
    from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
            "events": {"broker": {"address": broker.address}},
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        import threading

        from .utils import URNS as U

        errors = []
        stop = False

        def msg():
            m = pb.Request()
            m.target.subjects.add(id=U["role"],
                                  value="superadministrator-r-id")
            m.target.resources.add(id=U["entity"], value=ORG)
            m.target.actions.add(id=U["actionID"], value=U["read"])
            m.context.subject.value = json.dumps({
                "id": "root",
                "role_associations": [
                    {"role": "superadministrator-r-id", "attributes": []}
                ],
                "hierarchical_scopes": [],
            }).encode()
            return m

        def serve():
            while not stop:
                resp = client.is_allowed(msg())
                if resp.decision != pb.PERMIT:
                    errors.append(resp)
                    return

        threads = [threading.Thread(target=serve) for _ in range(3)]
        for t in threads:
            t.start()
        rules = worker.store.get_resource_service("rule")
        for i in range(15):
            rules.create([{"id": f"soak{i}", "name": f"soak{i}",
                           "effect": "PERMIT",
                           "target": {"subjects": [
                               {"id": U["role"], "value": f"soak-role-{i}"}
                           ]}}])
            rules.delete(ids=[f"soak{i}"])
        stop = True
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:1]
    finally:
        client.close()
        server.stop()
        worker.stop()


def test_dead_subscriber_reaped_on_idle_topic(broker):
    """A subscriber that disconnects while its topic is idle must be
    reaped by the stream heartbeat — not pinned in q.get() until the next
    emit (dead queues+threads would otherwise accumulate forever)."""
    import access_control_srv_tpu.srv.broker as brokermod
    from access_control_srv_tpu.srv.broker import SocketEventBus

    old = brokermod.HEARTBEAT_INTERVAL
    brokermod.HEARTBEAT_INTERVAL = 0.2
    try:
        bus = SocketEventBus(broker.address)
        bus.topic("idle-topic").on(lambda e, m, c: None)
        deadline = time.time() + 5
        while time.time() < deadline and not broker._subscribers.get("idle-topic"):
            time.sleep(0.05)
        assert len(broker._subscribers.get("idle-topic", [])) == 1
        bus.close()  # shutdown() actually tears the stream connection
        deadline = time.time() + 10
        while time.time() < deadline and broker._subscribers.get("idle-topic"):
            time.sleep(0.1)
        assert not broker._subscribers.get("idle-topic")
    finally:
        brokermod.HEARTBEAT_INTERVAL = old


def test_kill_and_restart_resumes_from_journal(tmp_path):
    """Durability: a broker restarted on the same data_dir replays its
    journal — topic logs, committed consumer offsets and the KV store all
    survive, and a subscriber resuming from its committed offset sees
    exactly the uncommitted tail (reference: offsets resumed per topic at
    subscribe, src/worker.ts:123,354-361)."""
    data_dir = str(tmp_path / "broker-data")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        topic = bus.topic("durable.topic")
        for i in range(5):
            topic.emit("thing", {"i": i})
        offsets = SocketOffsetStore(server.address)
        offsets.commit("durable.topic", 3)
        cache = SocketSubjectCache(server.address)
        cache.set("cache:u1:subject", {"id": "u1"})
        cache.set("cache:gone:subject", {"id": "gone"})
        cache.evict_prefix("cache:gone")
        bus.close(); offsets.close(); cache.close()
    finally:
        server.stop()

    # cold restart on the same journal (fresh port)
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server2.address)
        topic = bus.topic("durable.topic")
        assert topic.offset == 5
        assert [m["i"] for _, m in topic.read(0)] == [0, 1, 2, 3, 4]
        offsets = SocketOffsetStore(server2.address)
        assert offsets.get("durable.topic") == 3
        cache = SocketSubjectCache(server2.address)
        assert cache.get("cache:u1:subject") == {"id": "u1"}
        assert not cache.exists("cache:gone:subject")

        # resume from the committed offset: replay 3..4, then live
        got = []
        topic.on(lambda e, m, ctx: got.append((m["i"], ctx["offset"])),
                 starting_offset=offsets.get("durable.topic"))
        topic.emit("thing", {"i": 5})
        deadline = time.time() + 5
        while len(got) < 3 and time.time() < deadline:
            time.sleep(0.02)
        assert got == [(3, 3), (4, 4), (5, 5)]
        bus.close(); offsets.close(); cache.close()
    finally:
        server2.stop()


def test_journal_skips_torn_tail(tmp_path):
    data_dir = str(tmp_path / "broker-data")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        bus.topic("t").emit("a", {"n": 1})
        bus.close()
    finally:
        server.stop()
    # simulate a crash mid-append
    with open(os.path.join(data_dir, "broker.journal"), "a") as fh:
        fh.write('{"k": "emit", "t": "t", "e": "b"')
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server2.address)
        assert bus.topic("t").read(0) == [("a", {"n": 1})]
        bus.close()
    finally:
        server2.stop()


def test_broker_auth_rejects_and_accepts():
    server = BrokerServer(secret="hunter2").start()
    try:
        unauthed = SocketSubjectCache(server.address)  # no secret
        with pytest.raises(ConnectionError, match="auth"):
            unauthed.get("k")
        with pytest.raises(ConnectionError, match="auth"):
            SocketSubjectCache(server.address, secret="wrong")
        cache = SocketSubjectCache(server.address, secret="hunter2")
        cache.set("k", 1)
        assert cache.get("k") == 1
        cache.close()

        bus = SocketEventBus(server.address, secret="hunter2")
        topic = bus.topic("authed.topic")
        got = []
        topic.on(lambda e, m, ctx: got.append(m))
        topic.emit("ev", {"x": 1})
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.02)
        assert got == [{"x": 1}]
        bus.close()
    finally:
        server.stop()


def test_worker_config_passes_broker_secret(tmp_path):
    server = BrokerServer(secret="s3cr3t").start()
    try:
        worker = Worker().start(
            {
                "events": {"broker": {"address": server.address,
                                      "secret": "s3cr3t"}},
                "policies": {"type": "database"},
            }
        )
        worker.bus.topic("x").emit("ping", {"ok": True})
        assert worker.bus.topic("x").read(0) == [("ping", {"ok": True})]
        worker.stop()
        # and a wrong secret fails fast at startup
        with pytest.raises(ConnectionError, match="auth"):
            Worker().start(
                {
                    "events": {"broker": {"address": server.address,
                                          "secret": "nope"}},
                    "policies": {"type": "database"},
                }
            )
    finally:
        server.stop()


def test_two_workers_share_one_policy_state(broker):
    """Multi-worker shared mutable policy state (the reference's
    shared-Arango role, src/resourceManager.ts hot-sync over shared
    persistence): CRUD on worker A becomes decision-visible on worker B
    without restart, via the broker's journaled CRUD topic log."""
    from .utils import URNS as U
    from access_control_srv_tpu.models import Attribute, Request, Target

    def make():
        return Worker().start({
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
            "events": {"broker": {"address": broker.address}},
        })

    def req(role):
        return Request(
            target=Target(
                subjects=[Attribute(id=U["role"], value=role),
                          Attribute(id=U["subjectID"], value="u1")],
                resources=[Attribute(id=U["entity"], value=ORG)],
                actions=[Attribute(id=U["actionID"], value=U["read"])],
            ),
            context={"resources": [], "subject": {
                "id": "u1",
                "role_associations": [{"role": role, "attributes": []}],
                "hierarchical_scopes": [],
            }},
        )

    worker_a = make()
    worker_b = make()
    try:
        assert worker_a.replicator is not None
        assert worker_b.engine.is_allowed(
            req("replica-role")).decision == "INDETERMINATE"

        # CRUD on A: new rule + attach to the seeded policy
        rules_a = worker_a.store.get_resource_service("rule")
        rules_a.create([{
            "id": "replica-rule", "name": "replica",
            "effect": "PERMIT",
            "target": {
                "subjects": [{"id": U["role"], "value": "replica-role"}],
                "resources": [{"id": U["entity"], "value": ORG}],
                "actions": [],
            },
        }])
        policies_a = worker_a.store.get_resource_service("policy")
        doc = dict(policies_a.read()["items"][0]["payload"])
        doc["rules"] = list(doc.get("rules") or []) + ["replica-rule"]
        assert policies_a.update([doc])["operation_status"]["code"] == 200

        # worker B converges without restart (replication debounce +
        # recompile are async)
        deadline = time.time() + 20
        while time.time() < deadline:
            if worker_b.engine.is_allowed(
                req("replica-role")).decision == "PERMIT":
                break
            time.sleep(0.1)
        assert worker_b.engine.is_allowed(
            req("replica-role")).decision == "PERMIT"
        # and B's evaluator (kernel path) answers the same
        out = worker_b.evaluator.is_allowed_batch([req("replica-role")])
        assert out[0].decision == "PERMIT"

        # delete on A propagates too
        rules_a.delete(ids=["replica-rule"])
        deadline = time.time() + 20
        while time.time() < deadline:
            if worker_b.engine.is_allowed(
                req("replica-role")).decision == "INDETERMINATE":
                break
            time.sleep(0.1)
        assert worker_b.engine.is_allowed(
            req("replica-role")).decision == "INDETERMINATE"
    finally:
        worker_a.stop()
        worker_b.stop()


def test_late_worker_replays_crud_log(broker):
    """A worker that boots AFTER mutations landed replays the broker's
    CRUD log to the same state (the durable-shared-store property)."""
    from .utils import URNS as U
    from access_control_srv_tpu.models import Attribute, Request, Target

    def req(role):
        return Request(
            target=Target(
                subjects=[Attribute(id=U["role"], value=role),
                          Attribute(id=U["subjectID"], value="u1")],
                resources=[Attribute(id=U["entity"], value=ORG)],
                actions=[Attribute(id=U["actionID"], value=U["read"])],
            ),
            context={"resources": [], "subject": {
                "id": "u1",
                "role_associations": [{"role": role, "attributes": []}],
                "hierarchical_scopes": [],
            }},
        )

    cfg = {
        "policies": {"type": "database"},
        "seed_data": {
            "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
            "policies": os.path.join(SEED, "policies.yaml"),
            "rules": os.path.join(SEED, "rules.yaml"),
        },
        "events": {"broker": {"address": broker.address}},
    }
    worker_a = Worker().start(cfg)
    try:
        rules_a = worker_a.store.get_resource_service("rule")
        rules_a.create([{
            "id": "late-rule", "name": "late", "effect": "PERMIT",
            "target": {
                "subjects": [{"id": U["role"], "value": "late-role"}],
                "resources": [{"id": U["entity"], "value": ORG}],
                "actions": [],
            },
        }])
        policies_a = worker_a.store.get_resource_service("policy")
        doc = dict(policies_a.read()["items"][0]["payload"])
        doc["rules"] = list(doc.get("rules") or []) + ["late-rule"]
        assert policies_a.update([doc])["operation_status"]["code"] == 200

        worker_b = Worker().start(cfg)  # boots after the mutations
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                if worker_b.engine.is_allowed(
                    req("late-role")).decision == "PERMIT":
                    break
                time.sleep(0.1)
            assert worker_b.engine.is_allowed(
                req("late-role")).decision == "PERMIT"
        finally:
            worker_b.stop()
    finally:
        worker_a.stop()


def _free_port() -> int:
    import socket as _socket

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_broker(port: int, data_dir: str) -> subprocess.Popen:
    proc = subprocess.Popen(
        [sys.executable, "-m", "access_control_srv_tpu", "--broker",
         "--addr", f"127.0.0.1:{port}", "--broker-data-dir", data_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    line = proc.stdout.readline()  # "broker listening on ..."
    assert "listening" in line, line
    return proc


def test_subscription_survives_broker_restart(tmp_path):
    """Regression (PR 9 satellite): a dropped subscription connection must
    not silently end a listener's feed.  The pump reconnects with jittered
    backoff and resubscribes from the offset after the last frame it
    delivered — frames emitted while the broker was down (journal-durable
    log) and frames emitted after the restart all arrive, exactly once."""
    data_dir = str(tmp_path / "reconnect-data")
    port = _free_port()
    proc = _spawn_broker(port, data_dir)
    bus = SocketEventBus(f"127.0.0.1:{port}")
    topic = bus.topic("reconnect.topic")
    got = []
    topic.on(lambda e, m, ctx: got.append((m["i"], ctx["offset"])),
             starting_offset=0)
    try:
        topic.emit("thing", {"i": 0})
        deadline = time.time() + 5
        while time.time() < deadline and len(got) < 1:
            time.sleep(0.02)
        assert got == [(0, 0)]

        # broker process dies mid-subscription ...
        proc.kill()
        proc.wait(timeout=10)
        time.sleep(0.2)
        # ... and restarts on the same port + journal; frames emitted
        # after the restart continue the offset sequence
        proc = _spawn_broker(port, data_dir)
        emitter = SocketEventBus(f"127.0.0.1:{port}")
        emitter.topic("reconnect.topic").emit("thing", {"i": 1})
        emitter.topic("reconnect.topic").emit("thing", {"i": 2})
        deadline = time.time() + 15
        while time.time() < deadline and len(got) < 3:
            time.sleep(0.05)
        assert got == [(0, 0), (1, 1), (2, 2)]  # no loss, no redelivery
        emitter.close()
    finally:
        bus.close()
        proc.kill()
        proc.wait(timeout=10)


# --------------------------------------------- snapshot + compaction (PR 11)


def test_snapshot_compacts_journal_and_survives_restart(tmp_path):
    """snapshot_every=N: the N-th journal record triggers a
    crash-consistent snapshot and the journal restarts empty behind it;
    a cold restart replays snapshot + tail and reproduces the exact
    topic/KV/offset state of a full-journal replay."""
    data_dir = str(tmp_path / "snap-data")
    server = BrokerServer(data_dir=data_dir, snapshot_every=5).start()
    try:
        bus = SocketEventBus(server.address)
        topic = bus.topic("snap.topic")
        for i in range(4):
            topic.emit("thing", {"i": i})
        status = bus.snapshot_status()
        assert status["exists"] is False  # 4 records < snapshot_every
        assert status["tail_records"] == 4
        # the 5th record crosses the cadence: snapshot + truncation
        topic.emit("thing", {"i": 4})
        status = bus.snapshot_status()
        assert status["exists"] is True
        assert status["watermark"] == 5
        assert status["tail_records"] == 0
        assert status["age_s"] is not None and status["age_s"] >= 0
        # tail after the snapshot
        offsets = SocketOffsetStore(server.address)
        offsets.commit("snap.topic", 2)
        cache = SocketSubjectCache(server.address)
        cache.set("cache:snap:subject", {"id": "snap"})
        bus.close(); offsets.close(); cache.close()
    finally:
        server.stop()

    # journal holds ONLY the post-snapshot tail
    with open(os.path.join(data_dir, "broker.journal")) as fh:
        tail_lines = [ln for ln in fh if ln.strip()]
    assert len(tail_lines) == 2

    server2 = BrokerServer(data_dir=data_dir, snapshot_every=5).start()
    try:
        bus = SocketEventBus(server2.address)
        topic = bus.topic("snap.topic")
        assert [m["i"] for _, m in topic.read(0)] == [0, 1, 2, 3, 4]
        offsets = SocketOffsetStore(server2.address)
        assert offsets.get("snap.topic") == 2
        cache = SocketSubjectCache(server2.address)
        assert cache.get("cache:snap:subject") == {"id": "snap"}
        status = bus.snapshot_status()
        assert status["watermark"] == 5
        assert status["tail_records"] == 2
        bus.close(); offsets.close(); cache.close()
    finally:
        server2.stop()


def test_forced_snapshot_command_roundtrip(tmp_path):
    """The ``snapshot`` wire op compacts on demand (no cadence set)."""
    data_dir = str(tmp_path / "force-data")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        bus.topic("t").emit("a", {"n": 1})
        bus.topic("t").emit("b", {"n": 2})
        status = bus.snapshot()
        assert status["exists"] is True and status["tail_records"] == 0
        assert os.path.getsize(
            os.path.join(data_dir, "broker.journal")) == 0
        bus.close()
    finally:
        server.stop()
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server2.address)
        assert bus.topic("t").read(0) == [("a", {"n": 1}),
                                          ("b", {"n": 2})]
        bus.close()
    finally:
        server2.stop()


def test_corrupt_snapshot_fails_closed(tmp_path):
    """A flipped byte in the snapshot state fails the CRC: boot ignores
    the snapshot (reporting the error) instead of loading torn state."""
    data_dir = str(tmp_path / "corrupt-snap")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        bus.topic("t").emit("a", {"n": 1})
        bus.snapshot()
        bus.close()
    finally:
        server.stop()
    path = os.path.join(data_dir, "broker.snapshot")
    blob = json.load(open(path))
    assert '"n":1' in blob["state"]
    blob["state"] = blob["state"].replace('"n":1', '"n":9')
    json.dump(blob, open(path, "w"))
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        assert "snapshot_error" in (server2.recovered or {})
        bus = SocketEventBus(server2.address)
        # compaction emptied the journal, so fail-closed means empty
        # state — never the silently-corrupted payload
        assert bus.topic("t").read(0) == []
        bus.close()
    finally:
        server2.stop()


def test_journal_crc_detects_midfile_corruption(tmp_path):
    """A flipped byte mid-journal fails that record's CRC: replay keeps
    the consistent prefix, truncates there, and reports what it
    dropped."""
    data_dir = str(tmp_path / "crc-data")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        topic = bus.topic("t")
        for i in range(5):
            topic.emit("thing", {"i": i})
        bus.close()
    finally:
        server.stop()
    path = os.path.join(data_dir, "broker.journal")
    lines = open(path).readlines()
    assert len(lines) == 5 and all(ln.startswith("C") for ln in lines)
    lines[2] = lines[2].replace('"i": 2', '"i": 7')  # flip bytes, keep CRC
    open(path, "w").writelines(lines)
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        assert server2.recovered and server2.recovered["dropped_bytes"] > 0
        bus = SocketEventBus(server2.address)
        assert [m["i"] for _, m in bus.topic("t").read(0)] == [0, 1]
        bus.close()
    finally:
        server2.stop()


def test_torn_write_failpoint_recovers_prefix(tmp_path):
    """Arm the ``broker.journal.write`` torn failpoint inside an
    in-process broker: the torn append is detected on replay (CRC +
    missing newline) and the journal truncates back to the consistent
    prefix."""
    from access_control_srv_tpu.srv.faults import REGISTRY

    data_dir = str(tmp_path / "torn-data")
    server = BrokerServer(data_dir=data_dir).start()
    try:
        bus = SocketEventBus(server.address)
        topic = bus.topic("t")
        for i in range(3):
            topic.emit("thing", {"i": i})
        with REGISTRY.arm([{"site": "broker.journal.write",
                            "action": "torn", "torn_frac": 0.4}]):
            topic.emit("thing", {"i": 3})  # torn on disk, live in memory
        assert [m["i"] for _, m in topic.read(0)] == [0, 1, 2, 3]
        bus.close()
    finally:
        server.stop()
    server2 = BrokerServer(data_dir=data_dir).start()
    try:
        assert server2.recovered and server2.recovered["dropped_bytes"] > 0
        bus = SocketEventBus(server2.address)
        # the torn record is gone; the prefix survives intact
        assert [m["i"] for _, m in bus.topic("t").read(0)] == [0, 1, 2]
        bus.close()
    finally:
        server2.stop()
