"""ReBAC differential suite: relation tuples against the bit-reader.

The tuple store (srv/relations.py) folds Zanzibar-style relationship
closures into the stage-B bitplane format at encode time
(ops/relation.pack_relation_bitplanes); the scalar oracle walks the same
graph per decision (core/relation_path.py).  These tests pin the two
paths bit-identical across rewrite kinds (direct / computed-userset /
tuple-to-userset), path depths 1..6 and every kernel variant, plus the
store's incremental-closure identity (patched tables == from-scratch),
cycle safety, tenant isolation on a shared bus, and the serving
invariant that tuple churn swaps no compiled program.
"""

import random

import numpy as np
import pytest

from access_control_srv_tpu.core.relation_path import (
    RelationGraph,
    check_relation_path,
    parse_path,
)
from access_control_srv_tpu.ops import compile_policies, encode_requests
from access_control_srv_tpu.ops.kernel import DecisionKernel
from access_control_srv_tpu.ops.prefilter import PrefilteredKernel
from access_control_srv_tpu.ops.relation import relation_bits_needed
from access_control_srv_tpu.srv.events import EventBus
from access_control_srv_tpu.srv.relations import RelationTupleStore

from .test_kernel_differential import DEC_CODE
from .test_prefilter import force_active
from .utils import build_request, fixture, make_engine

NS = "urn:restorecommerce:acs:model:document.Document"
FOLDER = "urn:restorecommerce:acs:model:folder.Folder"
READ = "urn:restorecommerce:acs:names:action:read"


def _populate(tmp_path, value):
    from access_control_srv_tpu.core.engine import AccessController
    from access_control_srv_tpu.core.loader import populate

    text = open(fixture("relation_policies.yml")).read()
    assert text.count("value: viewer") == 1
    p = tmp_path / "rebac.yml"
    p.write_text(text.replace("value: viewer", f"value: {value}"))
    eng = AccessController()
    populate(eng, str(p))
    return eng


def _requests(subjects, resource_ids):
    return [
        build_request(
            subject_id=s, resource_type=NS, resource_id=r, action_type=READ
        )
        for s in subjects
        for r in resource_ids
    ]


def _differential(eng, store, requests, kern=None):
    """Kernel decisions on relation planes == oracle walk, row by row."""
    compiled = compile_policies(eng.policy_sets, eng.urns)
    assert compiled.supported and relation_bits_needed(compiled)
    kern = kern or DecisionKernel(compiled)
    eng.relation_store = store
    batch = encode_requests(
        requests, compiled,
        relation_tables=store.tables_for(compiled) if store else None,
    )
    decision, _, status = kern.evaluate(batch)
    n = 0
    for b, req in enumerate(requests):
        if not batch.eligible[b] or status[b] != 200:
            continue
        expected = eng.is_allowed(req)
        assert decision[b] == DEC_CODE[expected.decision], (
            b, expected.decision)
        n += 1
    assert n > 0
    return n


# ------------------------------------------------------- rewrite kinds


@pytest.mark.parametrize(
    "kind", ["direct", "computed_userset", "tuple_to_userset"]
)
def test_rewrite_kinds_kernel_vs_oracle(kind):
    eng = make_engine("relation_policies.yml")
    store = RelationTupleStore()
    if kind == "direct":
        store.create([(NS, "doc1", "viewer", "alice")])
    elif kind == "computed_userset":
        store.set_rewrite(
            NS, "viewer", [("this",), ("computed_userset", "owner")]
        )
        store.create([(NS, "doc1", "owner", "alice")])
    else:  # tuple_to_userset: doc viewers include the parent's viewers
        store.set_rewrite(
            NS, "viewer",
            [("this",), ("tuple_to_userset", "parent", "viewer")],
        )
        store.create([
            (NS, "doc1", "parent", {"object": {"entity": FOLDER,
                                               "id": "f1"}}),
            (FOLDER, "f1", "viewer", "alice"),
        ])
    reqs = _requests(["alice", "bob"], ["doc1", "doc2"])
    _differential(eng, store, reqs)
    # alice sees doc1 through every rewrite kind; bob never does
    assert eng.is_allowed(reqs[0]).decision == "PERMIT"
    assert eng.is_allowed(reqs[2]).decision == "DENY"


# ----------------------------------------------------------- path depth


@pytest.mark.parametrize("depth", [1, 2, 3, 4, 5, 6])
def test_depth_chain_kernel_vs_oracle(depth, tmp_path):
    """Multi-step path expressions (parent.parent....owner) against a
    folder chain of the matching depth; one hop short must fail."""
    path = ".".join(["parent"] * (depth - 1) + ["owner"])
    eng = _populate(tmp_path, path)
    store = RelationTupleStore()
    if depth == 1:
        store.create([(NS, "doc1", "owner", "alice")])
    else:
        chain = [(NS, "doc1", "parent",
                  {"object": {"entity": FOLDER, "id": "f1"}})]
        for i in range(1, depth - 1):
            chain.append((FOLDER, f"f{i}", "parent",
                          {"object": {"entity": FOLDER, "id": f"f{i+1}"}}))
        chain.append((FOLDER, f"f{depth-1}", "owner", "alice"))
        store.create(chain)
        # a second doc whose chain stops one folder short: never reaches
        store.create([(NS, "doc2", "parent",
                       {"object": {"entity": FOLDER, "id": f"f{depth-1}"}})])
    _differential(eng, store, _requests(["alice", "bob"], ["doc1", "doc2"]))
    assert store.check(path, NS, "doc1", "alice")
    assert not store.check(path, NS, "doc1", "bob")


# ------------------------------------------------------ kernel variants


def test_kernel_variants_agree():
    """Dense, signature-prefiltered and pod-sharded kernels read the
    same relation planes to the same decisions."""
    from jax.sharding import Mesh
    import jax

    from access_control_srv_tpu.parallel.pod_shard import PodShardedKernel

    eng = make_engine("relation_policies.yml")
    compiled = compile_policies(eng.policy_sets, eng.urns)
    store = RelationTupleStore()
    store.set_rewrite(
        NS, "viewer", [("this",), ("computed_userset", "owner")]
    )
    store.create([
        (NS, "doc1", "owner", "alice"),
        (NS, "doc2", "viewer", "bob"),
        (NS, "doc3", "viewer", {"object": {"entity": "group", "id": "g"},
                                "relation": "member"}),
        ("group", "g", "member", "carol"),
    ])
    reqs = _requests(["alice", "bob", "carol", "mallory"],
                     ["doc1", "doc2", "doc3", ["doc1", "doc3"]])
    batch = encode_requests(
        reqs, compiled, relation_tables=store.tables_for(compiled)
    )
    dense = DecisionKernel(compiled)
    pre = force_active(PrefilteredKernel(compiled))
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    sharded = PodShardedKernel(compiled, Mesh(devices, ("data", "model")))

    d_ref, c_ref, s_ref = dense.evaluate(batch)
    for kern in (pre, sharded):
        d, c, s = kern.evaluate(batch)
        el = batch.eligible
        assert np.array_equal(d[el], d_ref[el])
        assert np.array_equal(c[el], c_ref[el])
        assert np.array_equal(s[el], s_ref[el])
    eng.relation_store = store
    for b in range(len(reqs)):
        if batch.eligible[b]:
            assert d_ref[b] == DEC_CODE[eng.is_allowed(reqs[b]).decision], b


# --------------------------------------------------- incremental closure


_FUZZ_RELS = ["viewer", "owner", "editor", "parent"]
_FUZZ_USERS = [f"u{i}" for i in range(6)]
_FUZZ_OBJS = [(NS, f"doc{i}") for i in range(4)] + [
    (FOLDER, f"f{i}") for i in range(3)
]


def _random_tuple(rng):
    ns, oid = rng.choice(_FUZZ_OBJS)
    rel = rng.choice(_FUZZ_RELS)
    kind = rng.random()
    if kind < 0.6:
        subj = rng.choice(_FUZZ_USERS)
    elif kind < 0.85:
        ons, ooid = rng.choice(_FUZZ_OBJS)
        subj = {"object": {"entity": ons, "id": ooid}}
    else:
        ons, ooid = rng.choice(_FUZZ_OBJS)
        subj = {"object": {"entity": ons, "id": ooid},
                "relation": rng.choice(_FUZZ_RELS)}
    return (ns, oid, rel, subj)


@pytest.mark.parametrize("seed", [7, 1201])
def test_fuzz_patched_tables_equal_fresh(seed, tmp_path):
    """Random create/delete/rewrite churn: the incrementally-invalidated
    closure must produce byte-identical verdict tables (and fingerprint)
    to a store rebuilt from scratch at the final state — the delta
    soundness property of the dependency-recording memo cache."""
    eng = _populate(tmp_path, "viewer|parent.owner")
    compiled = compile_policies(eng.policy_sets, eng.urns)
    rng = random.Random(seed)
    store = RelationTupleStore()
    live: list[tuple] = []
    for step in range(120):
        op = rng.random()
        if op < 0.15 and live:
            victim = rng.choice(live)
            store.delete([victim])
            live.remove(victim)
        elif op < 0.25:
            ns, _ = rng.choice(_FUZZ_OBJS)
            rel = rng.choice(_FUZZ_RELS)
            rules = [("this",)]
            if rng.random() < 0.5:
                rules.append(("computed_userset", rng.choice(_FUZZ_RELS)))
            if rng.random() < 0.3:
                rules.append(("tuple_to_userset", "parent",
                              rng.choice(_FUZZ_RELS)))
            store.set_rewrite(ns, rel, rules)
        else:
            t = _random_tuple(rng)
            if store.create([t]):
                live.append(t)
        if step % 40 == 17:
            # mid-churn: warm the memo cache so later invalidations have
            # stale entries to catch
            store.tables_for(compiled)

    fresh = RelationTupleStore()
    for (ns, rel), rules in store.graph.rewrites.items():
        fresh.set_rewrite(ns, rel, rules)
    fresh.create(live)
    assert store.fingerprint() == fresh.fingerprint()
    patched = store.tables_for(compiled)
    rebuilt = fresh.tables_for(compiled)
    for name in ("obj_offs", "obj_keys", "pairs"):
        assert np.array_equal(patched[name], rebuilt[name]), name

    # cached-closure verdicts == uncached oracle walk on sampled queries
    for expr in ("viewer", "parent.owner", "owner!direct",
                 "viewer|parent.owner"):
        path = parse_path(expr)
        for ns, oid in _FUZZ_OBJS:
            for user in _FUZZ_USERS:
                assert store.check(expr, ns, oid, user) == \
                    check_relation_path(path, ns, oid, user, store.graph), \
                    (expr, ns, oid, user)


def test_cycle_safe_closure():
    """Mutually-recursive usersets terminate and stay correct: group a's
    members include group b's and vice versa."""
    g = RelationGraph()
    g.add("group", "a", "member",
          {"object": {"entity": "group", "id": "b"}, "relation": "member"})
    g.add("group", "b", "member",
          {"object": {"entity": "group", "id": "a"}, "relation": "member"})
    g.add("group", "b", "member", "alice")
    path = parse_path("member")
    assert check_relation_path(path, "group", "a", "alice", g)
    assert check_relation_path(path, "group", "b", "alice", g)
    assert not check_relation_path(path, "group", "a", "bob", g)

    store = RelationTupleStore()
    store.create([
        ("group", "a", "member",
         {"object": {"entity": "group", "id": "b"}, "relation": "member"}),
        ("group", "b", "member",
         {"object": {"entity": "group", "id": "a"}, "relation": "member"}),
        ("group", "b", "member", "alice"),
    ])
    assert store.check("member", "group", "a", "alice")
    assert store.witness("member", "group", "a", "alice") is not None
    assert not store.check("member", "group", "a", "bob")


# ---------------------------------------------------- bus + replication


def test_tenant_isolation_on_shared_bus():
    """Tenant-tagged tuple frames on one shared topic apply only to the
    matching tenant's store — cross-tenant tuples can never leak into
    another domain's closure."""
    bus = EventBus()
    a1 = RelationTupleStore(bus=bus, tenant="acme")
    a2 = RelationTupleStore(bus=bus, tenant="acme")
    b = RelationTupleStore(bus=bus, tenant="globex")
    for s in (a2, b):
        s.start_replication()
    a1.create([(NS, "doc1", "viewer", "alice")])
    assert a2.check("viewer", NS, "doc1", "alice")  # same tenant: applied
    assert not b.check("viewer", NS, "doc1", "alice")  # isolated
    assert a1.fingerprint() == a2.fingerprint()
    assert b.fingerprint() != a1.fingerprint()
    # origin-skip: the writer must not re-apply its own frame (gen stays
    # at one bump per mutation)
    assert a1.generation == 1


def test_boot_replay_converges():
    """A store attaching AFTER the churn replays the journaled topic to
    the survivor's exact fingerprint (the broker-durability boot path)."""
    bus = EventBus()
    first = RelationTupleStore(bus=bus)
    first.set_rewrite(NS, "viewer",
                      [("this",), ("computed_userset", "owner")])
    first.create([(NS, "doc1", "owner", "alice"),
                  (NS, "doc2", "viewer", "bob")])
    first.delete([(NS, "doc2", "viewer", "bob")])

    late = RelationTupleStore(bus=bus)
    late.replay()
    assert late.fingerprint() == first.fingerprint()
    assert late.check("viewer", NS, "doc1", "alice")
    assert not late.check("viewer", NS, "doc2", "bob")


# ------------------------------------------------- serving invariants


def test_fail_closed_without_store():
    """No tuple store: relation-bearing targets deny on the oracle AND
    the kernel (empty-table planes) — never PERMIT by omission."""
    eng = make_engine("relation_policies.yml")
    _differential(eng, None, _requests(["alice"], ["doc1"]))
    assert eng.is_allowed(_requests(["alice"], ["doc1"])[0]) \
        .decision == "DENY"


def test_tuple_churn_swaps_no_program():
    """The ReBAC serving invariant: tuple CRUD flips decisions with the
    compiled tables, kernel and jitted executables all byte-identical —
    only the host-side verdict tables and the decision-cache epoch move."""
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    eng = make_engine("relation_policies.yml")
    ev = HybridEvaluator(eng)
    ev.refresh()
    store = RelationTupleStore()
    ev.attach_relation_store(store)
    req = _requests(["bob"], ["doc1"])[0]

    version = ev._compiled.version
    jits = set(ev._shared_jits.keys())
    assert ev.is_allowed(req).decision == "DENY"
    store.create([(NS, "doc1", "viewer", "bob")])
    assert ev.is_allowed(req).decision == "PERMIT"
    store.delete([(NS, "doc1", "viewer", "bob")])
    assert ev.is_allowed(req).decision == "DENY"
    assert ev._compiled.version == version
    assert set(ev._shared_jits.keys()) == jits

    # replica convergence covers tuple state: equal policy tables with
    # divergent tuple logs must fingerprint differently
    fp_before = ev.table_fingerprint()
    store.create([(NS, "doc9", "viewer", "eve")])
    assert ev.table_fingerprint() != fp_before
