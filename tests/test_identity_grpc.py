"""Network identity client end-to-end (VERDICT r2 missing #1).

The reference holds a live gRPC channel to the identity service and
resolves subject tokens on the decision hot path
(src/worker.ts:135-143, src/core/accessController.ts:110-117); its suite
3 drives token -> findByToken -> HR rendezvous -> decision over real
transports (test/microservice_acs_enabled.spec.ts:106-223).  This test
does the same with this framework's pieces: MockIdentityServer on TCP,
Worker configured with the identity address (builds a GrpcIdentityClient),
the request arriving over the gRPC transport."""

import json
import os
import threading

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.identity import (
    GrpcIdentityClient,
    MockIdentityServer,
)
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer
from access_control_srv_tpu.srv.worker import Worker

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")


@pytest.fixture()
def rig():
    ids = MockIdentityServer()
    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
            "client": {"identity": {"address": ids.address, "timeout": 2.0}},
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    yield ids, worker, client
    client.close()
    server.stop()
    worker.stop()
    ids.stop()


def token_request(token: str) -> pb.Request:
    msg = pb.Request()
    msg.target.subjects.add(id=URNS["role"], value="superadministrator-r-id")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.resources.add(id=URNS["resourceID"], value="O1")
    msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
    msg.context.subject.value = json.dumps({"token": token}).encode()
    return msg


def test_worker_builds_grpc_identity_client(rig):
    ids, worker, client = rig
    assert isinstance(worker.identity_client, GrpcIdentityClient)
    assert worker.identity_client.address == ids.address


def test_token_resolution_and_rendezvous_over_wire(rig):
    """token -> network findByToken -> HR rendezvous -> PERMIT, with the
    request itself arriving over the gRPC transport."""
    ids, worker, client = rig
    ids.register(
        "net-tok-1",
        {
            "id": "ada",
            "tokens": [{"token": "net-tok-1", "interactive": True}],
            "role_associations": [
                {"role": "superadministrator-r-id", "attributes": []}
            ],
        },
    )
    auth_topic = worker.bus.topic("io.restorecommerce.authentication")

    def responder(event_name, message, ctx):
        if event_name != "hierarchicalScopesRequest":
            return

        def reply():
            auth_topic.emit(
                "hierarchicalScopesResponse",
                {
                    "token": message["token"],
                    "subject_id": "ada",
                    "interactive": True,
                    "hierarchical_scopes": [{"id": "OrgNet"}],
                },
            )

        threading.Thread(target=reply, daemon=True).start()

    auth_topic.on(responder)
    response = client.is_allowed(token_request("net-tok-1"))
    assert response.decision == pb.PERMIT
    assert ids.calls == ["net-tok-1"]  # resolved over the real channel
    assert worker.subject_cache.get("cache:ada:hrScopes") == [{"id": "OrgNet"}]


def test_unknown_token_fails_closed(rig):
    ids, worker, client = rig
    response = client.is_allowed(token_request("no-such-token"))
    assert response.decision != pb.PERMIT
    assert "no-such-token" in ids.calls


def test_identity_down_fails_closed(rig):
    ids, worker, client = rig
    ids.stop()
    response = client.is_allowed(token_request("net-tok-2"))
    assert response.decision != pb.PERMIT  # transport error -> unresolved


def test_token_cache_and_user_modified_eviction(rig):
    ids, worker, client = rig
    payload = {
        "id": "gil",
        "tokens": [{"token": "net-tok-3", "interactive": True}],
        "role_associations": [
            {"role": "superadministrator-r-id", "attributes": []}
        ],
    }
    ids.register("net-tok-3", payload)
    worker.subject_cache.set("cache:gil:hrScopes", [{"id": "OrgC"}])
    client.is_allowed(token_request("net-tok-3"))
    client.is_allowed(token_request("net-tok-3"))
    assert ids.calls.count("net-tok-3") == 1  # second hit served from cache

    # userModified evicts the token resolution; next request re-resolves
    worker.bus.topic("io.restorecommerce.users.resource").emit(
        "userModified", {"id": "gil", "tokens": payload["tokens"],
                         "role_associations": payload["role_associations"]},
    )
    client.is_allowed(token_request("net-tok-3"))
    assert ids.calls.count("net-tok-3") == 2
