"""Network identity client end-to-end (VERDICT r2 missing #1).

The reference holds a live gRPC channel to the identity service and
resolves subject tokens on the decision hot path
(src/worker.ts:135-143, src/core/accessController.ts:110-117); its suite
3 drives token -> findByToken -> HR rendezvous -> decision over real
transports (test/microservice_acs_enabled.spec.ts:106-223).  This test
does the same with this framework's pieces: MockIdentityServer on TCP,
Worker configured with the identity address (builds a GrpcIdentityClient),
the request arriving over the gRPC transport."""

import json
import os
import threading
import time

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.identity import (
    GrpcIdentityClient,
    MockIdentityServer,
)
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer
from access_control_srv_tpu.srv.worker import Worker

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")


@pytest.fixture()
def rig():
    ids = MockIdentityServer()
    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
            "client": {"identity": {"address": ids.address, "timeout": 2.0}},
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    yield ids, worker, client
    client.close()
    server.stop()
    worker.stop()
    ids.stop()


def token_request(token: str) -> pb.Request:
    msg = pb.Request()
    msg.target.subjects.add(id=URNS["role"], value="superadministrator-r-id")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.resources.add(id=URNS["resourceID"], value="O1")
    msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
    msg.context.subject.value = json.dumps({"token": token}).encode()
    return msg


def test_worker_builds_grpc_identity_client(rig):
    ids, worker, client = rig
    assert isinstance(worker.identity_client, GrpcIdentityClient)
    assert worker.identity_client.address == ids.address


def test_token_resolution_and_rendezvous_over_wire(rig):
    """token -> network findByToken -> HR rendezvous -> PERMIT, with the
    request itself arriving over the gRPC transport."""
    ids, worker, client = rig
    ids.register(
        "net-tok-1",
        {
            "id": "ada",
            "tokens": [{"token": "net-tok-1", "interactive": True}],
            "role_associations": [
                {"role": "superadministrator-r-id", "attributes": []}
            ],
        },
    )
    auth_topic = worker.bus.topic("io.restorecommerce.authentication")

    def responder(event_name, message, ctx):
        if event_name != "hierarchicalScopesRequest":
            return

        def reply():
            auth_topic.emit(
                "hierarchicalScopesResponse",
                {
                    "token": message["token"],
                    "subject_id": "ada",
                    "interactive": True,
                    "hierarchical_scopes": [{"id": "OrgNet"}],
                },
            )

        threading.Thread(target=reply, daemon=True).start()

    auth_topic.on(responder)
    response = client.is_allowed(token_request("net-tok-1"))
    assert response.decision == pb.PERMIT
    assert ids.calls == ["net-tok-1"]  # resolved over the real channel
    assert worker.subject_cache.get("cache:ada:hrScopes") == [{"id": "OrgNet"}]


def test_unknown_token_fails_closed(rig):
    ids, worker, client = rig
    response = client.is_allowed(token_request("no-such-token"))
    assert response.decision != pb.PERMIT
    assert "no-such-token" in ids.calls


def test_identity_down_fails_closed(rig):
    ids, worker, client = rig
    ids.stop()
    response = client.is_allowed(token_request("net-tok-2"))
    assert response.decision != pb.PERMIT  # transport error -> unresolved


def test_token_cache_and_user_modified_eviction(rig):
    ids, worker, client = rig
    payload = {
        "id": "gil",
        "tokens": [{"token": "net-tok-3", "interactive": True}],
        "role_associations": [
            {"role": "superadministrator-r-id", "attributes": []}
        ],
    }
    ids.register("net-tok-3", payload)
    worker.subject_cache.set("cache:gil:hrScopes", [{"id": "OrgC"}])
    client.is_allowed(token_request("net-tok-3"))
    client.is_allowed(token_request("net-tok-3"))
    assert ids.calls.count("net-tok-3") == 1  # second hit served from cache

    # userModified evicts the token resolution; next request re-resolves
    worker.bus.topic("io.restorecommerce.users.resource").emit(
        "userModified", {"id": "gil", "tokens": payload["tokens"],
                         "role_associations": payload["role_associations"]},
    )
    client.is_allowed(token_request("net-tok-3"))
    assert ids.calls.count("net-tok-3") == 2


class GatedIdentityServer:
    """Mock IDS whose handler blocks on a gate (and can sleep): drives the
    client's in-flight / timeout behavior under real gRPC concurrency —
    the reference's subtlest races live between findByToken resolution and
    userModified cache eviction (src/worker.ts:252-340)."""

    def __init__(self, subjects_by_token=None, delay: float = 0.0):
        import json
        import threading
        from concurrent import futures

        import grpc

        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

        self.subjects_by_token = subjects_by_token or {}
        self.gate = threading.Event()
        self.gate.set()
        self.delay = delay
        self.calls = []
        self._lock = threading.Lock()

        def find_by_token(request, context):
            with self._lock:
                self.calls.append(request.token)
            self.gate.wait(timeout=30)
            if self.delay:
                time.sleep(self.delay)
            payload = self.subjects_by_token.get(request.token)
            if payload is None:
                return pb.SubjectResponse(
                    payload=b"",
                    status=pb.OperationStatus(code=404, message="not found"),
                )
            return pb.SubjectResponse(
                payload=json.dumps(payload).encode(),
                status=pb.OperationStatus(code=200, message="success"),
            )

        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        handler = grpc.method_handlers_generic_handler(
            "acstpu.IdentityService",
            {
                "FindByToken": grpc.unary_unary_rpc_method_handler(
                    find_by_token,
                    request_deserializer=pb.FindByTokenRequest.FromString,
                    response_serializer=pb.SubjectResponse.SerializeToString,
                ),
            },
        )
        self.server.add_generic_rpc_handlers((handler,))
        self.port = self.server.add_insecure_port("127.0.0.1:0")
        self.server.start()

    @property
    def address(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.gate.set()
        self.server.stop(grace=None)


def test_timeout_flood_fails_closed_and_recovers():
    """A flood of resolutions against a too-slow IDS all fail closed
    (503, payload None); after the server speeds up the client recovers
    without restart."""
    import threading

    from access_control_srv_tpu.srv.identity import GrpcIdentityClient

    ids = GatedIdentityServer({"tok": {"id": "u"}}, delay=0.5)
    client = GrpcIdentityClient(ids.address, timeout=0.1)
    try:
        results = [None] * 24

        def resolve(i):
            results[i] = client.find_by_token("tok")

        threads = [threading.Thread(target=resolve, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(r is not None for r in results)
        assert all(r["payload"] is None for r in results)
        assert all(r["status"]["code"] == 503 for r in results)

        ids.delay = 0.0  # server recovers; same client object
        ok = client.find_by_token("tok")
        assert ok["payload"] == {"id": "u"}
    finally:
        client.close()
        ids.stop()


def test_eviction_during_in_flight_resolution_not_reinserted():
    """userModified-style eviction racing an in-flight resolution: the
    stale payload must not repopulate the cache after the eviction — the
    next lookup re-resolves and sees the NEW payload."""
    import threading

    from access_control_srv_tpu.srv.identity import GrpcIdentityClient

    ids = GatedIdentityServer({"tok": {"id": "u", "v": "old"}})
    client = GrpcIdentityClient(ids.address, timeout=10)
    try:
        ids.gate.clear()  # block the handler mid-resolution
        in_flight = []
        threads = [
            threading.Thread(
                target=lambda: in_flight.append(client.find_by_token("tok"))
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while not ids.calls and time.time() < deadline:
            time.sleep(0.01)
        assert ids.calls, "handler never reached"

        # the user is mutated while resolutions are parked in the server
        ids.subjects_by_token["tok"] = {"id": "u", "v": "new"}
        client.evict("tok")

        ids.gate.set()
        for t in threads:
            t.join(timeout=30)
        assert len(in_flight) == 8
        # in-flight callers may see the old payload (they began before the
        # mutation) but the CACHE must not: the next lookup re-resolves
        n_calls = len(ids.calls)
        fresh = client.find_by_token("tok")
        assert fresh["payload"] == {"id": "u", "v": "new"}
        assert len(ids.calls) == n_calls + 1  # not served from a stale cache
    finally:
        client.close()
        ids.stop()


def test_identity_soak_concurrent_resolutions_and_evictions():
    """Soak: 16 threads x 40 lookups over 8 tokens with interleaved
    evictions; no exceptions, every result is either fail-closed or the
    correct payload for its token, and the cache stays bounded."""
    import random
    import threading

    from access_control_srv_tpu.srv.identity import GrpcIdentityClient

    tokens = {f"tok-{i}": {"id": f"user-{i}"} for i in range(8)}
    ids = GatedIdentityServer(dict(tokens))
    client = GrpcIdentityClient(ids.address, timeout=5, cache_size=4)
    errors = []

    def hammer(seed):
        rng = random.Random(seed)
        try:
            for _ in range(40):
                tok = f"tok-{rng.randrange(8)}"
                out = client.find_by_token(tok)
                if out["payload"] is not None:
                    if out["payload"] != tokens[tok]:
                        errors.append((tok, out))
                if rng.random() < 0.2:
                    client.evict(tok if rng.random() < 0.5 else None)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    try:
        threads = [threading.Thread(target=hammer, args=(s,))
                   for s in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]
        assert len(client._cache) <= 4
    finally:
        client.close()
        ids.stop()
