"""Adapter transport (ISSUE 1 satellite): the pooled keep-alive HTTP
transport replacing the per-row ``urllib.urlopen``, the configurable
timeout, ``query_many`` batch concurrency with per-row error semantics,
and the HR-rendezvous shared-condition wakeup."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from access_control_srv_tpu.core.errors import (
    ContextQueryTransportError,
    UnexpectedContextQueryResponse,
)
from access_control_srv_tpu.models import Request, Target
from access_control_srv_tpu.srv.adapters import GraphQLAdapter, create_adapter
from access_control_srv_tpu.srv.cache import HRScopeProvider, SubjectCache

GQL_BODY = json.dumps({
    "data": {"op": {"details": [{"payload": {"id": "res-1"}}]}}
}).encode()


class _GqlHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive, like real gql endpoints
    delay_s = 0.0
    connections = set()

    def do_POST(self):
        self.connections.add(self.client_address)
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if self.delay_s:
            time.sleep(self.delay_s)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(GQL_BODY)))
        self.end_headers()
        self.wfile.write(GQL_BODY)

    def log_message(self, *args):
        pass


@pytest.fixture()
def gql_server():
    handler = type("Handler", (_GqlHandler,), {"connections": set(),
                                               "delay_s": 0.0})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}/graphql", handler
    finally:
        server.shutdown()
        server.server_close()


def context_query():
    return SimpleNamespace(query="query q { all { id } }", filters=[])


def request():
    return Request(target=Target(subjects=[], resources=[], actions=[]),
                   context={"resources": []})


def test_pooled_transport_reuses_connections(gql_server):
    url, handler = gql_server
    adapter = GraphQLAdapter(url)
    try:
        for _ in range(6):
            assert adapter.query(context_query(), request()) == \
                [{"id": "res-1"}]
        # keep-alive pooling: 6 sequential queries ride ONE connection
        # (the old urllib transport opened 6)
        assert len(handler.connections) == 1
    finally:
        adapter.close()


def test_query_many_fans_out_concurrently(gql_server):
    url, handler = gql_server
    handler.delay_s = 0.25
    adapter = GraphQLAdapter(url, max_concurrency=4)
    try:
        pairs = [(context_query(), request()) for _ in range(4)]
        t0 = time.perf_counter()
        results = adapter.query_many(pairs)
        elapsed = time.perf_counter() - t0
        assert results == [[{"id": "res-1"}]] * 4
        # 4 rows at 0.25s each: sequential would be ~1.0s
        assert elapsed < 0.75, f"batch not concurrent: {elapsed:.2f}s"
    finally:
        adapter.close()


def test_query_many_per_row_errors(gql_server):
    url, _ = gql_server
    adapter = GraphQLAdapter(url)
    bad = SimpleNamespace(query="q", filters=[])
    calls = {"n": 0}
    real = adapter.transport

    def flaky(u, body, headers):
        calls["n"] += 1
        if calls["n"] == 1:
            return b"not json"
        return real(u, body, headers)

    adapter.transport = flaky
    try:
        results = adapter.query_many(
            [(bad, request()), (context_query(), request())]
        )
        # row 0 failed, row 1 served: deny-on-error stays per-row
        assert isinstance(results[0], UnexpectedContextQueryResponse)
        assert results[1] == [{"id": "res-1"}]
    finally:
        adapter.close()


def test_configurable_timeout_bounds_slow_endpoint(gql_server):
    url, handler = gql_server
    handler.delay_s = 5.0
    adapter = GraphQLAdapter(url, timeout_s=0.3)
    try:
        t0 = time.perf_counter()
        with pytest.raises(Exception):
            adapter.query(context_query(), request())
        # far below the old hard-coded 30s urlopen timeout
        assert time.perf_counter() - t0 < 2.0
    finally:
        adapter.close()


def test_non_2xx_raises_clean_transport_error():
    """An upstream error (often an HTML body) must surface as a transport
    error carrying the HTTP status — the old urlopen raised HTTPError here
    — never reach GraphQL JSON parsing."""
    class _ErrorHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = b"<html><body>502 Bad Gateway</body></html>"
            self.send_response(502)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), _ErrorHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}/graphql"
    adapter = GraphQLAdapter(url)
    try:
        with pytest.raises(ContextQueryTransportError) as exc_info:
            adapter.query(context_query(), request())
        # the engine's deny-on-error branch reads .code for the
        # operation status, preserving the upstream classification
        assert exc_info.value.code == 502
    finally:
        adapter.close()
        server.shutdown()
        server.server_close()


def test_pool_follows_url_argument(gql_server):
    url, _ = gql_server
    adapter = GraphQLAdapter(url)
    try:
        assert adapter.query(context_query(), request()) == [{"id": "res-1"}]
        # repoint the adapter at a dead endpoint: the pool must rekey on
        # the url instead of silently posting to the original host
        adapter.url = "http://127.0.0.1:1/graphql"
        with pytest.raises(OSError):
            adapter.query(context_query(), request())
    finally:
        adapter.close()


def test_create_adapter_passes_transport_knobs():
    adapter = create_adapter({
        "graphql": {"url": "http://example.invalid/graphql"},
        "timeout_s": 1.5,
        "max_concurrency": 3,
    })
    assert adapter.timeout_s == 1.5
    assert adapter.max_concurrency == 3


# ------------------------------------------------- HR rendezvous wakeup


def test_hr_rendezvous_wakes_all_parked_waiters(monkeypatch):
    """N threads parked on the same token_date share ONE condition and all
    wake on a single hierarchicalScopesResponse (the satellite replacing
    one threading.Event per request)."""
    import access_control_srv_tpu.srv.cache as cache_mod

    # pin the rendezvous timestamp so all four calls share one token_date
    class FixedNow:
        @staticmethod
        def isoformat():
            return "FIXED"

    class FixedDatetime:
        @staticmethod
        def now(tz):
            return FixedNow()

    import datetime as real_datetime

    monkeypatch.setattr(
        cache_mod, "datetime",
        SimpleNamespace(datetime=FixedDatetime,
                        timezone=real_datetime.timezone),
    )

    requests_seen = []
    topic = SimpleNamespace(
        emit=lambda event, message: requests_seen.append(message["token"])
    )
    provider = HRScopeProvider(SubjectCache(), topic, timeout_ms=5_000)

    def subject():
        return {"id": "u1", "token": "tok-1",
                "tokens": [{"token": "tok-1"}]}

    results = []

    def waiter():
        results.append(provider.create_hr_scope({"subject": subject()}))

    threads = [threading.Thread(target=waiter) for _ in range(4)]
    for t in threads:
        t.start()
    deadline = time.time() + 2.0
    while provider.waiting.get("tok-1:FIXED", 0) < 4 and \
            time.time() < deadline:
        time.sleep(0.01)
    assert provider.waiting.get("tok-1:FIXED") == 4
    # ONE response wakes all four parked waiters
    provider.handle_hr_scopes_response({
        "token": "tok-1:FIXED",
        "subject_id": "u1",
        "hierarchical_scopes": [{"id": "root-org"}],
    })
    for t in threads:
        t.join(timeout=5.0)
        assert not t.is_alive(), "parked waiter never woke"
    assert len(results) == 4
    for ctx in results:
        assert ctx["subject"]["hierarchical_scopes"] == [{"id": "root-org"}]
    # bookkeeping drained: neither the waiting map nor the released set
    # leaks entries after the last waiter exits
    assert provider.waiting == {}
    assert provider._released == set()


def test_hr_rendezvous_timeout_unparks():
    provider = HRScopeProvider(
        SubjectCache(),
        SimpleNamespace(emit=lambda *a, **k: None),
        timeout_ms=100,
    )
    context = {"subject": {"id": "u1", "token": "tok-1"}}
    t0 = time.perf_counter()
    out = provider.create_hr_scope(context)
    assert time.perf_counter() - t0 < 2.0
    assert out is context or out == context
    assert provider.waiting == {}


def test_default_hr_timeout_lowered():
    from access_control_srv_tpu.srv.config import DEFAULT_CONFIG

    assert DEFAULT_CONFIG["authorization"]["hrReqTimeout"] == 15_000
    assert HRScopeProvider(SubjectCache()).timeout_ms == 15_000
