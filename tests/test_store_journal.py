"""Store persistence cost model: single-document mutations append one
journal record (O(doc)) instead of rewriting the full JSON snapshot
(O(corpus)) — the reference's ArangoDB writes per document
(src/resourceManager.ts persistence via resource-base / Arango).
Snapshot rewrites happen only on bulk loads, clears, and journal
compaction."""

import json
import os
import time

from access_control_srv_tpu.srv.store import Collection


def _mk_docs(n, prefix="d"):
    return [{"id": f"{prefix}{i}", "name": f"doc {i}", "n": i}
            for i in range(n)]


def test_single_mutations_do_not_rewrite_snapshot(tmp_path):
    d = str(tmp_path)
    col = Collection("rule", snapshot_dir=d)
    col.upsert_many(_mk_docs(500))  # bulk load -> snapshot
    snap = os.path.join(d, "rule.json")
    before = os.stat(snap).st_mtime_ns, os.path.getsize(snap)

    for i in range(50):
        col.upsert({"id": f"x{i}", "v": i})
    col.delete("x0")

    assert (os.stat(snap).st_mtime_ns, os.path.getsize(snap)) == before
    with open(os.path.join(d, "rule.journal")) as fh:
        records = [json.loads(l) for l in fh if l.strip()]
    assert len(records) == 51
    assert records[-1] == {"op": "delete", "id": "x0"}


def test_restart_replays_snapshot_plus_journal(tmp_path):
    d = str(tmp_path)
    col = Collection("rule", snapshot_dir=d)
    col.upsert_many(_mk_docs(10))
    col.upsert({"id": "extra", "v": 1})
    col.upsert({"id": "d3", "name": "doc 3 modified", "n": 3})
    col.delete("d4")

    col2 = Collection("rule", snapshot_dir=d)
    assert col2.get("extra") == {"id": "extra", "v": 1}
    assert col2.get("d3")["name"] == "doc 3 modified"
    assert col2.get("d4") is None
    assert len(col2.all()) == 10  # 10 - deleted + extra


def test_torn_journal_tail_skipped(tmp_path):
    d = str(tmp_path)
    col = Collection("rule", snapshot_dir=d)
    col.upsert({"id": "a", "v": 1})
    with open(os.path.join(d, "rule.journal"), "a") as fh:
        fh.write('{"op": "upsert", "doc": {"id": "b"')
    col2 = Collection("rule", snapshot_dir=d)
    assert col2.get("a") == {"id": "a", "v": 1}
    assert col2.get("b") is None


def test_compaction_rolls_journal_into_snapshot(tmp_path):
    d = str(tmp_path)
    col = Collection("rule", snapshot_dir=d, compact_every=10)
    for i in range(25):
        col.upsert({"id": f"k{i}", "v": i})
    # after crossing the threshold the journal restarts small
    jpath = os.path.join(d, "rule.journal")
    with open(jpath) as fh:
        n_records = sum(1 for l in fh if l.strip())
    assert n_records < 10
    col2 = Collection("rule", snapshot_dir=d)
    assert len(col2.all()) == 25


def test_mutation_cost_independent_of_corpus(tmp_path):
    """Micro-bench: median single-upsert latency on a 10k-doc corpus must
    be within 8x of an empty collection (it was O(corpus) before: a full
    10k-doc JSON rewrite per mutation)."""
    def median_upsert_s(col, n=30):
        times = []
        for i in range(n):
            doc = {"id": f"bench{i}", "v": i}
            t0 = time.perf_counter()
            col.upsert(doc)
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    small = Collection("small", snapshot_dir=str(tmp_path / "a"))
    t_small = median_upsert_s(small)

    big = Collection("big", snapshot_dir=str(tmp_path / "b"))
    big.upsert_many(_mk_docs(10_000))
    t_big = median_upsert_s(big)

    assert t_big < t_small * 8 + 0.002, (t_small, t_big)
