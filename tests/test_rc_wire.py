"""Reference-wire compatibility: a client speaking the RESTORECOMMERCE
proto surface (io.restorecommerce.* service names and message shapes,
reconstructed in proto/rc/ — reference bindings src/worker.ts:160-194)
drives this service end-to-end over real gRPC.

The client side here uses raw grpc channels + the generated rc stubs
directly (no framework helpers), standing in for a stock restorecommerce
client like acs-client.
"""

import json

import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen.rc import access_control_pb2 as rc_ac
from access_control_srv_tpu.srv.gen.rc import commandinterface_pb2 as rc_ci
from access_control_srv_tpu.srv.gen.rc import health_pb2 as rc_health
from access_control_srv_tpu.srv.gen.rc import policy_pb2 as rc_policy
from access_control_srv_tpu.srv.gen.rc import resource_base_pb2 as rc_rb
from access_control_srv_tpu.srv.gen.rc import rule_pb2 as rc_rule
from access_control_srv_tpu.srv.transport_grpc import GrpcServer

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"


@pytest.fixture(scope="module")
def rig():
    import os

    seed = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "seed_data",
    )
    worker = Worker().start({
        "policies": {"type": "database"},
        "seed_data": {
            "policy_sets": os.path.join(seed, "policy_sets.yaml"),
            "policies": os.path.join(seed, "policies.yaml"),
            "rules": os.path.join(seed, "rules.yaml"),
        },
    })
    server = GrpcServer(worker, "127.0.0.1:0").start()
    import grpc

    channel = grpc.insecure_channel(server.addr)
    yield worker, channel
    channel.close()
    server.stop()
    worker.stop()


def _call(channel, path, request, response_cls):
    rpc = channel.unary_unary(
        path,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=response_cls.FromString,
    )
    return rpc(request)


def _rc_request(role):
    msg = rc_ac.Request()
    msg.target.subjects.add(id=URNS["role"], value=role)
    msg.target.subjects.add(id=URNS["subjectID"], value="u1")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
    msg.context.subject.value = json.dumps({
        "id": "u1",
        "role_associations": [{"role": role, "attributes": []}],
        "hierarchical_scopes": [],
    }).encode()
    return msg


def test_is_allowed_under_reference_name(rig):
    _, channel = rig
    resp = _call(
        channel,
        "/io.restorecommerce.access_control.AccessControlService/IsAllowed",
        _rc_request("superadministrator-r-id"),
        rc_ac.Response,
    )
    assert resp.decision == rc_ac.Response.PERMIT
    assert resp.operation_status.code == 200

    resp2 = _call(
        channel,
        "/io.restorecommerce.access_control.AccessControlService/IsAllowed",
        _rc_request("nobody-role"),
        rc_ac.Response,
    )
    assert resp2.decision == rc_ac.Response.INDETERMINATE


def test_what_is_allowed_under_reference_name(rig):
    _, channel = rig
    rq = _call(
        channel,
        "/io.restorecommerce.access_control.AccessControlService/WhatIsAllowed",
        _rc_request("superadministrator-r-id"),
        rc_ac.ReverseQuery,
    )
    assert len(rq.policy_sets) >= 1
    ps = rq.policy_sets[0]
    assert ps.id
    assert ps.policies and ps.policies[0].rules


def test_rule_crud_under_reference_names(rig):
    worker, channel = rig
    # create a rule via the reference RuleService wire
    rule_list = rc_rule.RuleList()
    rule = rule_list.items.add()
    rule.id = "rc-wire-rule"
    rule.name = "rc-wire"
    rule.effect = rc_rule.PERMIT
    rule.target.subjects.add(id=URNS["role"], value="rc-wire-role")
    rule.target.resources.add(id=URNS["entity"], value=ORG)
    resp = _call(channel, "/io.restorecommerce.rule.RuleService/Create",
                 rule_list, rc_rule.RuleListResponse)
    assert resp.operation_status.code == 200
    assert resp.items[0].payload.id == "rc-wire-rule"

    # attach to the seeded policy via PolicyService/Update
    read = _call(channel, "/io.restorecommerce.policy.PolicyService/Read",
                 rc_rb.ReadRequest(), rc_policy.PolicyListResponse)
    assert read.operation_status.code == 200 and read.items
    pol = rc_policy.Policy()
    pol.CopyFrom(read.items[0].payload)
    pol.rules.append("rc-wire-rule")
    upd = rc_policy.PolicyList()
    upd.items.add().CopyFrom(pol)
    resp = _call(channel, "/io.restorecommerce.policy.PolicyService/Update",
                 upd, rc_policy.PolicyListResponse)
    assert resp.operation_status.code == 200

    # decision visible through the reference PDP wire
    resp = _call(
        channel,
        "/io.restorecommerce.access_control.AccessControlService/IsAllowed",
        _rc_request("rc-wire-role"),
        rc_ac.Response,
    )
    assert resp.decision == rc_ac.Response.PERMIT

    # filtered read via the resource-base DSL
    req = rc_rb.ReadRequest()
    group = req.filters.add()
    group.filters.add(field="id", operation=rc_rb.Filter.Operation.Value("eq"),
                      value="rc-wire-rule")
    read = _call(channel, "/io.restorecommerce.rule.RuleService/Read",
                 req, rc_rule.RuleListResponse)
    assert [i.payload.id for i in read.items] == ["rc-wire-rule"]

    # delete + restore the seeded policy
    dreq = rc_rb.DeleteRequest()
    dreq.ids.append("rc-wire-rule")
    dresp = _call(channel, "/io.restorecommerce.rule.RuleService/Delete",
                  dreq, rc_rb.DeleteResponse)
    assert dresp.operation_status.code == 200
    pol.rules.pop()
    upd = rc_policy.PolicyList()
    upd.items.add().CopyFrom(pol)
    _call(channel, "/io.restorecommerce.policy.PolicyService/Update",
          upd, rc_policy.PolicyListResponse)


def test_command_interface_under_reference_name(rig):
    _, channel = rig
    req = rc_ci.CommandRequest(name="version")
    resp = _call(
        channel,
        "/io.restorecommerce.commandinterface.CommandInterfaceService/Command",
        req, rc_ci.CommandResponse,
    )
    result = json.loads(resp.result.value)
    assert "version" in result


def test_health_under_standard_name(rig):
    _, channel = rig
    resp = _call(channel, "/grpc.health.v1.Health/Check",
                 rc_health.HealthCheckRequest(), rc_health.HealthCheckResponse)
    assert resp.status == rc_health.HealthCheckResponse.SERVING


def test_obligations_cross_the_reference_wire(rig):
    """Property-masking obligations flow through the rc ReverseQuery
    shape (repeated Attribute with nested attributes)."""
    worker, channel = rig
    # a property-scoped rule produces masked-property obligations for
    # requests asking for extra properties
    rule_list = rc_rule.RuleList()
    rule = rule_list.items.add()
    rule.id = "rc-prop-rule"
    rule.name = "rc-prop"
    rule.effect = rc_rule.PERMIT
    rule.target.subjects.add(id=URNS["role"], value="rc-prop-role")
    res = rule.target.resources.add(id=URNS["entity"], value=ORG)
    rule.target.resources.add(id=URNS["property"], value=ORG + "#name")
    _call(channel, "/io.restorecommerce.rule.RuleService/Create",
          rule_list, rc_rule.RuleListResponse)
    read = _call(channel, "/io.restorecommerce.policy.PolicyService/Read",
                 rc_rb.ReadRequest(), rc_policy.PolicyListResponse)
    pol = rc_policy.Policy()
    pol.CopyFrom(read.items[0].payload)
    pol.rules.append("rc-prop-rule")
    upd = rc_policy.PolicyList()
    upd.items.add().CopyFrom(pol)
    _call(channel, "/io.restorecommerce.policy.PolicyService/Update",
          upd, rc_policy.PolicyListResponse)
    try:
        msg = _rc_request("rc-prop-role")
        msg.target.resources.add(id=URNS["property"], value=ORG + "#name")
        msg.target.resources.add(id=URNS["property"], value=ORG + "#secret")
        rq = _call(
            channel,
            "/io.restorecommerce.access_control.AccessControlService"
            "/WhatIsAllowed",
            msg, rc_ac.ReverseQuery,
        )
        assert rq.obligations, "expected masked-property obligations"
        flat = [
            a.value
            for ob in rq.obligations
            for a in ob.attributes
        ]
        assert any("secret" in v for v in flat), flat
    finally:
        dreq = rc_rb.DeleteRequest()
        dreq.ids.append("rc-prop-rule")
        _call(channel, "/io.restorecommerce.rule.RuleService/Delete",
              dreq, rc_rb.DeleteResponse)
        pol.rules.pop()
        upd = rc_policy.PolicyList()
        upd.items.add().CopyFrom(pol)
        _call(channel, "/io.restorecommerce.policy.PolicyService/Update",
              upd, rc_policy.PolicyListResponse)


def test_read_pagination_and_sort(rig):
    worker, channel = rig
    rule_list = rc_rule.RuleList()
    for i in range(5):
        rule = rule_list.items.add()
        rule.id = f"rc-page-{i}"
        rule.name = f"page-{i}"
        rule.effect = rc_rule.PERMIT
        rule.target.subjects.add(id=URNS["role"], value=f"pg-{i}")
    _call(channel, "/io.restorecommerce.rule.RuleService/Create",
          rule_list, rc_rule.RuleListResponse)
    try:
        req = rc_rb.ReadRequest()
        group = req.filters.add()
        group.operator = rc_rb.FilterOp.Operator.Value("or")
        for i in range(5):
            group.filters.add(
                field="id",
                operation=rc_rb.Filter.Operation.Value("eq"),
                value=f"rc-page-{i}",
            )
        req.sorts.add(field="id", order=rc_rb.Sort.DESCENDING)
        req.limit = 2
        req.offset = 1
        read = _call(channel, "/io.restorecommerce.rule.RuleService/Read",
                     req, rc_rule.RuleListResponse)
        assert [i.payload.id for i in read.items] == [
            "rc-page-3", "rc-page-2"
        ]
    finally:
        dreq = rc_rb.DeleteRequest()
        dreq.ids.extend(f"rc-page-{i}" for i in range(5))
        _call(channel, "/io.restorecommerce.rule.RuleService/Delete",
              dreq, rc_rb.DeleteResponse)


# --------------------------------------------------------------------------
# Golden wire-byte vectors (VERDICT weak item 7).  Everything above
# round-trips through stubs generated from the SAME reconstructed protos, so
# a field-number error in the reconstruction would pass every test and break
# the first stock acs-client.  These vectors hand-encode the two
# highest-risk messages — access_control.Request and access_control.Response,
# the pair every rc decision call crosses the wire with — at the raw
# tag/varint level, independent of any protobuf runtime.  If a regenerated
# stub ever disagrees with these bytes, the field numbers moved.


def _tag(field_no, wire_type=2):
    """Proto wire tag byte(s): (field_no << 3) | wire_type, varint."""
    return _varint((field_no << 3) | wire_type)


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _ld(field_no, payload):
    """Length-delimited field (strings, bytes, sub-messages)."""
    if isinstance(payload, str):
        payload = payload.encode()
    return _tag(field_no, 2) + _varint(len(payload)) + payload


def _attr(attr_id, value, nested=()):
    """attribute.Attribute: id=1, value=2, attributes=3 (recursive)."""
    out = _ld(1, attr_id) + _ld(2, value)
    for sub in nested:
        out += _ld(3, sub)
    return out


def test_request_golden_wire_bytes():
    """access_control.Request: target=1 (rule.Target: subjects=1,
    resources=2, actions=3 of attribute.Attribute) and context=2
    (Context: subject=1 as google.protobuf.Any whose value=2 carries JSON
    bytes — the reference unmarshals exactly that shape,
    accessControlService.ts:103-125)."""
    subject_json = b'{"id":"u1","role_associations":[]}'
    golden = (
        _ld(1,  # Request.target
            _ld(1, _attr(URNS["role"], "admin-r-id"))          # subjects
            + _ld(2, _attr(URNS["entity"], ORG))               # resources
            + _ld(3, _attr(URNS["actionID"], URNS["read"])))   # actions
        + _ld(2,  # Request.context
              _ld(1,  # Context.subject: google.protobuf.Any
                  _ld(2, subject_json)))  # Any.value (type_url unset)
    )

    msg = rc_ac.Request()
    msg.target.subjects.add(id=URNS["role"], value="admin-r-id")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
    msg.context.subject.value = subject_json
    assert msg.SerializeToString(deterministic=True) == golden

    # and the stubs must parse the hand-encoded bytes back to the fields
    parsed = rc_ac.Request.FromString(golden)
    assert parsed.target.subjects[0].id == URNS["role"]
    assert parsed.target.resources[0].value == ORG
    assert parsed.target.actions[0].value == URNS["read"]
    assert parsed.context.subject.value == subject_json


def test_response_golden_wire_bytes():
    """access_control.Response: decision=1 (enum varint), obligations=2
    (attribute.Attribute incl. the nested attributes=3 the masked-property
    obligations use), evaluation_cacheable=3 (bool varint),
    operation_status=4 (status.OperationStatus: code=1, message=2).
    DENY(1) keeps the enum on the wire (proto3 drops zero defaults)."""
    prop = URNS["property"]
    golden = (
        _tag(1, 0) + _varint(1)  # decision = DENY
        + _ld(2, _attr(  # obligations: masked-property shape
            "urn:restorecommerce:acs:names:obligation:maskedProperty",
            ORG,
            nested=[_attr(prop, ORG + "#secret")]))
        + _tag(3, 0) + _varint(1)  # evaluation_cacheable = true
        + _ld(4, _tag(1, 0) + _varint(200) + _ld(2, "success"))
    )

    msg = rc_ac.Response()
    msg.decision = rc_ac.Response.DENY
    ob = msg.obligations.add(
        id="urn:restorecommerce:acs:names:obligation:maskedProperty",
        value=ORG,
    )
    ob.attributes.add(id=prop, value=ORG + "#secret")
    msg.evaluation_cacheable = True
    msg.operation_status.code = 200
    msg.operation_status.message = "success"
    assert msg.SerializeToString(deterministic=True) == golden

    parsed = rc_ac.Response.FromString(golden)
    assert parsed.decision == rc_ac.Response.DENY
    assert parsed.obligations[0].attributes[0].value == ORG + "#secret"
    assert parsed.evaluation_cacheable is True
    assert parsed.operation_status.code == 200


def test_policy_set_crud_under_reference_names(rig):
    from access_control_srv_tpu.srv.gen.rc import policy_set_pb2 as rc_ps

    worker, channel = rig
    ps_list = rc_ps.PolicySetList()
    ps = ps_list.items.add()
    ps.id = "rc-ps"
    ps.name = "rc-ps"
    ps.combining_algorithm = (
        "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
        "deny-overrides"
    )
    resp = _call(channel,
                 "/io.restorecommerce.policy_set.PolicySetService/Create",
                 ps_list, rc_ps.PolicySetListResponse)
    assert resp.operation_status.code == 200
    try:
        req = rc_rb.ReadRequest()
        group = req.filters.add()
        group.filters.add(field="id",
                          operation=rc_rb.Filter.Operation.Value("eq"),
                          value="rc-ps")
        read = _call(channel,
                     "/io.restorecommerce.policy_set.PolicySetService/Read",
                     req, rc_ps.PolicySetListResponse)
        assert [i.payload.id for i in read.items] == ["rc-ps"]
        assert read.items[0].payload.combining_algorithm.endswith(
            "deny-overrides")
        # upsert mutates in place
        ps.name = "rc-ps-renamed"
        upd = rc_ps.PolicySetList()
        upd.items.add().CopyFrom(ps)
        resp = _call(
            channel,
            "/io.restorecommerce.policy_set.PolicySetService/Upsert",
            upd, rc_ps.PolicySetListResponse)
        assert resp.items[0].payload.name == "rc-ps-renamed"
    finally:
        dreq = rc_rb.DeleteRequest()
        dreq.ids.append("rc-ps")
        dresp = _call(
            channel,
            "/io.restorecommerce.policy_set.PolicySetService/Delete",
            dreq, rc_rb.DeleteResponse)
        assert dresp.operation_status.code == 200
