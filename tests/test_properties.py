"""Property-level matcher matrix: isAllowed decisions and whatIsAllowed
reverse queries + masking obligations over property-scoped rules.

Suite-4 analog of the reference (test/properties.spec.ts); the expected
decisions, filtered-rule sets and obligation contents transcribe the
reference's asserted outcomes for the equivalent scenarios
(src/core/accessController.ts:465-654 property matcher,
:592-640 obligation accumulation, :578-581,644-647 skip-deny-rule).
"""

import pytest

from access_control_srv_tpu.models import Decision

from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
LOC = "urn:restorecommerce:acs:model:location.Location"
READ = URNS["read"]
MODIFY = URNS["modify"]
EXECUTE = URNS["execute"]
ENTITY = URNS["entity"]
MASKED = URNS["maskedProperty"]

LOC_ID = LOC + "#id"
LOC_NAME = LOC + "#name"
LOC_DESC = LOC + "#description"
ORG_ID = ORG + "#id"
ORG_NAME = ORG + "#name"
ORG_DESC = ORG + "#description"


def member_request(**kwargs):
    defaults = dict(
        subject_id="ada",
        subject_role="member",
        role_scoping_entity=ORG,
        role_scoping_instance="Org1",
        owner_indicatory_entity=ORG,
        owner_instance="Org1",
        action_type=READ,
    )
    defaults.update(kwargs)
    return build_request(**defaults)


def rule_ids(reverse_query, policy_index=0, set_index=0):
    return [
        r.id for r in reverse_query.policy_sets[set_index].policies[policy_index].rules
    ]


def policy_ids(reverse_query, set_index=0):
    return [p.id for p in reverse_query.policy_sets[set_index].policies]


def obligation_pairs(reverse_query):
    """Flatten obligations to (entity_value, [masked property values])."""
    out = []
    for ob in reverse_query.obligations:
        assert ob.id == ENTITY
        masked = []
        for a in ob.attributes:
            assert a.id == MASKED
            masked.append(a.value)
        out.append((ob.value, masked))
    return out


# --------------------------------------------------------------- operations


class TestMultipleOperations:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("ops_multi.yml")

    def test_deny_execute_out_of_scope(self, engine):
        # subject scoped to Org2 with an HR subtree rooted at Org3; the
        # operations are owned by Org1 -> rule HR check fails, fallback DENY
        request = member_request(
            role_scoping_instance="Org2",
            resource_type=["mutation.opA", "mutation.opB"],
            resource_id=["mutation.opA", "mutation.opB"],
            action_type=EXECUTE,
            owner_instance=["Org1", "Org1"],
            hierarchical_scopes=[{"id": "Org3", "children": []}],
        )
        assert engine.is_allowed(request).decision == Decision.DENY

    def test_permit_execute_in_scope(self, engine):
        # operation matching is sticky across request attributes: opB has no
        # rule but opA's match carries the request (ref :506-508)
        request = member_request(
            resource_type=["mutation.opA", "mutation.opB"],
            resource_id=["mutation.opA", "mutation.opB"],
            action_type=EXECUTE,
            owner_instance=["Org1", "Org1"],
        )
        assert engine.is_allowed(request).decision == Decision.PERMIT


# ------------------------------------------------- single entity with props


class TestIsAllowedSingleEntity:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_single.yml")

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_permit_subset_props(self, engine, action):
        for props in ([LOC_ID, LOC_NAME], [LOC_ID]):
            request = member_request(
                resource_type=LOC, resource_id="L1",
                resource_property=props, action_type=action,
            )
            assert engine.is_allowed(request).decision == Decision.PERMIT

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_deny_extra_prop(self, engine, action):
        request = member_request(
            resource_type=LOC, resource_id="L1",
            resource_property=[LOC_ID, LOC_NAME, LOC_DESC], action_type=action,
        )
        assert engine.is_allowed(request).decision == Decision.DENY

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_deny_no_props_in_request(self, engine, action):
        # rule enumerates properties, request names none -> cannot prove the
        # subset relationship -> fallback DENY
        request = member_request(
            resource_type=LOC, resource_id="L1", action_type=action,
        )
        assert engine.is_allowed(request).decision == Decision.DENY


class TestWhatIsAllowedSingleEntity:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_single.yml")

    def what(self, engine, **kwargs):
        kwargs.setdefault("role_scoping_instance", "SuperOrg1")
        return engine.what_is_allowed(member_request(**kwargs))

    def assert_location_tree(self, rq):
        """The Location policy survives with the read rule + fallback; the
        Organization policy (entity-targeted) is filtered out."""
        assert len(rq.policy_sets) == 1
        assert policy_ids(rq) == ["pol_location"]
        assert rule_ids(rq) == ["r_loc_read", "r_loc_fallback"]
        rule = rq.policy_sets[0].policies[0].rules[0]
        assert [a.value for a in rule.target.subjects] == ["member", ORG]
        assert [a.value for a in rule.target.resources] == [LOC, LOC_ID, LOC_NAME]
        assert [a.value for a in rule.target.actions] == [READ]

    def test_empty_obligation_subset_props(self, engine):
        for props in ([LOC_ID, LOC_NAME], [LOC_NAME]):
            rq = self.what(
                engine, resource_type=LOC, resource_id="L1",
                resource_property=props,
            )
            self.assert_location_tree(rq)
            assert rq.obligations == []

    def test_obligation_for_extra_prop(self, engine):
        rq = self.what(
            engine, resource_type=LOC, resource_id="L1",
            resource_property=[LOC_ID, LOC_NAME, LOC_DESC],
        )
        self.assert_location_tree(rq)
        pairs = obligation_pairs(rq)
        assert len(pairs) == 1
        assert pairs[0][0] == LOC
        assert pairs[0][1] == [LOC_DESC]

    def test_only_deny_rule_without_props(self, engine):
        rq = self.what(engine, resource_type=LOC, resource_id="L1")
        assert len(rq.policy_sets) == 1
        assert policy_ids(rq) == ["pol_location"]
        assert rule_ids(rq) == ["r_loc_fallback"]
        assert rq.policy_sets[0].policies[0].rules[0].effect == "DENY"
        assert rq.obligations == []


# --------------------------------------------- rules without property attrs


class TestRulesWithoutProperties:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_rules_noprop.yml")

    def test_is_allowed_any_props(self, engine):
        for props in ([LOC_ID, LOC_NAME], None):
            request = member_request(
                resource_type=LOC, resource_id="L1", resource_property=props,
            )
            assert engine.is_allowed(request).decision == Decision.PERMIT

    def test_what_is_allowed_never_masks(self, engine):
        for props in ([LOC_ID, LOC_NAME], None):
            rq = engine.what_is_allowed(
                member_request(
                    role_scoping_instance="SuperOrg1",
                    resource_type=LOC, resource_id="L1",
                    resource_property=props,
                )
            )
            assert rule_ids(rq) == ["r_loc_read", "r_loc_fallback"]
            rule = rq.policy_sets[0].policies[0].rules[0]
            assert [a.value for a in rule.target.resources] == [LOC]
            assert rq.obligations == []


# ----------------------------------------- permit-all + deny-one-prop pairs


class TestIsAllowedMaskRules:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_multi_rules.yml")

    def test_deny_when_denied_prop_requested(self, engine):
        for props in ([LOC_ID, LOC_NAME, LOC_DESC], [LOC_DESC]):
            request = member_request(
                resource_type=LOC, resource_id="L1", resource_property=props,
            )
            assert engine.is_allowed(request).decision == Decision.DENY

    def test_permit_when_denied_prop_absent(self, engine):
        request = member_request(
            resource_type=LOC, resource_id="L1",
            resource_property=[LOC_ID, LOC_NAME],
        )
        assert engine.is_allowed(request).decision == Decision.PERMIT

    def test_deny_without_props(self, engine):
        # no request properties -> the DENY rule cannot be ruled out
        request = member_request(resource_type=LOC, resource_id="L1")
        assert engine.is_allowed(request).decision == Decision.DENY

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_supervisor_unrestricted(self, engine, action):
        for props in ([LOC_ID, LOC_NAME, LOC_DESC], None):
            request = member_request(
                subject_role="supervisor",
                resource_type=LOC, resource_id="L1",
                resource_property=props, action_type=action,
            )
            assert engine.is_allowed(request).decision == Decision.PERMIT


class TestWhatIsAllowedMaskRules:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_multi_rules.yml")

    def what(self, engine, **kwargs):
        kwargs.setdefault("role_scoping_instance", "SuperOrg1")
        return engine.what_is_allowed(member_request(**kwargs))

    def test_obligation_when_denied_prop_requested(self, engine):
        for props in ([LOC_ID, LOC_NAME, LOC_DESC], [LOC_DESC]):
            rq = self.what(
                engine, resource_type=LOC, resource_id="L1",
                resource_property=props,
            )
            assert rule_ids(rq) == ["r_read_all", "r_read_deny_desc"]
            pairs = obligation_pairs(rq)
            assert len(pairs) == 1
            assert pairs[0][0] == LOC
            assert pairs[0][1] == [LOC_DESC]

    def test_no_obligation_for_allowed_props(self, engine):
        rq = self.what(
            engine, resource_type=LOC, resource_id="L1",
            resource_property=[LOC_ID, LOC_NAME],
        )
        assert rule_ids(rq) == ["r_read_all", "r_read_deny_desc"]
        assert rq.obligations == []

    def test_obligation_without_request_props(self, engine):
        # masked property comes from the DENY rule's own property attribute;
        # it is pushed once per request attribute (entity + resourceID = 2)
        # because with no request properties the reference's mask branch
        # fires on every attribute iteration
        # (reference: accessController.ts:622-640)
        rq = self.what(engine, resource_type=LOC, resource_id="L1")
        assert rule_ids(rq) == ["r_read_all", "r_read_deny_desc"]
        pairs = obligation_pairs(rq)
        assert len(pairs) == 1
        assert pairs[0][0] == LOC
        assert pairs[0][1] == [LOC_DESC] * 2

    def test_supervisor_no_obligations(self, engine):
        for props in ([LOC_ID, LOC_NAME, LOC_DESC], None):
            rq = self.what(
                engine, subject_role="supervisor",
                resource_type=LOC, resource_id="L1", resource_property=props,
            )
            assert rule_ids(rq) == ["r_read_super"]
            assert rq.obligations == []


# -------------------------------------------------------- multiple entities


def multi_entity_request(loc_props=None, org_props=None, **kwargs):
    props = []
    if loc_props or org_props:
        props = [loc_props or [], org_props or []]
    defaults = dict(
        resource_type=[LOC, ORG],
        resource_id=["L1", "O1"],
        resource_property=props or None,
        owner_instance=["Org1", "Org1"],
    )
    defaults.update(kwargs)
    return member_request(**defaults)


class TestIsAllowedMultipleEntities:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_single.yml")

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_permit_subset_props_both_entities(self, engine, action):
        for loc_props, org_props in (
            ([LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME]),
            ([LOC_ID], [ORG_ID]),
        ):
            request = multi_entity_request(loc_props, org_props, action_type=action)
            assert engine.is_allowed(request).decision == Decision.PERMIT

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_deny_extra_prop_on_one_entity(self, engine, action):
        request = multi_entity_request(
            [LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME, ORG_DESC], action_type=action,
        )
        assert engine.is_allowed(request).decision == Decision.DENY

    @pytest.mark.parametrize("action", [READ, MODIFY])
    def test_deny_without_props(self, engine, action):
        request = multi_entity_request(action_type=action)
        assert engine.is_allowed(request).decision == Decision.DENY


class TestWhatIsAllowedMultipleEntities:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_single.yml")

    def assert_both_policies(self, rq):
        assert policy_ids(rq) == ["pol_location", "pol_organization"]
        assert rule_ids(rq, 0) == ["r_loc_read", "r_loc_fallback"]
        assert rule_ids(rq, 1) == ["r_org_read", "r_org_fallback"]

    def test_empty_obligations_subset_props(self, engine):
        for loc_props, org_props in (
            ([LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME]),
            ([LOC_ID], [ORG_ID]),
        ):
            rq = engine.what_is_allowed(
                multi_entity_request(loc_props, org_props)
            )
            self.assert_both_policies(rq)
            assert rq.obligations == []

    def test_obligations_per_entity(self, engine):
        rq = engine.what_is_allowed(
            multi_entity_request(
                [LOC_ID, LOC_NAME, LOC_DESC], [ORG_ID, ORG_NAME, ORG_DESC]
            )
        )
        self.assert_both_policies(rq)
        pairs = obligation_pairs(rq)
        assert len(pairs) == 2
        assert pairs[0][0] == LOC and pairs[0][1] == [LOC_DESC]
        assert pairs[1][0] == ORG and pairs[1][1] == [ORG_DESC]

    def test_only_deny_rules_without_props(self, engine):
        rq = engine.what_is_allowed(multi_entity_request())
        assert policy_ids(rq) == ["pol_location", "pol_organization"]
        assert rule_ids(rq, 0) == ["r_loc_fallback"]
        assert rule_ids(rq, 1) == ["r_org_fallback"]
        assert rq.obligations == []


# --------------------------------- multiple entities with permit+deny pairs


class TestMultiEntityMaskRules:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("props_multi_rules_entities.yml")

    def test_is_allowed_permit_without_denied_props(self, engine):
        request = multi_entity_request([LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME])
        assert engine.is_allowed(request).decision == Decision.PERMIT

    def test_is_allowed_deny_with_denied_prop(self, engine):
        request = multi_entity_request(
            [LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME, ORG_DESC]
        )
        assert engine.is_allowed(request).decision == Decision.DENY

    def test_is_allowed_deny_without_props(self, engine):
        request = multi_entity_request()
        assert engine.is_allowed(request).decision == Decision.DENY

    def test_what_is_allowed_empty_obligation(self, engine):
        rq = engine.what_is_allowed(
            multi_entity_request([LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME])
        )
        assert rule_ids(rq, 0) == ["r_loc_all", "r_loc_deny_desc"]
        assert rule_ids(rq, 1) == ["r_org_all", "r_org_deny_desc"]
        assert rq.obligations == []

    def test_what_is_allowed_one_entity_obligation(self, engine):
        rq = engine.what_is_allowed(
            multi_entity_request([LOC_ID, LOC_NAME], [ORG_ID, ORG_NAME, ORG_DESC])
        )
        assert rule_ids(rq, 0) == ["r_loc_all", "r_loc_deny_desc"]
        assert rule_ids(rq, 1) == ["r_org_all", "r_org_deny_desc"]
        pairs = obligation_pairs(rq)
        assert len(pairs) == 1
        assert pairs[0][0] == ORG and pairs[0][1] == [ORG_DESC]

    def test_what_is_allowed_obligations_without_props(self, engine):
        # subject may read everything except the two denied properties;
        # with no properties in the request both DENY rules mask their own
        # property attribute
        rq = engine.what_is_allowed(multi_entity_request())
        assert rule_ids(rq, 0) == ["r_loc_all", "r_loc_deny_desc"]
        assert rule_ids(rq, 1) == ["r_org_all", "r_org_deny_desc"]
        pairs = obligation_pairs(rq)
        assert len(pairs) == 2
        # duplicate counts mirror the reference's per-request-attribute mask
        # pushes with sticky entityMatch: the Location deny rule fires on all
        # 4 request attributes (entityMatch stays true after the Location
        # entity matched), the Organization rule only on its own 2
        # (reference: accessController.ts:493,622-640)
        assert pairs[0][0] == LOC and pairs[0][1] == [LOC_DESC] * 4
        assert pairs[1][0] == ORG and pairs[1][1] == [ORG_DESC] * 2
