"""Adaptive per-batch padding caps (VERDICT r2 item 7): deep-HR / wide
traffic that used to overflow the fixed caps must stay kernel-eligible
(bucketed arrays), with per-reason ineligibility counters for what still
falls back."""

import numpy as np

from access_control_srv_tpu.models import Attribute, Request, Target
from access_control_srv_tpu.ops import compile_policies, encode_requests
from access_control_srv_tpu.ops.encode import _CAPS_CEIL, compute_caps

from .test_kernel_differential import run_differential
from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"


def deep_scopes(depth: int, width: int = 2, prefix: str = "o"):
    def node(path):
        children = []
        if len(path) < depth:
            children = [node(path + [i]) for i in range(width)]
        out = {"id": f"{prefix}-" + "-".join(map(str, path))}
        if children:
            out["children"] = children
        return out

    return [node([0])]


def deep_request(depth: int):
    return build_request(
        subject_id="ada", subject_role="member",
        role_scoping_entity=ORG, role_scoping_instance="o-0",
        resource_type=ORG, resource_id="X",
        action_type=URNS["read"],
        owner_indicatory_entity=ORG, owner_instance="o-0-0-1",
        hierarchical_scopes=[
            {"id": s["id"], "role": "member", **(
                {"children": s["children"]} if "children" in s else {})}
            for s in deep_scopes(depth)
        ],
    )


def test_deep_hr_stays_eligible_and_correct():
    """Depth-7 trees flatten to >32 HR pairs (the old fixed NHR): the
    batch buckets up and the rows stay on device, bit-identical."""
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    reqs = [deep_request(d) for d in (3, 5, 7)]
    caps = compute_caps(reqs, engine.urns)
    assert caps["NHR"] > 32  # genuinely beyond the old fixed cap
    batch = encode_requests(reqs, compiled)
    assert batch.eligible.all(), batch.ineligible_reasons
    n = run_differential(engine, reqs)
    assert n == len(reqs)


def test_caps_ceiling_still_marks_with_reason():
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    # depth beyond the NHR ceiling: falls back with a counted reason
    deep = deep_request(11)
    flat_pairs = 2 ** 11
    assert flat_pairs > _CAPS_CEIL["NHR"]
    batch = encode_requests([deep], compiled)
    assert not batch.eligible[0]
    assert batch.ineligible_reasons.get("hr-cap") == 1


def test_common_traffic_keeps_floor_shapes():
    """Requests within the floors must not inflate any dimension (one
    compiled kernel shape for steady-state serving)."""
    engine = make_engine("basic_policies.yml")
    reqs = [build_request(subject_id="ada", subject_role="member",
                          resource_type=ORG, resource_id="X",
                          action_type=URNS["read"]) for _ in range(8)]
    from access_control_srv_tpu.ops.encode import _CAPS_FLOOR

    assert compute_caps(reqs, engine.urns) == _CAPS_FLOOR


def test_reason_counter_for_token_subjects():
    engine = make_engine("basic_policies.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    req = Request(
        target=Target(
            subjects=[Attribute(id=URNS["subjectID"], value="ada")],
            resources=[Attribute(id=URNS["entity"], value=ORG)],
            actions=[Attribute(id=URNS["actionID"], value=URNS["read"])],
        ),
        context={"resources": [], "subject": {"token": "tok"}},
    )
    batch = encode_requests([req], compiled)
    assert not batch.eligible[0]
    assert batch.ineligible_reasons == {"token-subject": 1}


def test_evaluator_splits_mixed_depth_batches():
    """A few deep-HR rows must not inflate the compiled shapes of the
    whole batch: the evaluator encodes floor-fitting rows separately and
    all decisions stay bit-identical to the oracle."""
    from access_control_srv_tpu.ops.encode import fits_floor, request_needs
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    engine = make_engine("role_scopes.yml")
    ev = HybridEvaluator(engine)
    shallow = [build_request(subject_id="ada", subject_role="member",
                             role_scoping_entity=ORG,
                             role_scoping_instance="Org1",
                             resource_type=ORG, resource_id=f"X{i}",
                             action_type=URNS["read"]) for i in range(12)]
    deep = [deep_request(d) for d in (6, 7)]
    assert all(fits_floor(request_needs(r, engine.urns)) for r in shallow)
    assert not any(fits_floor(request_needs(r, engine.urns)) for r in deep)

    mixed = shallow[:6] + deep + shallow[6:]
    responses = ev.is_allowed_batch(mixed)
    for req, resp in zip(mixed, responses):
        assert resp.decision == engine.is_allowed(req).decision
