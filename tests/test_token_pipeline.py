"""Host-side eligibility pipeline (ISSUE 3 tentpole): batched token
resolution + HR-scope rendezvous keep token-authenticated rows on the
kernel, and adapter context-query prefetch keeps context-query rows on the
kernel — every fused row bit-identical to the scalar oracle, every failure
mode degrading per-row to the oracle, never to a changed decision."""

import copy
import threading
import time

import pytest

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.errors import ContextQueryTransportError
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.models import Attribute, Request, Target, Urns
from access_control_srv_tpu.ops import compile_policies, encode_requests
from access_control_srv_tpu.srv.adapters import GraphQLAdapter
from access_control_srv_tpu.srv.cache import HRScopeProvider, SubjectCache
from access_control_srv_tpu.srv.evaluator import HybridEvaluator
from access_control_srv_tpu.srv.identity import (
    CachingIdentityClient,
    StaticIdentityClient,
    TokenResolutionCache,
)
from access_control_srv_tpu.srv.telemetry import Telemetry

URNS = Urns()
ORG = "urn:restorecommerce:acs:model:organization.Organization"
WIDGET = "urn:restorecommerce:acs:model:widget.Widget"
PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
DO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides"


def role_tree(n_roles=6, entities=(ORG, WIDGET)):
    policies = []
    rid = 0
    for entity in entities:
        rules = []
        for r in range(n_roles):
            rules.append({
                "id": f"r{rid}",
                "target": {
                    "subjects": [{"id": URNS["role"], "value": f"role-{r}"}],
                    "resources": [{"id": URNS["entity"], "value": entity}],
                    "actions": [{"id": URNS["actionID"],
                                 "value": URNS["read"]}],
                },
                "effect": "PERMIT" if rid % 3 else "DENY",
            })
            rid += 1
        policies.append({"id": f"p-{entity[-6:]}", "combining_algorithm": PO,
                         "rules": rules})
    return {"policy_sets": [
        {"id": "s", "combining_algorithm": DO, "policies": policies}
    ]}


def token_request(i, token, entity=ORG):
    """A request whose subject arrives as a bare token (the production
    shape): no id, no role associations — everything comes from
    resolution."""
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value=f"role-{i % 6}"),
                      Attribute(id=URNS["subjectID"], value=f"user-{i % 8}")],
            resources=[Attribute(id=URNS["entity"], value=entity),
                       Attribute(id=URNS["resourceID"], value=f"res-{i}")],
            actions=[Attribute(id=URNS["actionID"], value=URNS["read"])],
        ),
        context={"resources": [], "subject": {"token": token}},
    )


def payload_for(i):
    return {
        "id": f"user-{i % 8}",
        "tokens": [{"token": f"tok-{i % 8}", "interactive": True}],
        "role_associations": [{"role": f"role-{i % 6}", "attributes": []}],
    }


def wired_engine(doc=None, scopes=()):
    engine = AccessController()
    for ps in load_policy_sets(doc or role_tree()):
        engine.update_policy_set(ps)
    ids = StaticIdentityClient()
    for i in range(8):
        ids.register(f"tok-{i}", payload_for(i))
    engine.identity_client = CachingIdentityClient(ids)
    cache = SubjectCache()
    for i in range(8):
        cache.set(f"cache:user-{i}:hrScopes", list(scopes))
    engine.hr_scope_provider = HRScopeProvider(cache)
    return engine


def assert_bit_identical(responses, oracle):
    for b, (got, want) in enumerate(zip(responses, oracle)):
        assert got.decision == want.decision, (b, got.decision, want.decision)
        assert got.evaluation_cacheable == want.evaluation_cacheable, b
        assert got.operation_status.code == want.operation_status.code, b
        assert got.operation_status.message == want.operation_status.message, b


class TestTokenResolutionEligibility:
    def test_resolved_token_rows_ride_the_kernel(self):
        engine = wired_engine()
        requests = [token_request(i, f"tok-{i % 8}") for i in range(32)]
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        telemetry = Telemetry()
        ev = HybridEvaluator(engine, telemetry=telemetry)
        copies = [copy.deepcopy(r) for r in requests]
        responses = ev.is_allowed_batch(copies)
        assert_bit_identical(responses, oracle)
        # the rows actually rode the device: encode after prepare shows
        # zero ineligible rows and the kernel path counter moved
        batch = encode_requests(copies, ev._compiled)
        assert batch.eligible.all(), batch.ineligible_reasons
        assert telemetry.paths.get("kernel") == len(requests)
        assert telemetry.paths.get("token-resolved") == len(requests)

    def test_resolution_failure_degrades_per_row_to_oracle(self):
        engine = wired_engine()
        requests = [
            token_request(i, f"tok-{i % 8}" if i % 2 else "unknown-token")
            for i in range(16)
        ]
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        telemetry = Telemetry()
        ev = HybridEvaluator(engine, telemetry=telemetry)
        copies = [copy.deepcopy(r) for r in requests]
        responses = ev.is_allowed_batch(copies)
        assert_bit_identical(responses, oracle)
        batch = encode_requests(copies, ev._compiled)
        assert int(batch.eligible.sum()) == 8
        assert batch.ineligible_reasons == {"token-unresolved": 8}
        assert telemetry.paths.get("token-unresolved") == 8

    def test_unprepared_token_rows_stay_ineligible(self):
        """Direct encodes (wire/native path) see unprepared requests: the
        pre-pipeline contract is unchanged."""
        engine = wired_engine()
        compiled = compile_policies(engine.policy_sets, engine.urns)
        batch = encode_requests([token_request(0, "tok-0")], compiled)
        assert not batch.eligible[0]
        assert batch.ineligible_reasons == {"token-subject": 1}

    def test_rendezvous_timeout_degrades_to_oracle(self):
        """A dead auth topic: resolution succeeds, the HR rendezvous times
        out, the subject keeps role associations but no scope list — the
        encoder sends the row to the oracle (missing-hr-scopes), which
        raises InvalidRequestContext exactly like the reference."""
        engine = wired_engine()

        class DeadTopic:
            def emit(self, *a, **k):
                pass

        engine.hr_scope_provider = HRScopeProvider(
            SubjectCache(), DeadTopic(), timeout_ms=50
        )
        ev = HybridEvaluator(engine)
        copies = [copy.deepcopy(token_request(i, f"tok-{i % 8}"))
                  for i in range(4)]
        ev.prepare_batch(copies)
        batch = encode_requests(copies, ev._compiled)
        assert not batch.eligible.any()
        assert batch.ineligible_reasons == {"missing-hr-scopes": 4}
        # ...and the oracle-served rows still match a fresh oracle walk
        requests = [token_request(i, f"tok-{i % 8}") for i in range(4)]
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        responses = ev.is_allowed_batch([copy.deepcopy(r) for r in requests])
        assert_bit_identical(responses, oracle)

    def test_batch_dedups_rpcs_and_rendezvous(self):
        """32 rows over 4 distinct tokens cost 4 identity RPCs (and zero
        on the next batch, served by the TTL cache)."""
        engine = wired_engine()
        calls = []
        inner = engine.identity_client.inner
        orig = inner.find_by_token

        def counting(token):
            calls.append(token)
            return orig(token)

        inner.find_by_token = counting
        ev = HybridEvaluator(engine)
        ev.prepare_batch([copy.deepcopy(token_request(i, f"tok-{i % 4}"))
                          for i in range(32)])
        assert sorted(calls) == [f"tok-{i}" for i in range(4)]
        ev.prepare_batch([copy.deepcopy(token_request(i, f"tok-{i % 4}"))
                          for i in range(32)])
        assert len(calls) == 4  # warm cache: no second round of RPCs

    def test_mixed_batch_token_plain_and_broken_rows(self):
        engine = wired_engine()
        requests = []
        for i in range(24):
            kind = i % 4
            if kind == 0:
                requests.append(token_request(i, f"tok-{i % 8}"))
            elif kind == 1:
                requests.append(token_request(i, "unknown-token"))
            elif kind == 2:  # plain resolved subject, no token
                r = token_request(i, "unused")
                r.context["subject"] = {
                    "id": f"user-{i % 8}",
                    "role_associations": [
                        {"role": f"role-{i % 6}", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                }
                requests.append(r)
            else:  # no target: host-side 400 DENY
                requests.append(Request(target=None, context={}))
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        ev = HybridEvaluator(engine)
        responses = ev.is_allowed_batch([copy.deepcopy(r) for r in requests])
        assert_bit_identical(responses, oracle)

    def test_wia_batch_resolves_tokens(self):
        """The reverse-query batch path prepares token rows too (the
        reference resolves tokens for whatIsAllowed as well)."""
        engine = wired_engine()
        requests = [token_request(i, f"tok-{i % 8}") for i in range(6)]
        oracle = [engine.what_is_allowed(copy.deepcopy(r)) for r in requests]
        ev = HybridEvaluator(engine)
        out = ev.what_is_allowed_batch([copy.deepcopy(r) for r in requests])
        for got, want in zip(out, oracle):
            got_ids = [(ps.id, sorted(p.id for p in ps.policies))
                       for ps in got.policy_sets]
            want_ids = [(ps.id, sorted(p.id for p in ps.policies))
                        for ps in want.policy_sets]
            assert got_ids == want_ids


class TestResolutionCache:
    def test_ttl_expiry_refetches(self):
        clock = [0.0]
        cache = TokenResolutionCache(ttl_s=10.0, time_fn=lambda: clock[0])
        entry = {"payload": {"id": "u"}, "status": {"code": 200}}
        _, gen = cache.lookup("t")
        assert cache.store("t", entry, gen)
        hit, _ = cache.lookup("t")
        assert hit["payload"] == {"id": "u"}
        clock[0] = 11.0
        hit, _ = cache.lookup("t")
        assert hit is None
        assert cache.stats()["expirations"] == 1

    def test_negative_caching_definitive_only(self):
        clock = [0.0]
        cache = TokenResolutionCache(
            ttl_s=10.0, negative_ttl_s=2.0, time_fn=lambda: clock[0]
        )
        _, gen = cache.lookup("bad")
        # definitive negative (404): cached for the negative TTL
        assert cache.store(
            "bad", {"payload": None, "status": {"code": 404}}, gen
        )
        hit, _ = cache.lookup("bad")
        assert hit is not None and hit["payload"] is None
        assert cache.stats()["negative_hits"] == 1
        clock[0] = 3.0
        assert cache.lookup("bad")[0] is None  # negative TTL elapsed
        # transport failure (5xx): never cached
        _, gen = cache.lookup("down")
        assert not cache.store(
            "down", {"payload": None, "status": {"code": 503}}, gen
        )
        assert cache.lookup("down")[0] is None

    def test_negative_cache_collapses_repeat_bad_tokens(self):
        inner = StaticIdentityClient()
        calls = []
        orig = inner.find_by_token

        def counting(token):
            calls.append(token)
            return orig(token)

        inner.find_by_token = counting
        client = CachingIdentityClient(inner)
        for _ in range(5):
            out = client.find_by_token("nope")
            assert out["payload"] is None
        assert calls == ["nope"]  # one RPC per negative-TTL window

    def test_eviction_race_blocks_stale_store(self):
        cache = TokenResolutionCache()
        _, gen = cache.lookup("t")
        cache.evict("t")  # userModified lands while resolution in flight
        assert not cache.store(
            "t", {"payload": {"id": "u"}, "status": {"code": 200}}, gen
        )
        assert cache.lookup("t")[0] is None

    def test_evict_subject_drops_all_tokens_of_user(self):
        cache = TokenResolutionCache()
        for tok in ("a", "b"):
            _, gen = cache.lookup(tok)
            cache.store(
                tok, {"payload": {"id": "ada"}, "status": {"code": 200}}, gen
            )
        _, gen = cache.lookup("c")
        cache.store(
            "c", {"payload": {"id": "gil"}, "status": {"code": 200}}, gen
        )
        assert cache.evict_subject("ada") == 2
        assert cache.lookup("a")[0] is None
        assert cache.lookup("b")[0] is None
        assert cache.lookup("c")[0] is not None

    def test_stale_cache_after_eviction_differential(self):
        """userModified eviction mid-stream: the next batch re-resolves and
        kernel rows stay bit-identical to the oracle under the NEW
        payload."""
        engine = wired_engine()
        ev = HybridEvaluator(engine)
        first = [copy.deepcopy(token_request(i, "tok-1")) for i in range(8)]
        ev.is_allowed_batch(first)
        # the user's role flips; the resolution cache is evicted like the
        # worker's userModified listener would
        engine.identity_client.inner.register("tok-1", {
            "id": "user-1",
            "tokens": [{"token": "tok-1", "interactive": True}],
            "role_associations": [{"role": "role-3", "attributes": []}],
        })
        engine.identity_client.evict_subject("user-1")
        second = [copy.deepcopy(token_request(i, "tok-1")) for i in range(8)]
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in second]
        responses = ev.is_allowed_batch(second)
        assert_bit_identical(responses, oracle)
        # the fresh payload actually landed in the encoded rows
        assert second[0].context["subject"]["role_associations"] == [
            {"role": "role-3", "attributes": []}
        ]

    def test_telemetry_counters_and_health_surface(self):
        telemetry = Telemetry()
        client = CachingIdentityClient(
            StaticIdentityClient({"t": {"id": "u"}}),
            counter=telemetry.identity,
        )
        client.find_by_token("t")
        client.find_by_token("t")
        snap = telemetry.snapshot()["identity_cache"]
        assert snap["misses"] == 1 and snap["hits"] == 1
        stats = client.cache_stats()
        assert stats["hits"] == 1 and stats["entries"] == 1
        # health_check exposes the same stats through the command interface
        from access_control_srv_tpu.srv.command import CommandInterface
        from access_control_srv_tpu.srv.config import Config

        engine = AccessController(identity_client=client)

        class Svc:
            pass

        svc = Svc()
        svc.engine = engine
        svc.evaluator = None
        health = CommandInterface(Config({}), svc).health_check({})
        assert health["status"] == "SERVING"
        assert health["token_resolution_cache"]["hits"] == 1


def cq_tree(with_later_reader=False):
    """A stress-shaped tree plus one trailing context-query rule over
    WIDGET; optionally a later role-gated rule that makes the merge
    observable (fusion must then refuse)."""
    doc = role_tree()
    cq_policies = [{
        "id": "p-cq", "combining_algorithm": PO,
        "rules": [{
            "id": "r-cq",
            "target": {"resources": [{"id": URNS["entity"],
                                      "value": WIDGET}]},
            "effect": "PERMIT",
            "context_query": {
                "filters": [{"field": "id", "operation": "eq",
                             "value": "res"}],
                "query": "query q { all { id } }",
            },
            "condition": "len(context._queryResult) > 0",
        }],
    }]
    if with_later_reader:
        cq_policies.append({
            "id": "p-later", "combining_algorithm": PO,
            "rules": [{
                "id": "r-later",
                "target": {
                    "subjects": [{"id": URNS["role"], "value": "role-0"}],
                    "resources": [{"id": URNS["entity"], "value": WIDGET}],
                },
                "effect": "DENY",
            }],
        })
    doc["policy_sets"].append(
        {"id": "cq", "combining_algorithm": DO, "policies": cq_policies}
    )
    return doc


class CountingAdapter:
    def __init__(self, fail_times=0, code=502):
        self.calls = 0
        self.fail_times = fail_times
        self.code = code

    def query(self, context_query, request):
        self.calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise ContextQueryTransportError(self.code, "boom")
        return [{"id": "res"}]


class TestContextQueryPrefetch:
    def _requests(self, n=16):
        out = []
        for i in range(n):
            out.append(Request(
                target=Target(
                    subjects=[
                        Attribute(id=URNS["role"], value=f"role-{i % 6}"),
                        Attribute(id=URNS["subjectID"], value=f"u{i}"),
                    ],
                    resources=[
                        Attribute(id=URNS["entity"],
                                  value=WIDGET if i % 2 else ORG),
                        Attribute(id=URNS["resourceID"], value=f"res-{i}"),
                    ],
                    actions=[Attribute(id=URNS["actionID"],
                                       value=URNS["read"])],
                ),
                context={"resources": [], "subject": {
                    "id": f"u{i}",
                    "role_associations": [
                        {"role": f"role-{i % 6}", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                }},
            ))
        return out

    def _run(self, doc, adapter, n=16):
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        engine.resource_adapter = adapter
        requests = self._requests(n)
        oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
        ev = HybridEvaluator(engine)
        responses = ev.is_allowed_batch([copy.deepcopy(r) for r in requests])
        assert_bit_identical(responses, oracle)
        batch = encode_requests(
            [copy.deepcopy(r) for r in requests], ev._compiled,
            engine.resource_adapter,
        )
        return batch

    def test_safe_rows_fuse_and_match_oracle(self):
        batch = self._run(cq_tree(), CountingAdapter())
        assert batch.eligible.all(), batch.ineligible_reasons

    def test_merge_observable_rows_degrade(self):
        """A later role-gated candidate rule could see the merged context:
        those rows must take the oracle, and still match it."""
        batch = self._run(cq_tree(with_later_reader=True), CountingAdapter())
        assert batch.ineligible_reasons.get("context-query") == 8
        assert int(batch.eligible.sum()) == 8  # ORG rows stay on device

    def test_prefetch_failure_degrades_to_oracle(self):
        batch = self._run(cq_tree(), CountingAdapter(fail_times=10 ** 6))
        assert batch.ineligible_reasons.get("context-query-error") == 8
        assert int(batch.eligible.sum()) == 8

    def test_condition_error_on_merged_context_aborts_like_oracle(self):
        doc = cq_tree()
        doc["policy_sets"][-1]["policies"][0]["rules"][0]["condition"] = (
            "context._queryResult[0].missing_field.deeper == 1"
        )
        batch = self._run(doc, CountingAdapter())
        assert batch.eligible.all(), batch.ineligible_reasons
        assert batch.cond_abort.any()


class TestAdapterRetry:
    def _adapter(self, fail_times, code):
        calls = []

        def transport(url, body, headers):
            calls.append(time.monotonic())
            if len(calls) <= fail_times:
                raise ContextQueryTransportError(code, "flaky")
            return b'{"data": {"op": {"details": [{"payload": {"id": 1}}]}}}'

        adapter = GraphQLAdapter(
            "http://example/graphql", transport=transport,
            retry_backoff_s=0.01,
        )
        cq = type("CQ", (), {"query": "query q", "filters": []})()
        request = Request(target=Target(), context={"resources": []})
        return adapter, cq, request, calls

    def test_transient_5xx_retried_once(self):
        adapter, cq, request, calls = self._adapter(1, 502)
        out = adapter.query(cq, request)
        assert out == [{"id": 1}]
        assert len(calls) == 2

    def test_second_5xx_failure_surfaces(self):
        adapter, cq, request, calls = self._adapter(2, 503)
        with pytest.raises(ContextQueryTransportError):
            adapter.query(cq, request)
        assert len(calls) == 2  # exactly one retry, then give up

    def test_definitive_4xx_not_retried(self):
        adapter, cq, request, calls = self._adapter(1, 404)
        with pytest.raises(ContextQueryTransportError):
            adapter.query(cq, request)
        assert len(calls) == 1

    def test_retry_disabled_by_config(self):
        calls = []

        def transport(url, body, headers):
            calls.append(1)
            raise ContextQueryTransportError(502, "down")

        adapter = GraphQLAdapter(
            "http://example/graphql", transport=transport,
            retry_transient=False,
        )
        cq = type("CQ", (), {"query": "query q", "filters": []})()
        with pytest.raises(ContextQueryTransportError):
            adapter.query(cq, Request(target=Target(), context={}))
        assert len(calls) == 1


class TestBatcherPipeline:
    def test_pipelined_batches_resolve_in_order(self):
        """The eval-worker pipeline must preserve per-request results while
        the collector prepares the next batch during device execution."""
        from access_control_srv_tpu.srv.batcher import MicroBatcher

        engine = wired_engine()
        ev = HybridEvaluator(engine)
        batcher = MicroBatcher(ev, window_ms=1.0, min_kernel_batch=4)
        batcher.start()
        try:
            requests = [token_request(i, f"tok-{i % 8}") for i in range(64)]
            oracle = [engine.is_allowed(copy.deepcopy(r)) for r in requests]
            futures = [batcher.submit(copy.deepcopy(r)) for r in requests]
            responses = [f.result(timeout=30) for f in futures]
            assert_bit_identical(responses, oracle)
        finally:
            batcher.stop()

    def test_stop_drains_inflight_batches(self):
        from access_control_srv_tpu.srv.batcher import MicroBatcher

        engine = wired_engine()
        ev = HybridEvaluator(engine)
        batcher = MicroBatcher(ev, window_ms=1.0, min_kernel_batch=4)
        batcher.start()
        futures = [batcher.submit(copy.deepcopy(token_request(i, f"tok-{i % 8}")))
                   for i in range(16)]
        time.sleep(0.05)
        batcher.stop()
        done = [f for f in futures if f.done()]
        assert done, "stop() must drain submitted work"
        for f in done:
            assert f.result(timeout=1) is not None
