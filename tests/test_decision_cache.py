"""Decision-cache correctness (ISSUE 1 tentpole): unit coverage for TTL
expiry, the LRU bound, epoch flush and subject-prefix eviction; worker-level
coverage for all four invalidation paths (CRUD epoch, userModified /
userDeleted, flush_cache command, TTL); and the differential suite asserting
cache-on vs cache-off bit-identical decision streams under randomized
CRUD/userModified interleavings (the semantics bar: cache on/off must never
change a decision)."""

import random

import pytest

from access_control_srv_tpu.models import Decision, Response
from access_control_srv_tpu.models.model import OperationStatus
from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.decision_cache import (
    DecisionCache,
    request_fingerprint,
)

from .test_srv import ORG, PO, READ, SEED, admin_request, seed_cfg
from .utils import URNS, build_request

USERS_TOPIC = "io.restorecommerce.users.resource"


def permit_response(message="success"):
    return Response(
        decision=Decision.PERMIT,
        obligations=[],
        evaluation_cacheable=True,
        operation_status=OperationStatus(code=200, message=message),
    )


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


# ------------------------------------------------------------------ unit


class TestDecisionCacheUnit:
    def test_ttl_expiry(self):
        clock = FakeClock()
        cache = DecisionCache(ttl_s=10.0, time_fn=clock)
        cache.put("alice\x1fk1", permit_response())
        assert cache.get("alice\x1fk1").decision == Decision.PERMIT
        clock.now += 9.9
        assert cache.get("alice\x1fk1") is not None
        clock.now += 0.2  # past write + ttl
        assert cache.get("alice\x1fk1") is None
        stats = cache.stats()
        assert stats["evictions"] == 1  # lazily collected on lookup
        assert stats["entries"] == 0

    def test_lru_bound_and_recency(self):
        cache = DecisionCache(max_entries=4, shards=1)
        for i in range(4):
            cache.put(f"u\x1fk{i}", permit_response())
        # touch k0 so k1 is now least-recently-used
        assert cache.get("u\x1fk0") is not None
        cache.put("u\x1fk4", permit_response())
        assert cache.stats()["entries"] == 4
        assert cache.get("u\x1fk1") is None  # LRU victim
        assert cache.get("u\x1fk0") is not None  # recency protected it
        assert cache.stats()["evictions"] == 1

    def test_epoch_flush_is_logical(self):
        cache = DecisionCache()
        cache.put("u\x1fk", permit_response())
        cache.bump_epoch()
        assert cache.get("u\x1fk") is None
        # stale-epoch entries count as miss + eviction and are collected
        stats = cache.stats()
        assert stats["misses"] == 1 and stats["evictions"] == 1
        # writes under the new epoch serve again
        cache.put("u\x1fk", permit_response())
        assert cache.get("u\x1fk") is not None

    def test_subject_prefix_eviction(self):
        cache = DecisionCache()
        alice = build_request(subject_id="alice", subject_role="r1",
                              resource_type=ORG, resource_id="O1",
                              action_type=READ)
        # "alice2" shares a string prefix with "alice" but is a distinct
        # subject: the separator must keep it out of alice's eviction
        alice2 = build_request(subject_id="alice2", subject_role="r1",
                               resource_type=ORG, resource_id="O1",
                               action_type=READ)
        bob = build_request(subject_id="bob", subject_role="r1",
                            resource_type=ORG, resource_id="O1",
                            action_type=READ)
        keys = [request_fingerprint(r) for r in (alice, alice2, bob)]
        assert keys[0].startswith("alice\x1f")
        for key in keys:
            cache.put(key, permit_response())
        assert cache.evict_subject("alice") == 1
        assert cache.get(keys[0]) is None
        assert cache.get(keys[1]) is not None
        assert cache.get(keys[2]) is not None

    def test_flush_and_pattern_eviction(self):
        cache = DecisionCache()
        cache.put("alice\x1fk", permit_response())
        cache.put("alina\x1fk", permit_response())
        cache.put("bob\x1fk", permit_response())
        assert cache.evict_pattern("ali") == 2  # prefix semantics
        assert cache.stats()["entries"] == 1
        # empty pattern = full flush (reference flush_cache without pattern)
        epoch = cache.stats()["epoch"]
        assert cache.evict_pattern("") == 1
        stats = cache.stats()
        assert stats["entries"] == 0 and stats["epoch"] == epoch + 1

    def test_put_refuses_stale_epoch_snapshot(self):
        # a decision whose evaluation spans an epoch bump (CRUD/restore
        # completing mid-walk) must never be stored as fresh: the writer's
        # lookup-time snapshot, not the epoch at write time, stamps it
        cache = DecisionCache()
        epoch = cache.epoch  # snapshot at lookup/miss time
        cache.bump_epoch()   # tree mutation lands while computing
        assert not cache.put("u\x1fk", permit_response(), epoch=epoch)
        assert cache.get("u\x1fk") is None
        assert cache.stats()["entries"] == 0
        # a snapshot matching the current epoch stores normally
        assert cache.put("u\x1fk", permit_response(), epoch=cache.epoch)
        assert cache.get("u\x1fk") is not None

    def test_put_gates_on_cacheable_and_status(self):
        cache = DecisionCache()
        uncacheable = permit_response()
        uncacheable.evaluation_cacheable = False
        unknown = permit_response()
        unknown.evaluation_cacheable = None
        errored = permit_response()
        errored.operation_status = OperationStatus(code=500, message="boom")
        assert not cache.put("u\x1fa", uncacheable)
        assert not cache.put("u\x1fb", unknown)
        assert not cache.put("u\x1fc", errored)
        assert cache.put("u\x1fd", permit_response())
        assert cache.stats()["entries"] == 1

    def test_disabled_cache_never_stores_or_hits(self):
        cache = DecisionCache(enabled=False)
        assert not cache.put("u\x1fk", permit_response())
        assert cache.get("u\x1fk") is None
        assert cache.stats()["misses"] == 0  # disabled lookups not counted

    def test_hit_returns_fresh_response_object(self):
        cache = DecisionCache()
        cache.put("u\x1fk", permit_response())
        first = cache.get("u\x1fk")
        first.decision = Decision.DENY  # caller mutates its copy
        second = cache.get("u\x1fk")
        assert second.decision == Decision.PERMIT


class TestRequestFingerprint:
    def test_attribute_order_insensitive(self):
        base = build_request(subject_id="u1", subject_role="r1",
                             resource_type=ORG, resource_id="O1",
                             action_type=READ)
        shuffled = build_request(subject_id="u1", subject_role="r1",
                                 resource_type=ORG, resource_id="O1",
                                 action_type=READ)
        shuffled.target.subjects = list(reversed(shuffled.target.subjects))
        shuffled.target.resources = list(reversed(shuffled.target.resources))
        assert request_fingerprint(base) == request_fingerprint(shuffled)

    def test_context_changes_key(self):
        plain = build_request(subject_id="u1", subject_role="r1",
                              resource_type=ORG, resource_id="O1",
                              action_type=READ)
        scoped = build_request(subject_id="u1", subject_role="r1",
                               role_scoping_entity=ORG,
                               role_scoping_instance="system",
                               resource_type=ORG, resource_id="O1",
                               action_type=READ)
        assert request_fingerprint(plain) != request_fingerprint(scoped)

    def test_derived_context_keys_excluded(self):
        a = build_request(subject_id="u1", subject_role="r1",
                          resource_type=ORG, resource_id="O1",
                          action_type=READ)
        b = build_request(subject_id="u1", subject_role="r1",
                          resource_type=ORG, resource_id="O1",
                          action_type=READ)
        b.context["_queryResult"] = [{"id": "res"}]  # evaluation output
        assert request_fingerprint(a) == request_fingerprint(b)

    def test_no_target_not_cacheable(self):
        from access_control_srv_tpu.models import Request

        assert request_fingerprint(Request(target=None, context={})) is None


class TestFingerprintSingleComputation:
    """The fingerprint digest is memoized on the request object
    (``_dc_key``): however many layers consult the cache — batcher fast
    path, evaluator single path, batch keying — one request pays for
    exactly one blake2b computation."""

    @pytest.fixture()
    def digest_counter(self, monkeypatch):
        from access_control_srv_tpu.srv import decision_cache as dc

        calls = {"n": 0}
        real = dc.blake2b

        def counting(*args, **kwargs):
            calls["n"] += 1
            return real(*args, **kwargs)

        monkeypatch.setattr(dc, "blake2b", counting)
        return calls

    def test_unit_repeat_fingerprint_is_free(self, digest_counter):
        request = build_request(subject_id="u1", subject_role="r1",
                                resource_type=ORG, resource_id="O1",
                                action_type=READ)
        key = request_fingerprint(request)
        assert key is not None and digest_counter["n"] == 1
        assert request_fingerprint(request) == key
        assert digest_counter["n"] == 1  # memoized, not recomputed

    def test_serving_path_computes_fingerprint_once(self, worker,
                                                    digest_counter):
        request = admin_request()
        assert worker.service.is_allowed(request).decision == Decision.PERMIT
        assert digest_counter["n"] == 1  # cold miss: one digest total
        # the same object resubmitted rides its memo through every layer
        assert worker.service.is_allowed(request).decision == Decision.PERMIT
        assert digest_counter["n"] == 1
        # a fresh equivalent request pays one digest for its cache hit
        digest_counter["n"] = 0
        assert worker.service.is_allowed(
            admin_request()
        ).decision == Decision.PERMIT
        assert digest_counter["n"] == 1

    def test_batch_path_computes_one_fingerprint_per_request(
            self, worker, digest_counter):
        requests = [admin_request() for _ in range(4)]
        responses = worker.service.is_allowed_batch(requests)
        assert all(r.decision == Decision.PERMIT for r in responses)
        assert digest_counter["n"] == len(requests)


# ---------------------------------------------------------------- worker


def reader_rule(rid="r_reader", role="reader-role", effect="PERMIT",
                cacheable=True):
    return {
        "id": rid,
        "name": rid,
        "target": {
            "subjects": [{"id": URNS["role"], "value": role}],
            "resources": [{"id": URNS["entity"], "value": ORG}],
            "actions": [{"id": URNS["actionID"], "value": READ}],
        },
        "effect": effect,
        "evaluation_cacheable": cacheable,
    }


def install_reader_tree(worker, **rule_kwargs):
    worker.store.get_resource_service("rule").create(
        [reader_rule(**rule_kwargs)]
    )
    worker.store.get_resource_service("policy").create(
        [{"id": "p_readers", "combining_algorithm": PO,
          "rules": ["r_reader"], "evaluation_cacheable": True}]
    )
    worker.store.get_resource_service("policy_set").create(
        [{"id": "ps_readers", "combining_algorithm": PO,
          "policies": ["p_readers"]}]
    )


def reader_request(subject_id="u-reader"):
    return build_request(subject_id=subject_id, subject_role="reader-role",
                         role_scoping_entity=ORG,
                         role_scoping_instance="system",
                         resource_type=ORG, resource_id="O1",
                         action_type=READ)


@pytest.fixture()
def worker():
    w = Worker().start(seed_cfg())
    yield w
    w.stop()


class TestWorkerCachePath:
    def test_repeat_traffic_served_from_cache(self, worker):
        cold = worker.service.is_allowed(admin_request())
        assert cold.decision == Decision.PERMIT
        assert cold.evaluation_cacheable is True
        hits_before = worker.decision_cache.stats()["hits"]
        warm = worker.service.is_allowed(admin_request())
        stats = worker.decision_cache.stats()
        assert stats["hits"] == hits_before + 1
        assert (warm.decision, warm.evaluation_cacheable,
                warm.operation_status.code) == \
            (cold.decision, cold.evaluation_cacheable,
             cold.operation_status.code)
        assert worker.telemetry.paths.snapshot().get("cache-hit", 0) >= 1

    def test_crud_update_invalidates_before_serving(self, worker):
        install_reader_tree(worker)
        request = reader_request()
        assert worker.service.is_allowed(request).decision == Decision.PERMIT
        assert worker.service.is_allowed(request).decision == Decision.PERMIT
        assert worker.decision_cache.stats()["hits"] >= 1
        # rule flip must serve immediately — a stale cached PERMIT after
        # the tree swap would be a correctness bug, not a staleness window
        worker.store.get_resource_service("rule").update(
            [reader_rule(effect="DENY")]
        )
        assert worker.service.is_allowed(request).decision == Decision.DENY

    def test_rule_delete_invalidates(self, worker):
        install_reader_tree(worker)
        request = reader_request()
        assert worker.service.is_allowed(request).decision == Decision.PERMIT
        worker.store.get_resource_service("rule").delete(["r_reader"])
        assert worker.service.is_allowed(request).decision != Decision.PERMIT

    def test_user_events_evict_subject(self, worker):
        warm = worker.service.is_allowed(admin_request())
        assert warm.evaluation_cacheable is True
        evictions = worker.decision_cache.stats()["evictions"]
        worker.bus.topic(USERS_TOPIC).emit("userModified", {"id": "root"})
        assert worker.decision_cache.stats()["evictions"] == evictions + 1
        # re-warm, then userDeleted takes the same eviction path
        worker.service.is_allowed(admin_request())
        evictions = worker.decision_cache.stats()["evictions"]
        worker.bus.topic(USERS_TOPIC).emit("userDeleted", {"id": "root"})
        assert worker.decision_cache.stats()["evictions"] == evictions + 1

    def test_user_event_other_subject_keeps_entries(self, worker):
        worker.service.is_allowed(admin_request())
        entries = worker.decision_cache.stats()["entries"]
        assert entries >= 1
        worker.bus.topic(USERS_TOPIC).emit("userModified", {"id": "someone"})
        assert worker.decision_cache.stats()["entries"] == entries

    def test_flush_cache_db_index_routing(self, worker):
        worker.service.is_allowed(admin_request())
        assert worker.decision_cache.stats()["entries"] >= 1
        # db 4 (subject cache analog) leaves decisions alone
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 4}}
        )
        assert "decisions" not in out["flushed"]
        assert worker.decision_cache.stats()["entries"] >= 1
        # db 5 (the reference acs-client decision cache DB) flushes them
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 5}}
        )
        assert out["flushed"]["decisions"] >= 1
        assert worker.decision_cache.stats()["entries"] == 0

    def test_flush_cache_string_db_index_coerced(self, worker):
        # loosely-typed JSON payloads send "5": the command must coerce
        # and flush instead of silently flushing nothing with status ok
        worker.service.is_allowed(admin_request())
        assert worker.decision_cache.stats()["entries"] >= 1
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": "5"}}
        )
        assert out["flushed"]["decisions"] >= 1
        assert worker.decision_cache.stats()["entries"] == 0

    def test_flush_cache_unrecognized_db_index_errors(self, worker):
        worker.service.is_allowed(admin_request())
        entries = worker.decision_cache.stats()["entries"]
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 7}}
        )
        assert "error" in out
        assert worker.decision_cache.stats()["entries"] == entries
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": "not-a-db"}}
        )
        assert "error" in out

    def test_flush_cache_pattern_narrows_to_subject(self, worker):
        install_reader_tree(worker)
        worker.service.is_allowed(admin_request())  # subject "root"
        worker.service.is_allowed(reader_request("u-reader"))
        out = worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 5, "pattern": "u-reader"}}
        )
        assert out["flushed"]["decisions"] == 1
        # root's entry survives and still serves a hit
        hits = worker.decision_cache.stats()["hits"]
        worker.service.is_allowed(admin_request())
        assert worker.decision_cache.stats()["hits"] == hits + 1

    def test_decision_spanning_epoch_bump_is_not_cached(self, worker,
                                                        monkeypatch):
        """The CRUD-during-evaluation interleaving: a decision computed
        against the old tree that completes after the epoch bump must not
        be served as fresh for a TTL."""
        install_reader_tree(worker)
        evaluator = worker.service.evaluator
        cache = worker.decision_cache
        real = evaluator._oracle_is_allowed

        def bump_mid_flight(request):
            response = real(request)
            cache.bump_epoch()  # CRUD/restore completes while in flight
            return response

        monkeypatch.setattr(evaluator, "_oracle_is_allowed", bump_mid_flight)
        stores = cache.stats()["stores"]
        assert evaluator.is_allowed(reader_request()).decision == \
            Decision.PERMIT
        # the write-through was refused: its epoch snapshot predates the
        # bump, so nothing stale entered the cache
        assert cache.stats()["stores"] == stores
        assert cache.stats()["entries"] == 0

    def test_batch_spanning_epoch_bump_is_not_cached(self, worker,
                                                     monkeypatch):
        install_reader_tree(worker)
        evaluator = worker.service.evaluator
        cache = worker.decision_cache
        real = evaluator._is_allowed_batch_uncached

        def bump_mid_flight(requests):
            responses = real(requests)
            cache.bump_epoch()
            return responses

        monkeypatch.setattr(
            evaluator, "_is_allowed_batch_uncached", bump_mid_flight
        )
        responses = evaluator.is_allowed_batch(
            [reader_request(), admin_request()]
        )
        assert all(r.decision == Decision.PERMIT for r in responses)
        assert cache.stats()["entries"] == 0

    def test_config_update_bumps_epoch(self, worker):
        epoch = worker.decision_cache.stats()["epoch"]
        worker.command_interface.command(
            "config_update", {"service:probe": True}
        )
        assert worker.decision_cache.stats()["epoch"] == epoch + 1

    def test_ttl_expiry_through_worker(self):
        w = Worker().start(seed_cfg(decision_cache={
            "enabled": True, "ttl_s": 3600, "max_entries": 1024,
            "shards": 4,
        }))
        try:
            clock = FakeClock()
            w.decision_cache._time = clock
            w.service.is_allowed(admin_request())
            hits = w.decision_cache.stats()["hits"]
            w.service.is_allowed(admin_request())
            assert w.decision_cache.stats()["hits"] == hits + 1
            clock.now += 3601.0
            misses = w.decision_cache.stats()["misses"]
            response = w.service.is_allowed(admin_request())
            assert response.decision == Decision.PERMIT
            assert w.decision_cache.stats()["misses"] > misses
        finally:
            w.stop()

    def test_disabled_by_config(self):
        w = Worker().start(seed_cfg(decision_cache={"enabled": False}))
        try:
            assert w.decision_cache is None
            response = w.service.is_allowed(admin_request())
            assert response.decision == Decision.PERMIT
            health = w.command_interface.command("health_check")
            assert "decision_cache" not in health
        finally:
            w.stop()


# ----------------------------------------------------------- differential


def response_bits(response):
    return (
        response.decision,
        response.evaluation_cacheable,
        response.operation_status.code if response.operation_status else None,
        tuple(
            (o.id, o.value) for o in (response.obligations or [])
        ),
    )


ROLES = ("superadministrator-r-id", "reader-role", "nobody")
SUBJECTS = ("root", "u-reader", "u-other")


def probe_requests():
    requests = []
    for subject in SUBJECTS:
        for role in ROLES:
            requests.append(build_request(
                subject_id=subject, subject_role=role,
                role_scoping_entity=ORG, role_scoping_instance="system",
                resource_type=ORG, resource_id="O1", action_type=READ,
            ))
    return requests


def test_differential_cache_on_off_under_random_interleaving():
    """The semantics bar: a cache-on worker and a cache-off worker fed the
    same randomized stream of decisions, rule CRUD, userModified events and
    flush commands must emit bit-identical responses at every step."""
    rng = random.Random(1312)
    on = Worker().start(seed_cfg())
    off = Worker().start(seed_cfg(decision_cache={"enabled": False}))
    workers = (on, off)
    try:
        assert on.decision_cache is not None and off.decision_cache is None

        def compare_all(step):
            # fresh request objects per worker: engines mutate context
            for a, b in zip(probe_requests(), probe_requests()):
                ra = on.service.is_allowed(a)
                rb = off.service.is_allowed(b)
                assert response_bits(ra) == response_bits(rb), (
                    f"divergence at step {step}"
                )

        def op_create():
            for w in workers:
                install_reader_tree(w)

        def op_update():
            # update of a deleted rule is a per-item 404 no-op — identical
            # on both workers, which is all the differential needs
            effect = rng.choice(("PERMIT", "DENY"))
            cacheable = rng.random() < 0.8
            for w in workers:
                w.store.get_resource_service("rule").update(
                    [reader_rule(effect=effect, cacheable=cacheable)]
                )

        def op_delete():
            for w in workers:
                w.store.get_resource_service("rule").delete(["r_reader"])

        def op_user_event():
            event = rng.choice(("userModified", "userDeleted"))
            subject = rng.choice(SUBJECTS)
            for w in workers:
                w.bus.topic(USERS_TOPIC).emit(event, {"id": subject})

        def op_flush():
            payload = rng.choice((
                {},
                {"data": {"db_index": 5}},
                {"data": {"pattern": rng.choice(SUBJECTS)}},
            ))
            for w in workers:
                w.command_interface.command("flush_cache", payload)

        def op_traffic():
            # repeat traffic between mutations so warm hits actually serve
            for a, b in zip(probe_requests(), probe_requests()):
                ra = on.service.is_allowed(a)
                rb = off.service.is_allowed(b)
                assert response_bits(ra) == response_bits(rb)

        ops = (op_create, op_update, op_delete, op_user_event, op_flush,
               op_traffic)
        compare_all("seed")
        for step in range(24):
            rng.choice(ops)()
            compare_all(step)
        # the interleaving must have exercised real cache serving
        assert on.decision_cache.stats()["hits"] > 0
    finally:
        on.stop()
        off.stop()


def test_differential_batch_path_cache_on_off():
    """Batched endpoint: warm batch traffic through the cache-on worker
    matches the cache-off worker row for row."""
    on = Worker().start(seed_cfg())
    off = Worker().start(seed_cfg(decision_cache={"enabled": False}))
    try:
        install_reader_tree(on)
        install_reader_tree(off)
        for _ in range(3):  # cold, then warm passes
            ra = on.service.is_allowed_batch(probe_requests())
            rb = off.service.is_allowed_batch(probe_requests())
            assert [response_bits(r) for r in ra] == \
                [response_bits(r) for r in rb]
        assert on.decision_cache.stats()["hits"] > 0
    finally:
        on.stop()
        off.stop()
