"""Batched whatIsAllowed differential: the device-assisted reverse query
(ops/reverse.py) must produce ReverseQuery trees AND obligations
bit-identical to the scalar oracle — including obligations accumulated
from target-match calls whose final verdict is False (the reference's
side-effecting scan, accessController.ts:592-640)."""

import copy

import pytest

from access_control_srv_tpu.core import populate
from access_control_srv_tpu.ops import (
    ReverseQueryKernel,
    compile_policies,
    encode_requests,
    what_is_allowed_batch,
)

from .test_kernel_differential import grid_requests
from .utils import fixture, make_engine


def rq_shape(rq):
    """Comparable projection of a ReverseQuery (ids, structure, masks)."""
    return {
        "sets": [
            {
                "id": ps.id,
                "ca": ps.combining_algorithm,
                "policies": [
                    {
                        "id": p.id,
                        "effect": p.effect,
                        "cacheable": p.evaluation_cacheable,
                        "has_rules": p.has_rules,
                        "rules": [
                            (r.id, r.effect, r.condition,
                             r.evaluation_cacheable)
                            for r in p.rules
                        ],
                    }
                    for p in ps.policies
                ],
            }
            for ps in rq.policy_sets
        ],
        "obligations": [
            (o.id, o.value,
             [(n.id, n.value) for n in (o.attributes or [])])
            for o in rq.obligations
        ],
        "status": (rq.operation_status.code, rq.operation_status.message),
    }


@pytest.mark.parametrize(
    "fixture_name",
    [
        "basic_policies.yml",
        "policy_targets.yml",
        "policy_set_targets.yml",
        "role_scopes.yml",
        "conditions.yml",
        "acl_policies.yml",
        "props_single.yml",
        "props_rules_noprop.yml",
        "props_multi_rules.yml",
        "props_multi_rules_entities.yml",
        "ops_multi.yml",
    ],
)
def test_reverse_differential(fixture_name):
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kernel = ReverseQueryKernel(compiled, engine.policy_sets)

    requests = grid_requests(n=100, seed=211)
    oracle_out = [
        engine.what_is_allowed(copy.deepcopy(r)) for r in requests
    ]
    batch = encode_requests(
        [copy.deepcopy(r) for r in requests], compiled
    )
    kernel_out = what_is_allowed_batch(
        engine, compiled, kernel,
        [copy.deepcopy(r) for r in requests], batch,
    )
    n_device = 0
    for b in range(len(requests)):
        assert rq_shape(kernel_out[b]) == rq_shape(oracle_out[b]), b
        if batch.eligible[b]:
            n_device += 1
    assert n_device > 60  # the device path must actually be exercised


def test_reverse_multi_set_tree():
    engine = make_engine()
    for name in ["basic_policies.yml", "policy_targets.yml",
                 "props_multi_rules.yml"]:
        populate(engine, fixture(name))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kernel = ReverseQueryKernel(compiled, engine.policy_sets)
    requests = grid_requests(n=80, seed=97)
    oracle_out = [engine.what_is_allowed(copy.deepcopy(r)) for r in requests]
    kernel_out = what_is_allowed_batch(
        engine, compiled, kernel, [copy.deepcopy(r) for r in requests]
    )
    for b in range(len(requests)):
        assert rq_shape(kernel_out[b]) == rq_shape(oracle_out[b]), b


def test_evaluator_wia_batch_and_hot_mutation(monkeypatch):
    """HybridEvaluator.what_is_allowed_batch serves device-assisted and
    stays consistent across a hot tree mutation (version-pinned snapshot;
    stale compiles fall back to the oracle).  The adaptive dispatch is
    pinned to the kernel path (fixture trees sit under REVERSE_MIN_RULES)."""
    from access_control_srv_tpu.core.loader import load_policy_sets_from_file
    from access_control_srv_tpu.ops import reverse as reverse_mod
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator

    monkeypatch.setattr(reverse_mod, "REVERSE_MIN_RULES", 0)
    engine = make_engine("policy_targets.yml")
    ev = HybridEvaluator(engine)
    requests = grid_requests(n=30, seed=311)

    oracle_out = [engine.what_is_allowed(copy.deepcopy(r)) for r in requests]
    batch_out = ev.what_is_allowed_batch([copy.deepcopy(r) for r in requests])
    for b in range(len(requests)):
        assert rq_shape(batch_out[b]) == rq_shape(oracle_out[b]), b
    assert ev._rq_kernel is not None  # lazily built on first use

    # hot mutation: add a second tree, refresh, answers must track it
    for ps in load_policy_sets_from_file(fixture("basic_policies.yml")):
        engine.update_policy_set(ps)
    ev.refresh(wait=True)
    oracle_out2 = [engine.what_is_allowed(copy.deepcopy(r)) for r in requests]
    batch_out2 = ev.what_is_allowed_batch([copy.deepcopy(r) for r in requests])
    for b in range(len(requests)):
        assert rq_shape(batch_out2[b]) == rq_shape(oracle_out2[b]), b


def test_adaptive_wia_dispatch():
    """Small trees serve the reverse query from the scalar walk (the
    device round-trip loses below REVERSE_MIN_RULES — bench_all.py wia
    row measured ~6x); the threshold routes to the kernel above it."""
    from access_control_srv_tpu.ops.reverse import REVERSE_MIN_RULES
    from access_control_srv_tpu.srv.evaluator import HybridEvaluator
    from access_control_srv_tpu.srv.telemetry import Telemetry

    engine = make_engine("policy_targets.yml")
    telemetry = Telemetry()
    ev = HybridEvaluator(engine, telemetry=telemetry)
    compiled = ev._compiled
    assert compiled is not None and compiled.n_rules < REVERSE_MIN_RULES

    requests = grid_requests(n=12, seed=5)
    oracle_out = [engine.what_is_allowed(copy.deepcopy(r)) for r in requests]
    out = ev.what_is_allowed_batch([copy.deepcopy(r) for r in requests])
    assert telemetry.paths.get("oracle-wia") == len(requests)
    assert telemetry.paths.get("kernel-wia") == 0
    assert ev._rq_kernel is None  # never built below the threshold
    for b in range(len(requests)):
        assert rq_shape(out[b]) == rq_shape(oracle_out[b]), b
