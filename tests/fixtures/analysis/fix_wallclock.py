"""acs-lint fixture: wall-clock time.time() in timing logic.

Expected findings:
  * deadline_in:time.time
Expected suppressions: 1 (uptime display).
time.monotonic() is never flagged.
"""

import time

_START = time.monotonic()


def deadline_in(budget_s):
    return time.time() + budget_s  # FINDING: deadline math on wall clock


def elapsed():
    return time.monotonic() - _START  # ok


def uptime_display():
    # acs-lint: ignore[wall-clock] fixture: human-facing display value
    return time.time()
