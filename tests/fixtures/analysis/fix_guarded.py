"""acs-lint fixture: guarded-by discipline — violations and exemptions.

Expected findings (path, rule, symbol):
  * Store.unlocked_read:self._data      (read outside the lock)
  * Store.unlocked_write:self._data     (write outside the lock)
  * Store.wrong_lock:self._data         (held a DIFFERENT lock)
  * peek:_registry                      (module global outside the lock)
Expected suppressions: 1 (Store.suppressed_read).
Everything else is exempt: __init__ stores, with-lock access, holds:
helper, condition wait_for lambda.
"""

import threading

_registry = {}  # guarded-by: _registry_lock
_registry_lock = threading.Lock()


def peek():
    return _registry.get("x")  # FINDING: global outside _registry_lock


def register(key, value):
    with _registry_lock:
        _registry[key] = value  # ok: under the lock


class Store:
    def __init__(self):
        self._data = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._other = threading.Lock()
        self._cond = threading.Condition()
        self.pending = []  # guarded-by: _cond

    def unlocked_read(self):
        return len(self._data)  # FINDING

    def unlocked_write(self, k, v):
        self._data[k] = v  # FINDING

    def wrong_lock(self):
        with self._other:
            return dict(self._data)  # FINDING: _other is not _lock

    def locked_ok(self, k):
        with self._lock:
            return self._data.get(k)

    def _drain(self):  # holds: _lock
        self._data.clear()  # ok: holds annotation

    def suppressed_read(self):
        # acs-lint: ignore[guarded-by] fixture: deliberate racy len
        return len(self._data)

    def wait_ok(self):
        with self._cond:
            self._cond.wait_for(lambda: bool(self.pending), timeout=0.01)
            return list(self.pending)  # ok: lambda + body under _cond
