"""acs-lint fixture: dispatch-half purity of evaluate_async.

Expected findings:
  * Kernel.evaluate_async:out_dev.block_until_ready
  * Kernel.evaluate_async:np.asarray(out_dev)
Not findings: the SAME calls inside the returned materialize() thunk
(Clean.evaluate_async), np.asarray of something that is not a device
result binding.
"""

import numpy as np


class Kernel:
    def evaluate_async(self, batch):
        out_dev = self._dispatch(batch)
        out_dev.block_until_ready()  # FINDING: sync in the dispatch half
        host = np.asarray(out_dev)   # FINDING: D2H in the dispatch half

        def materialize():
            return host

        return materialize

    def _dispatch(self, batch):
        return batch


class Clean:
    def evaluate_async(self, batch):
        out_dev = self._dispatch(batch)
        shape = np.asarray(batch.shape)  # ok: not a device-call binding

        def materialize():
            out_dev.block_until_ready()      # ok: materialize half
            return np.asarray(out_dev), shape

        return materialize

    def _dispatch(self, batch):
        return batch
