"""acs-lint fixture: thread lifecycle — every Thread daemonized or joined.

Expected findings:
  * leak:threading.Thread        (neither daemon nor joined)
Not findings: daemon=True kwarg, assigned-then-joined,
assigned-then-daemonized.
"""

import threading


def leak(fn):
    threading.Thread(target=fn).start()  # FINDING


def ok_daemon(fn):
    threading.Thread(target=fn, daemon=True).start()


def ok_joined(fn):
    worker = threading.Thread(target=fn)
    worker.start()
    worker.join(timeout=1.0)


def ok_daemonized_later(fn):
    pump = threading.Thread(target=fn)
    pump.daemon = True
    pump.start()
