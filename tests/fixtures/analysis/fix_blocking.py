"""acs-lint fixture: blocking calls lexically under a lock.

Expected findings:
  * Pump.stall:time.sleep            (sleep under the lock)
  * Pump.drain:self.jobs.get         (queue get with timeout under lock)
  * Pump.flush:os.fsync              (fsync inside a holds: helper)
Not findings: cond.wait_for on the held condition, dict .get, str.join,
queue get OUTSIDE the lock.
"""

import os
import queue
import threading
import time


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self.jobs = queue.Queue()
        self.table = {}
        self.fh = None

    def stall(self):
        with self._lock:
            time.sleep(0.01)  # FINDING

    def drain(self):
        with self._lock:
            return self.jobs.get(timeout=1.0)  # FINDING

    def flush(self):  # holds: _lock
        os.fsync(self.fh.fileno())  # FINDING: blocking in a holds: helper

    def ok_wait(self):
        with self._cond:
            self._cond.wait_for(lambda: self.table, timeout=0.01)

    def ok_lookups(self):
        with self._lock:
            name = ", ".join(sorted(self.table))
            return self.table.get(name)

    def ok_outside(self):
        item = self.jobs.get(timeout=1.0)
        with self._lock:
            self.table[item] = True
