"""acs-lint fixture: self-declared host-only module importing jax.

Expected findings:
  * <module>:import jax          (top-level)
  * lazy:import jax.numpy        (lazy import inside a function)
"""

# acs-lint: host-only

import json  # noqa: F401 — ok
import jax  # noqa: F401  # FINDING


def lazy():
    import jax.numpy as jnp  # noqa: F401  # FINDING: lazy import counts

    return jnp


def fine():
    return json.dumps({})
