"""Golden tests for role scoping: direct + hierarchical owner matching,
multi-entity requests (sticky entity-match quirk), operation/execute
targets, HR-disabled rules and conditions."""

import pytest

from access_control_srv_tpu.models import Decision

from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
LOC = "urn:restorecommerce:acs:model:location.Location"
READ = URNS["read"]
MODIFY = URNS["modify"]
EXECUTE = URNS["execute"]


def check(engine, expected, **kwargs):
    defaults = dict(
        subject_role="member",
        role_scoping_entity=ORG,
        role_scoping_instance="Org1",
    )
    defaults.update(kwargs)
    request = build_request(**defaults)
    response = engine.is_allowed(request)
    assert response.decision == expected, kwargs
    return response


class TestRoleScopes:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("role_scopes.yml")

    def test_permit_member_read_location(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=LOC,
              resource_id="L1", action_type=READ,
              owner_indicatory_entity=ORG, owner_instance="Org1")

    def test_permit_multi_entity_read(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada",
              resource_type=[LOC, ORG], resource_id=["L1", "O1"],
              action_type=READ, owner_indicatory_entity=ORG,
              owner_instance=["Org1", "Org1"])

    def test_deny_multi_entity_owner_mismatch(self, engine):
        check(engine, Decision.DENY, subject_id="ada",
              resource_type=[LOC, ORG], resource_id=["L1", "O1"],
              action_type=READ, owner_indicatory_entity=ORG,
              owner_instance=["Org1", "otherOrg"])

    def test_deny_member_modify_location(self, engine):
        check(engine, Decision.DENY, subject_id="ada", resource_type=LOC,
              resource_id="L1", action_type=MODIFY,
              owner_indicatory_entity=ORG, owner_instance="Org1")

    def test_permit_manager_modify_location(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", subject_role="manager",
              role_scoping_instance="SuperOrg1", resource_type=LOC,
              resource_id="L1", action_type=MODIFY,
              owner_indicatory_entity=ORG, owner_instance="Org1")

    def test_deny_manager_foreign_org(self, engine):
        # HR scopes restricted to Org2 subtree; owner Org1 is outside it
        check(engine, Decision.DENY, subject_id="ada", subject_role="manager",
              role_scoping_instance="Org2", resource_type=LOC, resource_id="L1",
              action_type=MODIFY, owner_indicatory_entity=ORG,
              owner_instance="Org1",
              hierarchical_scopes=[{"id": "Org2", "children": [{"id": "Org3"}]}])

    def test_permit_manager_execute(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", subject_role="manager",
              resource_type="mutation.runPipeline",
              resource_id="mutation.runPipeline", action_type=EXECUTE,
              owner_indicatory_entity=ORG, owner_instance="Org1")

    def test_deny_manager_execute_foreign_org(self, engine):
        check(engine, Decision.DENY, subject_id="ada", subject_role="manager",
              role_scoping_instance="Org2",
              resource_type="mutation.runPipeline",
              resource_id="mutation.runPipeline", action_type=EXECUTE,
              owner_indicatory_entity=ORG, owner_instance="Org1",
              hierarchical_scopes=[{"id": "Org2", "role": "manager",
                                     "children": [{"id": "Org3"}]}])

    def test_deny_member_execute(self, engine):
        check(engine, Decision.DENY, subject_id="ada",
              resource_type="mutation.runPipeline",
              resource_id="mutation.runPipeline", action_type=EXECUTE,
              owner_indicatory_entity=ORG, owner_instance="Org1")


class TestHRDisabled:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("hr_disabled.yml")

    def test_permit_direct_scope(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=LOC,
              resource_id="L1", action_type=READ,
              owner_indicatory_entity=ORG, owner_instance="Org1")

    def test_deny_hierarchical_scope_disabled(self, engine):
        # owner Org2 is inside the HR subtree of Org1 but HR matching is off
        check(engine, Decision.DENY, subject_id="ada", resource_type=LOC,
              resource_id="L1", action_type=READ,
              owner_indicatory_entity=ORG, owner_instance="Org2")


class TestConditions:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("conditions.yml")

    def test_deny_modify_other_account(self, engine):
        check(engine, Decision.DENY, subject_id="ada", resource_type=USER,
              resource_id="not-ada", action_type=MODIFY)

    def test_permit_modify_own_account(self, engine):
        check(engine, Decision.PERMIT, subject_id="ada", resource_type=USER,
              resource_id="ada", action_type=MODIFY)

    def test_deny_invalid_context(self, engine):
        # with no context at all the role-gated rules can't match; the
        # fallback deny rule still applies (status stays 200)
        request = build_request(
            subject_id="ada", subject_role="member",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=USER, resource_id="ada", action_type=MODIFY,
        )
        request.context = None
        response = engine.is_allowed(request)
        assert response.decision == Decision.DENY

    def test_deny_condition_exception(self, engine):
        # a context that lets the conditional rule match but makes its
        # condition raise -> deny-by-default with an error status
        request = build_request(
            subject_id="ada", subject_role="member",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=USER, resource_id="ada", action_type=MODIFY,
        )
        del request.context["resources"]
        response = engine.is_allowed(request)
        assert response.decision == Decision.DENY
        assert response.operation_status.code == 500
