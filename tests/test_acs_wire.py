"""Self-authorization hardening over the WIRE (VERDICT r2 item 9):
exact 403 message texts, runtime config_update and the set_api_key bypass
all driven end-to-end through the gRPC transport — the reference's
microservice_acs_enabled.spec.ts flow (:379-1075, :613-617)."""

import threading

import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

from .test_srv_acs import (
    HR_TREE,
    TEST_ENTITY,
    denied_message,
    role_associations,
)
from .utils import URNS, fixture, marshall_yaml_policies

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SUBJECT_ID_URN = "urn:oasis:names:tc:xacml:1.0:subject:subject-id"


@pytest.fixture(scope="module")
def rig():
    w = Worker().start(
        {
            "policies": {"type": "database"},
            "authorization": {"enabled": False, "enforce": False,
                              "hrReqTimeout": 2000},
        }
    )
    w.identity_client.register(
        "admin_token",
        {
            "id": "admin_user_id",
            "tokens": [{"token": "admin_token"}],
            "role_associations": role_associations("admin-r-id"),
        },
    )
    auth_topic = w.bus.topic("io.restorecommerce.authentication")

    def responder(event_name, message, ctx):
        if event_name != "hierarchicalScopesRequest":
            return

        def reply():
            auth_topic.emit(
                "hierarchicalScopesResponse",
                {
                    "token": message["token"],
                    "subject_id": "admin_user_id",
                    "hierarchical_scopes": HR_TREE,
                },
            )

        threading.Thread(target=reply, daemon=True).start()

    auth_topic.on(responder)

    # seed the self-auth policies while ACS is off
    policy_sets, policies, rules = marshall_yaml_policies(
        fixture("default_policies.yml")
    )
    w.store.get_resource_service("policy_set").create(policy_sets)
    w.store.get_resource_service("policy").create(policies)
    w.store.get_resource_service("rule").create(rules)

    server = GrpcServer(w, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    yield w, client
    client.close()
    server.stop()
    w.stop()


def wire_rule(rule_id: str, owner_instance: str = "orgC") -> pb.Rule:
    rule = pb.Rule(id=rule_id, name=f"rule {rule_id}", effect="PERMIT")
    rule.target.subjects.add(id=SUBJECT_ID_URN, value="test-r-id")
    rule.target.resources.add(id=URNS["entity"], value=TEST_ENTITY)
    owner = rule.meta.owners.add(
        id=URNS["ownerIndicatoryEntity"], value=ORG
    )
    owner.attributes.add(id=URNS["ownerInstance"], value=owner_instance)
    return rule


def admin_pb_subject(scope: str = "orgC") -> pb.Subject:
    return pb.Subject(id="admin_user_id", token="admin_token", scope=scope)


def test_config_update_toggles_authorization_over_wire(rig):
    worker, client = rig
    assert worker.cfg.get("authorization:enabled") is False

    out = client.command("config_update", {"authorization:enabled": True})
    assert out["status"] == "updated"
    assert worker.cfg.get("authorization:enabled") is True

    # invalid scope now denied with the reference's exact 403 text
    result = client.crud(
        "rule", "Create",
        pb.RuleList(items=[wire_rule("wire_acs_r1", "INVALID")],
                    subject=admin_pb_subject(scope="orgA")),
    )
    assert result.operation_status.code == 403
    assert result.operation_status.message == denied_message(
        "admin_user_id", "rule", "CREATE", "orgA"
    )

    # valid scope + owner permits over the wire
    result = client.crud(
        "rule", "Create",
        pb.RuleList(items=[wire_rule("wire_acs_r2")],
                    subject=admin_pb_subject(scope="orgC")),
    )
    assert result.operation_status.code == 200


def test_set_api_key_bypass_over_wire(rig):
    worker, client = rig
    client.command("config_update", {"authorization:enabled": True})

    # no key set: an unknown operator subject is denied
    nobody = pb.Subject(id="ops", token="ops-secret-key", scope="orgA")
    result = client.crud(
        "rule", "Create",
        pb.RuleList(items=[wire_rule("wire_acs_r3")], subject=nobody),
    )
    assert result.operation_status.code == 403
    assert result.operation_status.message == denied_message(
        "ops", "rule", "CREATE", "orgA"
    )

    # set_api_key over the wire: the same subject now bypasses ACS
    out = client.command(
        "set_api_key", {"authentication": {"apiKey": "ops-secret-key"}}
    )
    assert out["status"] == "set"
    result = client.crud(
        "rule", "Create",
        pb.RuleList(items=[wire_rule("wire_acs_r3")], subject=nobody),
    )
    assert result.operation_status.code == 200

    # a wrong key still goes through ACS and is denied
    wrong = pb.Subject(id="ops", token="not-the-key", scope="orgA")
    result = client.crud(
        "rule", "Create",
        pb.RuleList(items=[wire_rule("wire_acs_r4")], subject=wrong),
    )
    assert result.operation_status.code == 403
