"""acs-lint unit tests: each rule against its fixture module, baseline
gate semantics (new / stale / unjustified), suppression accounting,
idempotence, and the runtime lock-order detector's self-tests.

The fixture tree (tests/fixtures/analysis/) is OUTSIDE the shipped scan
root on purpose: its modules violate every rule by construction and must
never leak into the package gate (tests/test_analysis_gate.py)."""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import pytest

from access_control_srv_tpu.analysis import (
    ALL_RULES,
    run_analysis,
)
from access_control_srv_tpu.analysis import baseline as baseline_mod
from access_control_srv_tpu.analysis.locktrace import (
    LockOrderError,
    lock_order_watch,
)
from access_control_srv_tpu.analysis.runner import main as lint_main

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"

# the complete expected finding set for the fixture tree: (path, rule,
# symbol) — line numbers are deliberately NOT part of finding identity
EXPECTED = {
    ("tests/fixtures/analysis/fix_blocking.py", "blocking-under-lock",
     "Pump.stall:time.sleep"),
    ("tests/fixtures/analysis/fix_blocking.py", "blocking-under-lock",
     "Pump.drain:self.jobs.get"),
    ("tests/fixtures/analysis/fix_blocking.py", "blocking-under-lock",
     "Pump.flush:os.fsync"),
    ("tests/fixtures/analysis/fix_dispatch.py", "dispatch-purity",
     "Kernel.evaluate_async:block_until_ready"),
    ("tests/fixtures/analysis/fix_dispatch.py", "dispatch-purity",
     "Kernel.evaluate_async:np.asarray(out_dev)"),
    ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
     "Store.unlocked_read:self._data"),
    ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
     "Store.unlocked_write:self._data"),
    ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
     "Store.wrong_lock:self._data"),
    ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
     "peek:_registry"),
    ("tests/fixtures/analysis/fix_hostonly.py", "host-only-jax",
     "<module>:import jax"),
    ("tests/fixtures/analysis/fix_hostonly.py", "host-only-jax",
     "lazy:import jax.numpy"),
    ("tests/fixtures/analysis/fix_threads.py", "thread-lifecycle",
     "leak:Thread(<unassigned>)"),
    ("tests/fixtures/analysis/fix_wallclock.py", "wall-clock",
     "deadline_in:time.time"),
}


@pytest.fixture(scope="module")
def fixture_report():
    return run_analysis(FIXTURES)


# --------------------------------------------------------------- findings


def test_fixture_findings_exact(fixture_report):
    """Every planted violation is found; nothing else is."""
    assert {f.key for f in fixture_report.findings} == EXPECTED
    assert not fixture_report.errors


def test_every_rule_exercised(fixture_report):
    assert {f.rule for f in fixture_report.findings} == set(ALL_RULES)


def test_findings_carry_display_line_and_message(fixture_report):
    for finding in fixture_report.findings:
        assert finding.line > 0
        assert finding.message


def test_suppressions_counted_with_reasons(fixture_report):
    sups = {(s.path, s.rule): s.reason
            for s in fixture_report.suppressions}
    assert ("tests/fixtures/analysis/fix_guarded.py",
            "guarded-by") in sups
    assert ("tests/fixtures/analysis/fix_wallclock.py",
            "wall-clock") in sups
    assert len(fixture_report.suppressions) == 2
    for reason in sups.values():
        assert reason.strip()


def test_idempotent(fixture_report):
    """Two runs over the same tree produce identical ordered findings."""
    again = run_analysis(FIXTURES)
    assert [f.key for f in again.findings] == \
        [f.key for f in fixture_report.findings]
    assert [(s.path, s.rule, s.symbol, s.line) for s in again.suppressions] \
        == [(s.path, s.rule, s.symbol, s.line)
            for s in fixture_report.suppressions]


# ---------------------------------------------------------- baseline gate


def _write_baseline(path: Path, keys, justification="accepted in test"):
    path.write_text(json.dumps({
        "version": 1,
        "suppressions": [
            {"path": p, "rule": r, "symbol": s,
             "justification": justification}
            for (p, r, s) in sorted(keys)
        ],
    }))


def test_baseline_full_match_is_clean(tmp_path, fixture_report):
    bl = tmp_path / "baseline.json"
    _write_baseline(bl, EXPECTED)
    report = run_analysis(FIXTURES, baseline=bl)
    assert report.diff is not None
    assert report.diff.clean and report.ok
    assert report.diff.matched == len(EXPECTED)


def test_new_finding_fails_gate(tmp_path):
    bl = tmp_path / "baseline.json"
    partial = sorted(EXPECTED)[:-1]
    _write_baseline(bl, partial)
    report = run_analysis(FIXTURES, baseline=bl)
    assert not report.ok
    assert [f.key for f in report.diff.new] == [sorted(EXPECTED)[-1]]


def test_stale_entry_fails_gate(tmp_path):
    """A baselined finding that no longer exists must fail the run —
    a stale suppression can swallow a future regression."""
    bl = tmp_path / "baseline.json"
    ghost = ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
             "Store.fixed_long_ago:self._data")
    _write_baseline(bl, set(EXPECTED) | {ghost})
    report = run_analysis(FIXTURES, baseline=bl)
    assert not report.ok
    assert [e.key for e in report.diff.stale] == [ghost]


def test_unjustified_entry_fails_gate(tmp_path):
    bl = tmp_path / "baseline.json"
    _write_baseline(bl, EXPECTED, justification="   ")
    report = run_analysis(FIXTURES, baseline=bl)
    assert not report.ok
    assert len(report.diff.unjustified) == len(EXPECTED)


def test_save_carries_justifications(tmp_path, fixture_report):
    bl = tmp_path / "baseline.json"
    key = sorted(EXPECTED)[0]
    baseline_mod.save(bl, fixture_report.findings, {key: "why"})
    entries = {e.key: e.justification for e in baseline_mod.load(bl)}
    assert entries[key] == "why"
    assert set(entries) == EXPECTED


# ------------------------------------------------------------- CLI runner


def test_cli_exit_codes(tmp_path, capsys):
    assert lint_main(["--root", str(FIXTURES), "--no-baseline"]) == 1
    bl = tmp_path / "baseline.json"
    _write_baseline(bl, EXPECTED)
    assert lint_main(
        ["--root", str(FIXTURES), "--baseline", str(bl)]
    ) == 0
    ghost = ("tests/fixtures/analysis/fix_guarded.py", "guarded-by",
             "Store.fixed_long_ago:self._data")
    _write_baseline(bl, set(EXPECTED) | {ghost})
    assert lint_main(
        ["--root", str(FIXTURES), "--baseline", str(bl)]
    ) == 1
    out = capsys.readouterr().out
    assert "stale-baseline" in out


def test_cli_json_report(capsys):
    lint_main(["--root", str(FIXTURES), "--no-baseline", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert data["ok"] is False
    assert {tuple(f[k] for k in ("path", "rule", "symbol"))
            for f in data["findings"]} == EXPECTED
    assert data["by_rule"]["guarded-by"] == 4


# ------------------------------------------------- runtime lock ordering


def test_locktrace_detects_injected_inversion():
    """A,B then B,A — sequentially, one thread — is already a conviction:
    the orders happened, the deadlock merely hasn't been scheduled."""
    with lock_order_watch() as watch:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    with pytest.raises(LockOrderError) as exc:
        watch.assert_acyclic()
    assert exc.value.cycle[0] == exc.value.cycle[-1]
    assert len(exc.value.cycle) >= 3


def test_locktrace_consistent_order_is_clean():
    with lock_order_watch() as watch:
        a = threading.Lock()
        b = threading.Lock()
        c = threading.RLock()
        for _ in range(3):
            with a:
                with b:
                    with c:
                        pass
            with a:
                with c:
                    pass
    watch.assert_acyclic()
    assert watch.edges()  # the order graph was actually recorded


def test_locktrace_reentrant_rlock_no_self_edge():
    with lock_order_watch() as watch:
        r = threading.RLock()
        with r:
            with r:
                pass
    watch.assert_acyclic()
    assert not watch.edges()


def test_locktrace_condition_compatible():
    """Tracked locks serve as Condition underlying locks: wait_for
    releases/restores through the private hooks, cross-thread."""
    with lock_order_watch() as watch:
        cond = threading.Condition(threading.Lock())
        rcond = threading.Condition()  # default RLock, also tracked
        released = []

        def waiter():
            with cond:
                cond.wait_for(lambda: bool(released), timeout=2.0)

        thread = threading.Thread(target=waiter)
        thread.start()
        time.sleep(0.05)
        with cond:
            released.append(True)
            cond.notify_all()
        thread.join(timeout=2.0)
        assert not thread.is_alive()
        with rcond:
            rcond.wait(timeout=0.01)
    watch.assert_acyclic()


def test_locktrace_uninstall_restores_factories():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    with lock_order_watch():
        assert threading.Lock is not orig_lock
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock
