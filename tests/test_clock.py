"""srv/clock.py: monotonic-anchored wall clock, and the regression the
module exists for — ResourceService metadata stamps must never go
backward when the wall clock does (NTP slew, manual adjustment).

Before the fix, ``ResourceService._create_metadata`` stamped
``meta.modified``/``meta.created`` straight from ``time.time()``: a
backward wall step between two MODIFYs produced ``modified`` values that
DECREASE while document history advances, silently reordering history
for replication reconciliation and any since-I-read-it client check."""

from __future__ import annotations

import time

import pytest

from access_control_srv_tpu.core.engine import AccessController
from access_control_srv_tpu.srv import clock as clock_mod
from access_control_srv_tpu.srv import store as store_mod
from access_control_srv_tpu.srv.clock import monotonic_wall
from access_control_srv_tpu.srv.store import PolicyStore


# ---------------------------------------------------------- monotonic_wall


def test_monotonic_wall_reads_as_epoch_seconds():
    assert abs(monotonic_wall() - time.time()) < 5.0


def test_monotonic_wall_never_decreases():
    last = monotonic_wall()
    for _ in range(1000):
        now = monotonic_wall()
        assert now >= last
        last = now


def test_monotonic_wall_immune_to_wall_steps(monkeypatch):
    """Stepping the wall clock (as NTP would) must not move the value:
    only the monotonic term advances after the import-time anchor."""
    before = monotonic_wall()
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() - 3600.0)
    during = monotonic_wall()
    assert during >= before
    assert during - before < 5.0  # the -1h step did NOT leak through


# ----------------------------------------------- metadata stamp regression


@pytest.fixture()
def rule_service():
    store = PolicyStore(AccessController())
    return store.services["rule"]


def test_modified_stamp_survives_backward_wall_step(
    rule_service, monkeypatch
):
    """MODIFY after a backward wall step: the new ``modified`` stamp must
    not precede the previous one (regression for the time.time() stamp)."""
    doc = {"id": "r-clock", "effect": "PERMIT"}
    first = rule_service._create_metadata([dict(doc)], "CREATE", None)[0]
    t_first = first["meta"]["modified"]

    # the wall clock steps back one hour between the two mutations
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() - 3600.0)
    second = rule_service._create_metadata([dict(doc)], "MODIFY", None)[0]
    t_second = second["meta"]["modified"]

    assert t_second >= t_first, (
        f"modified went backward across a wall step: {t_first} -> "
        f"{t_second} — document history reordered"
    )


def test_created_preserved_and_epoch_like(rule_service):
    """created falls out of the monotonic-anchored clock but still reads
    as a plausible Unix epoch stamp (wire compatibility)."""
    [item] = rule_service._create_metadata(
        [{"id": "r-epoch", "effect": "PERMIT"}], "CREATE", None
    )
    created = item["meta"]["created"]
    assert abs(created - time.time()) < 60.0
    assert item["meta"]["modified"] >= created


def test_store_module_uses_blessed_clock():
    """The stamp path imports monotonic_wall; raw time.time() must not
    return (acs-lint's wall-clock rule enforces this tree-wide, this is
    the targeted guard)."""
    import inspect

    src = inspect.getsource(store_mod.ResourceService._create_metadata)
    assert "now = monotonic_wall()" in src
    code_lines = [ln.split("#", 1)[0] for ln in src.splitlines()]
    assert not any("time.time()" in ln for ln in code_lines)
    # and the clock module carries the single blessed wall read
    assert "time.time()" in inspect.getsource(clock_mod)
