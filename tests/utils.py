"""Test helpers: request builder + engine construction.

The builder produces the canonical request/context wire shape the service
receives after protobuf-Any unmarshalling (modeled on the reference test
harness, test/utils.ts buildRequest): subject attributes are
[role, subject-id]; resources are (entity, resource-id, properties...) runs
or operation attributes for execute actions; context carries resource meta
(owners, acls) and the subject's role associations + hierarchical scopes.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Union

from access_control_srv_tpu.core import AccessController, populate
from access_control_srv_tpu.models import Attribute, Request, Target, Urns

URNS = Urns()

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def make_engine(fixture_name: Optional[str] = None, **kwargs) -> AccessController:
    engine = AccessController(**kwargs)
    if fixture_name:
        populate(engine, fixture(fixture_name))
    return engine


def marshall_yaml_policies(path: str):
    """Flatten a nested fixture YAML into the three flat CRUD payload lists
    (children referenced by id), the shape the resource services persist
    (modeled on reference test/utils.ts marshallYamlPolicies:282-309)."""
    import yaml

    with open(path) as fh:
        doc = yaml.safe_load(fh)
    policy_sets, policies, rules = [], [], []
    for ps in doc.get("policy_sets") or []:
        ps = dict(ps)
        child_policies = ps.pop("policies", []) or []
        ps["policies"] = []
        for pol in child_policies:
            pol = dict(pol)
            child_rules = pol.pop("rules", []) or []
            pol["rules"] = []
            for rule in child_rules:
                rule = dict(rule)
                pol["rules"].append(rule["id"])
                rules.append(rule)
            ps["policies"].append(pol["id"])
            policies.append(pol)
        policy_sets.append(ps)
    return policy_sets, policies, rules


def _listify(value) -> list:
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def build_request(
    subject_id: Optional[str] = None,
    subject_role: Optional[str] = None,
    role_scoping_entity: Optional[str] = None,
    role_scoping_instance: Union[str, Sequence[str], None] = None,
    resource_type: Union[str, Sequence[str], None] = None,
    resource_id: Union[str, Sequence[str], None] = None,
    resource_property: Union[str, Sequence, None] = None,
    action_type: Optional[str] = None,
    owner_indicatory_entity: Optional[str] = None,
    owner_instance: Union[str, Sequence[str], None] = None,
    acl_indicatory_entity: Optional[str] = None,
    acl_instances: Optional[Sequence[str]] = None,
    multiple_acl_indicatory_entity: Optional[Sequence[str]] = None,
    org_instances: Optional[Sequence[str]] = None,
    subject_instances: Optional[Sequence[str]] = None,
    hierarchical_scopes: Optional[list] = None,
) -> Request:
    subjects = [
        Attribute(id=URNS["role"], value=subject_role or "member"),
        Attribute(id=URNS["subjectID"], value=subject_id or ""),
    ]

    resources: list[Attribute] = []
    types = _listify(resource_type)
    ids = _listify(resource_id)
    props = _listify(resource_property)

    if action_type == URNS["execute"]:
        for operation_name in types:
            resources.append(Attribute(id=URNS["operation"], value=operation_name))
    else:
        for i, rtype in enumerate(types):
            resources.append(Attribute(id=URNS["entity"], value=rtype))
            resources.append(
                Attribute(id=URNS["resourceID"], value=ids[i] if i < len(ids) else "")
            )
            for prop in props:
                if isinstance(prop, str):
                    resources.append(Attribute(id=URNS["property"], value=prop))
                else:
                    # nested per-entity property lists: keep only properties
                    # belonging to this entity
                    entity_name = rtype.rsplit(":", 1)[-1]
                    for p in prop:
                        if entity_name in p:
                            resources.append(Attribute(id=URNS["property"], value=p))

    actions = [Attribute(id=URNS["actionID"], value=action_type or "")]

    acls: list[dict] = []
    if acl_indicatory_entity and acl_instances:
        acls = [
            {
                "id": URNS["aclIndicatoryEntity"],
                "value": acl_indicatory_entity,
                "attributes": [
                    {"id": URNS["aclInstance"], "value": inst}
                    for inst in acl_instances
                ],
            }
        ]
    elif multiple_acl_indicatory_entity and org_instances and subject_instances:
        acls = [
            {
                "id": URNS["aclIndicatoryEntity"],
                "value": multiple_acl_indicatory_entity[0],
                "attributes": [
                    {"id": URNS["aclInstance"], "value": inst}
                    for inst in org_instances
                ],
            },
            {
                "id": URNS["aclIndicatoryEntity"],
                "value": multiple_acl_indicatory_entity[1],
                "attributes": [
                    {"id": URNS["aclInstance"], "value": inst}
                    for inst in subject_instances
                ],
            },
        ]

    owner_instances = _listify(owner_instance)
    ctx_resources: list[dict] = []
    for i, rid in enumerate(ids if action_type != URNS["execute"] else types):
        owners = []
        if owner_indicatory_entity and owner_instances:
            inst = (
                owner_instances[i]
                if i < len(owner_instances)
                else owner_instances[-1]
            )
            owners = [
                {
                    "id": URNS["ownerIndicatoryEntity"],
                    "value": owner_indicatory_entity,
                    "attributes": [
                        {"id": URNS["ownerInstance"], "value": inst}
                    ],
                }
            ]
        ctx_resources.append({"id": rid, "meta": {"owners": owners, "acls": acls}})

    role_associations = []
    if subject_role and role_scoping_entity and role_scoping_instance:
        role_associations = [
            {
                "role": subject_role,
                "attributes": [
                    {
                        "id": URNS["roleScopingEntity"],
                        "value": role_scoping_entity,
                        "attributes": [
                            {
                                "id": URNS["roleScopingInstance"],
                                "value": inst,
                            }
                            for inst in _listify(role_scoping_instance)
                        ],
                    }
                ],
            }
        ]

    if hierarchical_scopes is None:
        hierarchical_scopes = (
            [
                {
                    "id": "SuperOrg1",
                    "role": subject_role,
                    "children": [
                        {
                            "id": "Org1",
                            "children": [
                                {"id": "Org2", "children": [{"id": "Org3"}]}
                            ],
                        }
                    ],
                }
            ]
            if role_scoping_entity and role_scoping_instance
            else []
        )

    return Request(
        target=Target(subjects=subjects, resources=resources, actions=actions),
        context={
            "resources": ctx_resources,
            "subject": {
                "id": subject_id,
                "role_associations": role_associations,
                "hierarchical_scopes": hierarchical_scopes,
            },
        },
    )
