"""JS-condition migration: reference policies carrying raw JavaScript
conditions (the reference evals them, src/core/utils.ts:47-56) run
UNMODIFIED through the JS-subset interpreter (core/js_conditions.py).

The fixture tests load the REFERENCE'S OWN fixture files straight from
/root/reference/test/fixtures (read-only; skipped when absent) — no
hand-migration, the acceptance bar for existing policy corpora.
"""

import os

import pytest

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.conditions import condition_matches
from access_control_srv_tpu.core.js_conditions import (
    JsConditionError,
    evaluate_js_condition,
)
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.models import Attribute, Request, Target, Urns

URNS = Urns()
REFERENCE_FIXTURES = "/root/reference/test/fixtures"
USER = "urn:restorecommerce:acs:model:user.User"
LOCATION = "urn:restorecommerce:acs:model:location.Location"


def req(role, entity, action, context=None, subject_id="u1"):
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value=role),
                      Attribute(id=URNS["subjectID"], value=subject_id)],
            resources=[Attribute(id=URNS["entity"], value=entity)],
            actions=[Attribute(id=URNS["actionID"], value=URNS[action])],
        ),
        context=context if context is not None else {
            "resources": [],
            "subject": {"id": subject_id,
                        "role_associations": [
                            {"role": role, "attributes": []}],
                        "hierarchical_scopes": []},
        },
    )


# --------------------------------------------------------- interpreter unit

class TestInterpreter:
    def _r(self, context):
        return Request(target=Target(subjects=[], resources=[], actions=[]),
                       context=context)

    def test_find_and_null(self):
        r = self._r({"resources": [{"id": "a"}, {"id": "b"}]})
        assert evaluate_js_condition(
            'context.resources.find((x) => { return x.id == "b"; }) != null;',
            r)
        assert not evaluate_js_condition(
            'context.resources.find((x) => { return x.id == "z"; }) != null;',
            r)

    def test_let_if_completion(self):
        r = self._r({"subject": {"id": "u7"}, "resources": [{"id": "u7"}]})
        cond = """
            let subjectID;
            if (context && context.subject) {
              subjectID = context.subject.id;
            }
            let resources = context.resources;
            if (!resources) {
              resources = [];
            }
            resources.find((resource) => {
                return resource.id == subjectID;
            }) != null;"""
        assert evaluate_js_condition(cond, r)
        r2 = self._r({"subject": {"id": "u7"}, "resources": [{"id": "x"}]})
        assert not evaluate_js_condition(cond, r2)
        # no resources key: the guard substitutes [] -> no match
        r3 = self._r({"subject": {"id": "u7"}})
        assert not evaluate_js_condition(cond, r3)

    def test_property_of_null_raises_like_js(self):
        r = self._r(None)
        with pytest.raises(JsConditionError):
            evaluate_js_condition("context.resources.length > 0;", r)

    def test_js_truthiness_empty_array(self):
        r = self._r({"resources": []})
        # [] is truthy in JS, unlike Python
        assert evaluate_js_condition(
            "context.resources ? true : false;", r)

    def test_loose_vs_strict_equality(self):
        r = self._r({"n": 5})
        assert evaluate_js_condition('context.n == "5";', r)
        assert not evaluate_js_condition('context.n === "5";', r)

    def test_budget_bounds_runaway(self):
        r = self._r({"xs": list(range(100))})
        with pytest.raises(JsConditionError):
            evaluate_js_condition(
                "context.xs.map((a) => context.xs.map((b) => "
                "context.xs.map((c) => context.xs.map((d) => d))));", r)

    def test_dunder_traversal_blocked(self):
        r = self._r({"resources": []})
        with pytest.raises(JsConditionError):
            evaluate_js_condition(
                "request.__init__.__globals__ && true;", r)
        with pytest.raises(JsConditionError):
            evaluate_js_condition("target._replace && true;", r)

    def test_model_methods_invisible(self):
        r = self._r({"resources": []})
        # callables on model objects read as undefined, never invocable
        assert not evaluate_js_condition(
            "typeof request.copy == 'function';", r)

    def test_strict_equality_numbers(self):
        r = self._r({"n": 2.0})
        assert evaluate_js_condition("context.n === 2;", r)
        assert not evaluate_js_condition("context.n === true;", r)
        assert not evaluate_js_condition('context.n === "2";', r)

    def test_includes_is_strict(self):
        r = self._r({"xs": ["1", 2]})
        assert not evaluate_js_condition("context.xs.includes(1);", r)
        assert evaluate_js_condition("context.xs.includes(2);", r)
        assert evaluate_js_condition('context.xs.includes("1");', r)

    def test_str_methods_arity_safe(self):
        r = self._r({"s": "abcundefined"})
        assert evaluate_js_condition("context.s.includes();", r)
        assert not evaluate_js_condition('"abc".includes();', r)

    def test_condition_matches_routes_js(self):
        r = self._r({"resources": [{"id": "a"}]})
        assert condition_matches(
            'context.resources.find((x) => x.id == "a") != null;', r)


# --------------------------------------------- reference fixtures, verbatim

needs_reference = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_FIXTURES),
    reason="reference fixtures not present",
)


def load_reference_fixture(name):
    import yaml

    with open(os.path.join(REFERENCE_FIXTURES, name)) as fh:
        doc = yaml.safe_load(fh)
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    return engine


@needs_reference
class TestReferenceConditionsFixture:
    """Golden decisions over the UNMODIFIED reference conditions.yml
    (reference suite: test/core.spec.ts condition tests)."""

    @pytest.fixture(scope="class")
    def engine(self):
        return load_reference_fixture("conditions.yml")

    def test_read_permits_without_condition(self, engine):
        assert engine.is_allowed(
            req("SimpleUser", USER, "read")).decision == "PERMIT"

    def test_modify_own_account_permits(self, engine):
        context = {
            "subject": {"id": "u1", "role_associations": [
                {"role": "SimpleUser", "attributes": []}],
                "hierarchical_scopes": []},
            "resources": [{"id": "u1"}],
        }
        assert engine.is_allowed(
            req("SimpleUser", USER, "modify", context)
        ).decision == "PERMIT"

    def test_modify_foreign_account_denies(self, engine):
        context = {
            "subject": {"id": "u1", "role_associations": [
                {"role": "SimpleUser", "attributes": []}],
                "hierarchical_scopes": []},
            "resources": [{"id": "someone-else"}],
        }
        assert engine.is_allowed(
            req("SimpleUser", USER, "modify", context)
        ).decision == "DENY"

    def test_modify_with_empty_context_raises_like_reference(self, engine):
        # quirk parity: the matched fallback rule's ACL check dereferences
        # context.subject without a guard in the reference
        # (verifyACL.ts:112) — a subject-less context THROWS, and the
        # SERVICE envelope turns it into DENY (accessControlService.ts
        # :70-80; our srv/service.py deny-on-error)
        from access_control_srv_tpu.core.errors import InvalidRequestContext

        with pytest.raises(InvalidRequestContext):
            engine.is_allowed(req("SimpleUser", USER, "modify", {}))


@needs_reference
class TestReferenceContextQueryFixture:
    """The UNMODIFIED reference context_query.yml: adapter-fed
    _queryResult + JS condition (reference: accessController.ts:227-270,
    gql adapter src/core/resource_adapters/gql.ts)."""

    def make_engine(self, rows):
        engine = load_reference_fixture("context_query.yml")

        class Adapter:
            calls = []

            def query(self, context_query, request):
                self.calls.append(context_query)
                return rows

        engine.resource_adapter = Adapter()
        return engine

    def modify_request(self):
        # the resourceID attribute matters: without one, the matched
        # rule's ACL check dereferences the (context-query-merged)
        # context's missing subject and throws — with it, the no-ACL
        # early pass fires first (verifyACL.ts:56-59)
        return Request(
            target=Target(
                subjects=[Attribute(id=URNS["role"], value="SimpleUser")],
                resources=[
                    Attribute(id=URNS["entity"], value=LOCATION),
                    Attribute(id=URNS["resourceID"], value="loc1"),
                    Attribute(id=URNS["property"], value=LOCATION + "#address"),
                ],
                actions=[Attribute(id=URNS["actionID"], value=URNS["modify"])],
            ),
            context={
                "resources": [{"id": "loc1",
                               "address_id": "addr1"}],
                "subject": {"id": "u1", "role_associations": [
                    {"role": "SimpleUser", "attributes": []}],
                    "hierarchical_scopes": []},
            },
        )

    def test_german_address_permits(self):
        engine = self.make_engine(
            [{"payload": {"country_id": "Germany"}}]
        )
        assert engine.is_allowed(
            self.modify_request()).decision == "PERMIT"

    def test_foreign_address_denies(self):
        engine = self.make_engine(
            [{"payload": {"country_id": "France"}}]
        )
        assert engine.is_allowed(
            self.modify_request()).decision == "DENY"

    def test_mixed_addresses_deny(self):
        engine = self.make_engine([
            {"payload": {"country_id": "Germany"}},
            {"payload": {"country_id": "France"}},
        ])
        assert engine.is_allowed(
            self.modify_request()).decision == "DENY"

    def test_empty_query_result_is_vacuous_permit(self):
        # the reference's nil-check deny (accessController.ts:240-251) is
        # dead code — lodash merge never yields nil — so an EMPTY result
        # reaches the condition, whose find over [] returns undefined:
        # vacuously "all addresses are German" => PERMIT
        engine = self.make_engine([])
        assert engine.is_allowed(
            self.modify_request()).decision == "PERMIT"


@needs_reference
def test_reference_fixture_corpus_loads_unmodified():
    """Every reference fixture YAML parses and loads into the engine
    without modification (the PRP surface of the migration story)."""
    import yaml

    loaded = 0
    for name in sorted(os.listdir(REFERENCE_FIXTURES)):
        if not name.endswith(".yml"):
            continue
        with open(os.path.join(REFERENCE_FIXTURES, name)) as fh:
            doc = yaml.safe_load(fh)
        if not isinstance(doc, dict) or "policy_sets" not in doc:
            continue
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        assert engine.policy_sets
        loaded += 1
    assert loaded >= 10, f"only {loaded} fixture files loaded"
