"""Process entrypoint: python -m access_control_srv_tpu serves the gRPC
surface and shuts down cleanly on SIGINT (reference: src/start.ts:6-21)."""

import json
import os
import signal
import subprocess
import sys

from access_control_srv_tpu.srv.transport_grpc import GrpcClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_main_serves_and_stops_on_sigint(tmp_path):
    cfg_dir = tmp_path / "cfg"
    cfg_dir.mkdir()
    (cfg_dir / "config.json").write_text(
        json.dumps({"policies": {"type": "local", "paths": []}})
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "access_control_srv_tpu",
         "--config-dir", str(cfg_dir), "--addr", "127.0.0.1:0"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("serving on 127.0.0.1:"), line
        addr = line.split()[-1]
        client = GrpcClient(addr)
        assert client.health() == "SERVING"
        client.close()
    finally:
        proc.send_signal(signal.SIGINT)
        out, err = proc.communicate(timeout=30)
    assert "shutting down" in out, (out, err)
    assert proc.returncode == 0


def test_main_broker_mode():
    proc = subprocess.Popen(
        [sys.executable, "-m", "access_control_srv_tpu",
         "--broker", "--addr", "127.0.0.1:0"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("broker listening on "), line
        address = line.split()[-1]
        from access_control_srv_tpu.srv.broker import SocketEventBus

        bus = SocketEventBus(address)
        off = bus.topic("t").emit("e", {"ok": 1})
        assert off == 0
        bus.close()
    finally:
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0


def test_main_rejects_malformed_addr():
    """--addr without a numeric port exits with a usage error instead of
    an int() traceback."""
    import pytest

    from access_control_srv_tpu.__main__ import main

    for bad in ("localhost", "127.0.0.1:", "host:port"):
        with pytest.raises(SystemExit) as exc:
            main(["--broker", "--addr", bad])
        assert exc.value.code == 2


def test_healthcheck_module_against_live_server(tmp_path):
    """Container healthcheck (python -m access_control_srv_tpu.healthcheck)
    round-trips grpc.health.v1.Health/Check against a worker served from
    the shipped cfg/ directory (Dockerfile HEALTHCHECK contract)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "access_control_srv_tpu",
         "--config-dir", "cfg", "--addr", "127.0.0.1:0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo,
    )
    try:
        import queue
        import threading
        import time

        lines: "queue.Queue[str]" = queue.Queue()

        def pump():
            for ln in proc.stdout:
                lines.put(ln)

        threading.Thread(target=pump, daemon=True).start()
        addr = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                line = lines.get(timeout=1)
            except queue.Empty:
                continue
            if "serving on" in line:
                addr = line.strip().rsplit(" ", 1)[-1]
                break
        assert addr, "server never announced its address"
        rc = subprocess.run(
            [sys.executable, "-m", "access_control_srv_tpu.healthcheck",
             addr],
            capture_output=True, text=True, env=env, cwd=repo, timeout=60,
        )
        assert rc.returncode == 0, rc.stderr
        assert "SERVING" in rc.stdout
    finally:
        proc.terminate()
        proc.wait(timeout=15)
