"""ACL matrix golden tests (behavioral contract of reference
test/acl.spec.ts:87-410 against test/fixtures/acl_policies.yml):
create / modify / delete / read with ACL instances vs HR scopes,
subject-ID ACLs, and mixed org+user ACL entities.

The subject's HR scope tree is always SuperOrg1 -> Org1 -> Org2 -> Org3
(tests/utils.build_request default, mirroring reference test/utils.ts).
"""

import pytest

from access_control_srv_tpu.models import Decision

from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
BUCKET = "urn:restorecommerce:acs:model:bucket.Bucket"
CREATE = URNS["create"]
MODIFY = URNS["modify"]
DELETE = URNS["delete"]
READ = URNS["read"]


def check(engine, expected, **kwargs):
    defaults = dict(
        subject_id="Alice",
        subject_role="Admin",
        role_scoping_entity=ORG,
        resource_type=BUCKET,
        resource_id="test",
        owner_indicatory_entity=ORG,
    )
    defaults.update(kwargs)
    request = build_request(**defaults)
    response = engine.is_allowed(request)
    assert response.decision == expected, kwargs
    return response


class TestACL:
    @pytest.fixture(scope="class")
    def engine(self):
        return make_engine("acl_policies.yml")

    # --- create (every ACL instance must be inside subject HR scopes;
    #     reference acl.spec.ts:110-215) ---

    def test_permit_create_valid_acl_instances(self, engine):
        check(engine, Decision.PERMIT, action_type=CREATE,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              acl_indicatory_entity=ORG,
              acl_instances=["Org1", "Org2", "Org3"])

    def test_deny_create_invalid_acl_instances(self, engine):
        # Org4 is not in the subject's HR tree
        check(engine, Decision.DENY, action_type=CREATE,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              acl_indicatory_entity=ORG,
              acl_instances=["Org1", "Org4"])

    def test_permit_create_subject_id_acl(self, engine):
        # user.User ACL entities are exempt from HR validation on create
        check(engine, Decision.PERMIT, action_type=CREATE,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              acl_indicatory_entity=USER,
              acl_instances=["SubjectID1", "SubjectID2"])

    def test_permit_create_mixed_acl_valid_orgs(self, engine):
        check(engine, Decision.PERMIT, action_type=CREATE,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org2", "Org3"],
              subject_instances=["SubjectID1", "SubjectID2"])

    def test_deny_create_mixed_acl_invalid_orgs(self, engine):
        check(engine, Decision.DENY, action_type=CREATE,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org4"],
              subject_instances=["SubjectID1", "SubjectID2"])

    # --- modify (>=1 subject scope or subject id must appear in the ACL;
    #     reference acl.spec.ts:217-279) ---

    def test_permit_modify_reduced_valid_acl(self, engine):
        check(engine, Decision.PERMIT, action_type=MODIFY,
              role_scoping_instance="Org1", owner_instance="Org1",
              acl_indicatory_entity=ORG, acl_instances=["Org1"])

    def test_permit_modify_subject_id_in_acl(self, engine):
        # role scoped to Org4 (outside ACL orgs) but Alice appears in the
        # user-entity ACL
        check(engine, Decision.PERMIT, action_type=MODIFY,
              role_scoping_instance="Org4", owner_instance="Org4",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org2"],
              subject_instances=["SubjectID1", "Alice"])

    def test_deny_modify_invalid_acl(self, engine):
        # ACL contains Org4 which is outside the subject's HR scopes and
        # SuperOrg1 (the subject scope) is not in the ACL
        check(engine, Decision.DENY, action_type=MODIFY,
              role_scoping_instance="SuperOrg1", owner_instance="SuperOrg1",
              acl_indicatory_entity=ORG, acl_instances=["Org1", "Org4"])

    # --- delete (same subject-scope rule as modify;
    #     reference acl.spec.ts:281-344) ---

    def test_permit_delete_valid_acl(self, engine):
        check(engine, Decision.PERMIT, action_type=DELETE,
              role_scoping_instance="Org1", owner_instance="Org1",
              acl_indicatory_entity=ORG, acl_instances=["Org1", "Org2"])

    def test_permit_delete_subject_id_in_acl(self, engine):
        check(engine, Decision.PERMIT, action_type=DELETE,
              role_scoping_instance="Org4", owner_instance="Org4",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org2"],
              subject_instances=["SubjectID1", "Alice"])

    def test_deny_delete_no_scope_or_subject_in_acl(self, engine):
        check(engine, Decision.DENY, action_type=DELETE,
              role_scoping_instance="Org4", owner_instance="Org4",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org2"],
              subject_instances=["SubjectID1"])

    # --- read by the unscoped SimpleUser rule
    #     (reference acl.spec.ts:346-408) ---

    def test_permit_read_simple_user_valid_acl(self, engine):
        check(engine, Decision.PERMIT, action_type=READ,
              subject_role="SimpleUser",
              role_scoping_instance="Org1", owner_instance="Org1",
              acl_indicatory_entity=ORG,
              acl_instances=["Org1", "Org2", "Org3"])

    def test_permit_read_simple_user_subject_id_in_acl(self, engine):
        check(engine, Decision.PERMIT, action_type=READ,
              subject_role="SimpleUser",
              role_scoping_instance="Org4", owner_instance="Org4",
              multiple_acl_indicatory_entity=[ORG, USER],
              org_instances=["Org1", "Org2"],
              subject_instances=["SubjectID1", "Alice"])

    def test_deny_read_simple_user_scope_not_in_acl(self, engine):
        check(engine, Decision.DENY, action_type=READ,
              subject_role="SimpleUser",
              role_scoping_instance="Org4", owner_instance="Org1",
              acl_indicatory_entity=ORG,
              acl_instances=["Org1", "Org2", "Org3"])
