"""Multi-chip serving: the `parallel:data_devices` config key shards the
serving path's request batches over a device mesh (here the 8 virtual CPU
devices from conftest).  The reference scales horizontally with stateless
replicas behind a load balancer (src/worker.ts:161-198); this is the
TPU-native replacement — one worker, N chips, one sharded batch — proven
through the product path (Worker -> evaluator -> kernel), not a bare
kernel.
"""

import json
import os

import jax
import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"
SEED = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data",
    "seed_data",
)


def make_worker(data_devices):
    return Worker().start(
        {
            "policies": {"type": "database"},
            "parallel": {"data_devices": data_devices},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )


def batch_requests(n):
    reqs = []
    from access_control_srv_tpu.models import Attribute, Request, Target

    for i in range(n):
        role = "superadministrator-r-id" if i % 2 == 0 else "ordinary-user"
        reqs.append(
            Request(
                target=Target(
                    subjects=[
                        Attribute(id=URNS["role"], value=role),
                        Attribute(id=URNS["subjectID"], value=f"u{i}"),
                    ],
                    resources=[
                        Attribute(id=URNS["entity"], value=ORG),
                        Attribute(id=URNS["resourceID"], value=f"r{i}"),
                    ],
                    actions=[
                        Attribute(id=URNS["actionID"], value=URNS["read"])
                    ],
                ),
                context={
                    "resources": [],
                    "subject": {
                        "id": f"u{i}",
                        "role_associations": [{"role": role, "attributes": []}],
                        "hierarchical_scopes": [],
                    },
                },
            )
        )
    return reqs


@pytest.fixture(scope="module")
def rig():
    worker = make_worker(data_devices=8)
    yield worker
    worker.stop()


def test_mesh_built_from_config(rig):
    assert rig.mesh is not None
    assert rig.mesh.devices.size == 8
    assert rig.evaluator.mesh is rig.mesh
    assert rig.evaluator.kernel_active


def test_batch_decisions_match_oracle_on_mesh(rig):
    reqs = batch_requests(24)
    out = rig.evaluator.is_allowed_batch(reqs)
    oracle = [rig.engine.is_allowed(r) for r in reqs]
    assert [r.decision for r in out] == [r.decision for r in oracle]


def test_mesh_survives_hot_mutation(rig):
    """A CRUD-triggered recompile must rebuild the kernel WITH the mesh,
    and a hot rule attached to a policy must flip the decision of a
    previously-INDETERMINATE row through the mesh path."""
    reqs = batch_requests(16)
    before = rig.evaluator.is_allowed_batch(reqs)
    assert before[1].decision == "INDETERMINATE"  # ordinary-user row

    rule_service = rig.store.get_resource_service("rule")
    rule_service.create(
        [
            {
                "id": "mesh-hot-rule",
                "name": "hot",
                "effect": "PERMIT",
                "target": {
                    "subjects": [
                        {"id": URNS["role"], "value": "ordinary-user"}
                    ],
                    "resources": [{"id": URNS["entity"], "value": ORG}],
                    "actions": [],
                },
            }
        ],
        subject=None,
    )
    policy_service = rig.store.get_resource_service("policy")
    doc = dict(policy_service.read()["items"][0]["payload"])
    doc["rules"] = list(doc.get("rules") or []) + ["mesh-hot-rule"]
    res = policy_service.update([doc], subject=None)
    assert res["operation_status"]["code"] == 200, res

    kernel = rig.evaluator._kernel
    assert kernel is not None and kernel.mesh is rig.mesh
    out = rig.evaluator.is_allowed_batch(reqs)
    oracle = [rig.engine.is_allowed(r).decision for r in reqs]
    assert [r.decision for r in out] == oracle
    assert out[1].decision == "PERMIT"


def test_all_keyword_uses_every_device():
    worker = make_worker(data_devices="all")
    try:
        assert worker.mesh.devices.size == len(jax.devices())
    finally:
        worker.stop()


def test_minus_one_string_means_all():
    worker = make_worker(data_devices="-1")
    try:
        assert worker.mesh.devices.size == len(jax.devices())
    finally:
        worker.stop()


def test_invalid_data_devices_rejected():
    with pytest.raises(ValueError, match="parallel:data_devices"):
        make_worker(data_devices="auto")
    with pytest.raises(ValueError, match="parallel:data_devices"):
        make_worker(data_devices=-2)


def test_zero_data_devices_disables_mesh():
    worker = make_worker(data_devices=0)
    try:
        assert worker.mesh is None
    finally:
        worker.stop()


def test_grpc_batch_over_mesh(rig):
    server = GrpcServer(rig, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    try:
        batch_msg = pb.BatchRequest()
        for i in range(16):
            role = "superadministrator-r-id" if i % 2 == 0 else "nobody"
            msg = batch_msg.requests.add()
            msg.target.subjects.add(id=URNS["role"], value=role)
            msg.target.subjects.add(id=URNS["subjectID"], value=f"u{i}")
            msg.target.resources.add(id=URNS["entity"], value=ORG)
            msg.target.resources.add(id=URNS["resourceID"], value=f"r{i}")
            msg.target.actions.add(id=URNS["actionID"], value=URNS["read"])
            msg.context.subject.value = json.dumps(
                {
                    "id": f"u{i}",
                    "role_associations": [{"role": role, "attributes": []}],
                    "hierarchical_scopes": [],
                }
            ).encode()
        resp = client.is_allowed_batch(batch_msg)
        decisions = [r.decision for r in resp.responses]
        assert decisions[0] == pb.Decision.Value("PERMIT")
        assert decisions[1] == pb.Decision.Value("INDETERMINATE")
    finally:
        client.close()
        server.stop()


def make_sharded_worker(model_devices, data_devices=None):
    parallel = {"model_devices": model_devices}
    if data_devices is not None:
        parallel["data_devices"] = data_devices
    return Worker().start(
        {
            "policies": {"type": "database"},
            "parallel": parallel,
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )


def test_model_devices_builds_rule_sharded_kernel():
    """Config-only toggle: `parallel:model_devices` routes serving through
    the rule-axis sharded kernel (parallel/rule_shard.py) on a 2-axis
    data x model mesh, decisions identical to single-device."""
    from access_control_srv_tpu.parallel.rule_shard import RuleShardedKernel

    worker = make_sharded_worker(model_devices=4, data_devices=2)
    try:
        assert worker.mesh is not None
        assert worker.mesh.shape == {"data": 2, "model": 4}
        assert isinstance(worker.evaluator._kernel, RuleShardedKernel)
        reqs = batch_requests(24)
        out = worker.evaluator.is_allowed_batch(reqs)
        oracle = [worker.engine.is_allowed(r).decision for r in reqs]
        assert [r.decision for r in out] == oracle
    finally:
        worker.stop()


def test_model_devices_defaults_data_axis_to_remaining():
    worker = make_sharded_worker(model_devices=2)
    try:
        assert worker.mesh.shape["model"] == 2
        assert worker.mesh.shape["data"] == len(jax.devices()) // 2
    finally:
        worker.stop()


def test_model_devices_survives_hot_mutation():
    """A CRUD-triggered recompile rebuilds the RULE-SHARDED kernel (fresh
    partitioning over the model axis) and the new rule's decisions flow
    through it."""
    from access_control_srv_tpu.parallel.rule_shard import RuleShardedKernel

    worker = make_sharded_worker(model_devices=4, data_devices=2)
    try:
        reqs = batch_requests(16)
        before = worker.evaluator.is_allowed_batch(reqs)
        assert before[1].decision == "INDETERMINATE"
        rule_service = worker.store.get_resource_service("rule")
        rule_service.create(
            [
                {
                    "id": "shard-hot-rule",
                    "name": "hot",
                    "effect": "PERMIT",
                    "target": {
                        "subjects": [
                            {"id": URNS["role"], "value": "ordinary-user"}
                        ],
                        "resources": [{"id": URNS["entity"], "value": ORG}],
                        "actions": [],
                    },
                }
            ],
            subject=None,
        )
        policy_service = worker.store.get_resource_service("policy")
        doc = dict(policy_service.read()["items"][0]["payload"])
        doc["rules"] = list(doc.get("rules") or []) + ["shard-hot-rule"]
        res = policy_service.update([doc], subject=None)
        assert res["operation_status"]["code"] == 200, res
        kernel = worker.evaluator._kernel
        assert isinstance(kernel, RuleShardedKernel)
        out = worker.evaluator.is_allowed_batch(reqs)
        oracle = [worker.engine.is_allowed(r).decision for r in reqs]
        assert [r.decision for r in out] == oracle
        assert out[1].decision == "PERMIT"
    finally:
        worker.stop()


def test_model_devices_all_rejected():
    with pytest.raises(ValueError, match="parallel:model_devices"):
        make_sharded_worker(model_devices="all")
