"""Shared helpers for the cluster-tier test suites (test_router.py,
test_cluster_chaos.py): wire-level request builders, CRUD-over-gRPC
helpers and convergence polling against live replica processes."""

import json
import os
import time

from access_control_srv_tpu.srv.gen import access_control_pb2 as pb

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"
READ = URNS["read"]
PO = ("urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
      "permit-overrides")
SEED_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data", "seed_data",
)


def seed_paths() -> dict:
    return {
        "policy_sets": os.path.join(SEED_DIR, "policy_sets.yaml"),
        "policies": os.path.join(SEED_DIR, "policies.yaml"),
        "rules": os.path.join(SEED_DIR, "rules.yaml"),
    }


def wire_request(role="superadministrator-r-id", resource_id="O1"):
    """pb.Request for ORG read with the given role (the
    tests/test_grpc_transport.py wire shape)."""
    msg = pb.Request()
    msg.target.subjects.add(id=URNS["role"], value=role)
    msg.target.subjects.add(id=URNS["subjectID"], value="root")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.resources.add(id=URNS["resourceID"], value=resource_id)
    msg.target.actions.add(id=URNS["actionID"], value=READ)
    msg.context.subject.value = json.dumps({
        "id": "root",
        "role_associations": [{"role": role, "attributes": []}],
        "hierarchical_scopes": [],
    }).encode()
    entry = msg.context.resources.add()
    entry.value = json.dumps(
        {"id": resource_id, "meta": {"owners": []}}
    ).encode()
    return msg


def reader_rule_doc(rid="r_cluster", role="reader-role", effect="PERMIT"):
    return {
        "id": rid,
        "name": rid,
        "target": {
            "subjects": [{"id": URNS["role"], "value": role}],
            "resources": [{"id": URNS["entity"], "value": ORG}],
            "actions": [{"id": URNS["actionID"], "value": READ}],
        },
        "effect": effect,
    }


def _fill_attr(msg, doc):
    msg.id = doc.get("id") or ""
    msg.value = str(doc.get("value") or "")
    for child in doc.get("attributes") or []:
        _fill_attr(msg.attributes.add(), child)


def rule_to_pb(doc: dict) -> pb.Rule:
    msg = pb.Rule()
    msg.id = doc["id"]
    msg.name = doc.get("name") or ""
    msg.effect = doc.get("effect") or ""
    target = doc.get("target") or {}
    for field in ("subjects", "resources", "actions"):
        for attr in target.get(field) or []:
            _fill_attr(getattr(msg.target, field).add(), attr)
    return msg


def crud_fn(channel, service: str, method: str, resp_cls):
    return channel.unary_unary(
        f"/acstpu.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )


def upsert_rule(channel, doc: dict) -> int:
    fn = crud_fn(channel, "RuleService", "Upsert", pb.MutationResponse)
    rl = pb.RuleList(items=[rule_to_pb(doc)])
    rl.subject.id = "root"
    return fn(rl).operation_status.code


def create_reader_policy_tree(channel, rid="r_cluster") -> None:
    """Rule + policy + policy set for the reader role, via the router's
    CRUD surface (so the frames land in the cluster journal)."""
    assert upsert_rule(channel, reader_rule_doc(rid)) == 200
    pol = pb.PolicyList()
    item = pol.items.add()
    item.id = f"p_{rid}"
    item.combining_algorithm = PO
    item.rules.append(rid)
    pol.subject.id = "root"
    assert crud_fn(channel, "PolicyService", "Upsert",
                   pb.MutationResponse)(pol).operation_status.code == 200
    pset = pb.PolicySetList()
    item = pset.items.add()
    item.id = f"ps_{rid}"
    item.combining_algorithm = PO
    item.policies.append(f"p_{rid}")
    pset.subject.id = "root"
    assert crud_fn(channel, "PolicySetService", "Upsert",
                   pb.MutationResponse)(pset).operation_status.code == 200


def command_over(channel, name: str, payload: dict | None = None) -> dict:
    fn = channel.unary_unary(
        "/acstpu.CommandInterface/Command",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.CommandResponse.FromString,
    )
    request = pb.CommandRequest(name=name)
    if payload is not None:
        request.payload = json.dumps(payload).encode()
    resp = fn(request)
    return json.loads(resp.payload or b"{}")


def program_identities(addrs, timeout_s=5.0) -> list[dict]:
    import grpc

    out = []
    for addr in addrs:
        channel = grpc.insecure_channel(addr)
        try:
            out.append(command_over(channel, "program_identity"))
        finally:
            channel.close()
    return out


def wait_converged(addrs, timeout_s=30.0, min_epoch=0) -> list[dict]:
    """Poll program_identity on every replica until all report one
    (epoch, fingerprint) pair with epoch >= min_epoch; returns the final
    identity list (asserting convergence)."""
    deadline = time.monotonic() + timeout_s
    ids: list[dict] = []
    while time.monotonic() < deadline:
        ids = program_identities(addrs)
        pairs = {
            (i.get("policy_epoch"), i.get("table_fingerprint"))
            for i in ids
        }
        if len(pairs) == 1:
            epoch, fingerprint = next(iter(pairs))
            if fingerprint is not None and (epoch or 0) >= min_epoch:
                return ids
        time.sleep(0.2)
    raise AssertionError(f"replicas did not converge: {ids}")
