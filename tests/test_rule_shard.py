"""Rule-axis (model-parallel) sharded kernel: differential vs the
single-device kernel and the scalar oracle on a 2D (data x model) virtual
CPU mesh."""

import numpy as np
import pytest
from jax.sharding import Mesh

from access_control_srv_tpu.core import AccessController, populate
from access_control_srv_tpu.ops import (
    DecisionKernel,
    compile_policies,
    encode_requests,
)
from access_control_srv_tpu.parallel.rule_shard import (
    RuleShardedKernel,
    partition_rules,
)

from .test_kernel_differential import DEC_CODE, grid_requests
from .utils import fixture, make_engine


def make_2d_mesh(data: int, model: int) -> Mesh:
    import jax

    devices = np.array(jax.devices()[: data * model]).reshape(data, model)
    return Mesh(devices, ("data", "model"))


@pytest.mark.parametrize("data,model", [(4, 2), (2, 4), (1, 8)])
@pytest.mark.parametrize(
    "fixture_name", ["role_scopes.yml", "props_multi_rules_entities.yml",
                     "conditions.yml"]
)
def test_rule_shard_differential(fixture_name, data, model):
    engine = make_engine(fixture_name)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    mesh = make_2d_mesh(data, model)
    sharded = RuleShardedKernel(compiled, mesh)
    kernel = DecisionKernel(compiled)

    requests = grid_requests(n=96, seed=53)
    batch = encode_requests(requests, compiled)
    d_ref, c_ref, s_ref = kernel.evaluate(batch)
    d_sh, c_sh, s_sh = sharded.evaluate(batch)

    eligible = batch.eligible
    assert np.array_equal(d_sh[eligible], d_ref[eligible])
    assert np.array_equal(c_sh[eligible], c_ref[eligible])
    assert np.array_equal(s_sh[eligible], s_ref[eligible])

    # spot-check directly against the oracle too
    for b in range(0, len(requests), 7):
        if not eligible[b]:
            continue
        expected = engine.is_allowed(requests[b])
        assert d_sh[b] == DEC_CODE[expected.decision], b


def test_rule_shard_multi_set_tree():
    engine = make_engine()
    for name in ["basic_policies.yml", "policy_targets.yml", "role_scopes.yml"]:
        populate(engine, fixture(name))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    mesh = make_2d_mesh(2, 4)
    sharded = RuleShardedKernel(compiled, mesh)
    kernel = DecisionKernel(compiled)
    batch = encode_requests(grid_requests(n=80, seed=99), compiled)
    d_ref, c_ref, s_ref = kernel.evaluate(batch)
    d_sh, c_sh, s_sh = sharded.evaluate(batch)
    eligible = batch.eligible
    assert np.array_equal(d_sh[eligible], d_ref[eligible])
    assert np.array_equal(s_sh[eligible], s_ref[eligible])


def test_partition_covers_all_rules():
    engine = make_engine("role_scopes.yml")
    compiled = compile_policies(engine.policy_sets, engine.urns)
    part = partition_rules(compiled, 4)
    # every valid rule appears exactly once across shards
    total = sum(
        int(part.arrays["rule_valid"][d].sum()) for d in range(4)
    )
    assert total == compiled.n_rules
    # chunk offsets tile the padded rule axis
    assert list(part.kr_offsets) == [i * part.kr_local for i in range(4)]
