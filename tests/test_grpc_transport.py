"""End-to-end gRPC transport tests: a real grpc server + channel, protobuf
wire messages, the full five-service surface."""

import json
import os

import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

from .utils import URNS

ORG = "urn:restorecommerce:acs:model:organization.Organization"
PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")


@pytest.fixture(scope="module")
def rig():
    worker = Worker().start(
        {
            "policies": {"type": "database"},
            "seed_data": {
                "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
                "policies": os.path.join(SEED, "policies.yaml"),
                "rules": os.path.join(SEED, "rules.yaml"),
            },
        }
    )
    server = GrpcServer(worker, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    yield worker, client
    client.close()
    server.stop()
    worker.stop()


def wire_request(role="superadministrator-r-id", action=None):
    action = action or URNS["read"]
    msg = pb.Request()
    msg.target.subjects.add(id=URNS["role"], value=role)
    msg.target.subjects.add(id=URNS["subjectID"], value="root")
    msg.target.resources.add(id=URNS["entity"], value=ORG)
    msg.target.resources.add(id=URNS["resourceID"], value="O1")
    msg.target.actions.add(id=URNS["actionID"], value=action)
    msg.context.subject.value = json.dumps(
        {
            "id": "root",
            "role_associations": [{"role": role, "attributes": []}],
            "hierarchical_scopes": [],
        }
    ).encode()
    entry = msg.context.resources.add()
    entry.value = json.dumps({"id": "O1", "meta": {"owners": []}}).encode()
    return msg


def test_serialize_batch_response_byte_identity():
    """The off-dispatch-thread batch serializer must produce byte-identical
    envelopes to protobuf's own BatchResponse serialization, across the
    chunking threshold (length-delimited field-1 frames ARE the
    envelope)."""
    from access_control_srv_tpu.srv.transport_grpc import (
        _SER_CHUNK,
        serialize_batch_response,
    )

    def row(i):
        return pb.Response(
            decision=[pb.PERMIT, pb.DENY, pb.INDETERMINATE][i % 3],
            evaluation_cacheable=bool(i % 2),
            operation_status=pb.OperationStatus(
                code=200 if i % 5 else 403, message=f"m{i}" * (i % 7)
            ),
        )

    for n in (0, 1, 3, _SER_CHUNK, _SER_CHUNK + 1, 2 * _SER_CHUNK + 17):
        responses = [row(i) for i in range(n)]
        expected = pb.BatchResponse(responses=responses).SerializeToString()
        assert serialize_batch_response(responses) == expected, n
        # and the bytes parse back into the same rows
        parsed = pb.BatchResponse.FromString(
            serialize_batch_response(responses)
        )
        assert list(parsed.responses) == responses


def test_is_allowed_over_wire(rig):
    _, client = rig
    response = client.is_allowed(wire_request())
    assert response.decision == pb.PERMIT
    assert response.operation_status.code == 200
    response = client.is_allowed(wire_request(role="nobody"))
    assert response.decision == pb.INDETERMINATE


def test_batch_over_wire(rig):
    _, client = rig
    batch = pb.BatchRequest(
        requests=[wire_request() for _ in range(8)]
        + [wire_request(role="nobody") for _ in range(8)]
    )
    response = client.is_allowed_batch(batch)
    decisions = [r.decision for r in response.responses]
    assert decisions[:8] == [pb.PERMIT] * 8
    assert decisions[8:] == [pb.INDETERMINATE] * 8


def test_what_is_allowed_over_wire(rig):
    _, client = rig
    rq = client.what_is_allowed(wire_request())
    assert rq.operation_status.code == 200
    assert len(rq.policy_sets) == 1
    assert rq.policy_sets[0].id == "global_policy_set"
    assert rq.policy_sets[0].policies[0].rules[0].id == "super_admin_rule"


def test_crud_over_wire(rig):
    worker, client = rig
    rule = pb.Rule(id="r_wire", effect="PERMIT")
    rule.target.subjects.add(id=URNS["role"], value="wire-role")
    result = client.crud("rule", "Create", pb.RuleList(items=[rule]))
    assert result.operation_status.code == 200

    policy = pb.Policy(id="p_wire", combining_algorithm=PO, rules=["r_wire"])
    client.crud("policy", "Create", pb.PolicyList(items=[policy]))
    pset = pb.PolicySet(id="ps_wire", combining_algorithm=PO,
                        policies=["p_wire"])
    client.crud("policy_set", "Create", pb.PolicySetList(items=[pset]))

    # hot-synced decision over the wire
    response = client.is_allowed(wire_request(role="wire-role"))
    assert response.decision == pb.PERMIT

    # read back
    read = client.crud("rule", "Read", pb.ReadRequest(ids=["r_wire"]),
                       pb.RuleListResponse)
    assert read.items[0].id == "r_wire"
    assert read.items[0].target.subjects[0].value == "wire-role"

    # delete flips the decision back
    client.crud("rule", "Delete", pb.DeleteRequest(ids=["r_wire"]))
    response = client.is_allowed(wire_request(role="wire-role"))
    assert response.decision == pb.INDETERMINATE


def test_command_and_health_over_wire(rig):
    _, client = rig
    assert client.health() == "SERVING"
    version = client.command("version")
    assert version["version"]


def test_what_is_allowed_batch_over_wire(rig):
    worker, client = rig
    batch = pb.BatchRequest()
    for role in ("superadministrator-r-id", "nobody"):
        batch.requests.add().CopyFrom(wire_request(role=role))
    resp = client._call("acstpu.AccessControlService", "WhatIsAllowedBatch",
                        batch, pb.BatchReverseQuery)
    assert len(resp.responses) == 2
    # per-row parity with the single-request endpoint
    for i, role in enumerate(("superadministrator-r-id", "nobody")):
        single = client.what_is_allowed(wire_request(role=role))
        assert resp.responses[i].SerializeToString() == \
            single.SerializeToString()


def test_meta_timestamps_over_wire(rig):
    worker, client = rig
    rule = pb.Rule(id="r_ts_wire", effect="PERMIT")
    client.crud("rule", "Create", pb.RuleList(items=[rule]))
    read = client.crud("rule", "Read", pb.ReadRequest(ids=["r_ts_wire"]),
                       pb.RuleListResponse)
    meta = read.items[0].meta
    assert meta.created > 0 and meta.modified >= meta.created
