"""Self-authorization integration grid (behavioral contract of the
reference's test/microservice_acs_enabled.spec.ts): the service authorizes
its own policy CRUD by evaluating against the default_policies fixture,
subjects are resolved from tokens through a mock identity service, and
hierarchical scopes arrive through the HR-scope rendezvous loopback
(request out on the auth topic, test responder emits the response back —
the reference's no-cluster multi-node test pattern, spec.ts:286-322).

Covers: runtime authorization toggle, create/update/upsert/delete with
valid and invalid subject scopes (exact 403 message text,
e.g. spec.ts:613-617), multi-owner items, invalid-owner DENY, and
multiple scoping instances assigned to the same role (spec.ts:879-1075).
"""

import threading

import pytest

from access_control_srv_tpu.srv import Worker

from .utils import URNS, fixture, marshall_yaml_policies

ORG = "urn:restorecommerce:acs:model:organization.Organization"
TEST_ENTITY = "urn:restorecommerce:acs:model:test.Test"
SUBJECT_ID_URN = "urn:oasis:names:tc:xacml:1.0:subject:subject-id"

HR_TREE = [
    {
        "id": "mainOrg",
        "role": "admin-r-id",
        "children": [
            {"id": "orgA",
             "children": [{"id": "orgB", "children": [{"id": "orgC"}]}]}
        ],
    }
]


def role_associations(role, instances=("mainOrg",)):
    return [
        {
            "role": role,
            "attributes": [
                {
                    "id": URNS["roleScopingEntity"],
                    "value": ORG,
                    "attributes": [
                        {"id": URNS["roleScopingInstance"], "value": inst}
                        for inst in instances
                    ],
                }
            ],
        }
    ]


def owners(*instances):
    return [
        {
            "id": URNS["ownerIndicatoryEntity"],
            "value": ORG,
            "attributes": [
                {"id": URNS["ownerInstance"], "value": inst}
                for inst in instances
            ],
        }
    ]


def make_rule(rule_id="test_rule_id", name="test rule for test entity",
              owner_instances=("orgC",)):
    return {
        "id": rule_id,
        "name": name,
        "description": "test rule",
        "target": {
            "subjects": [{"id": SUBJECT_ID_URN, "value": "test-r-id"}],
            "resources": [{"id": URNS["entity"], "value": TEST_ENTITY}],
        },
        "effect": "PERMIT",
        "meta": {"owners": owners(*owner_instances)},
    }


def denied_message(subject_id, resource, action, scope):
    """(reference: resourceManager 403 text, spec.ts:613-617)"""
    return (
        f"Access not allowed for request with subject:{subject_id}, "
        f"resource:{resource}, action:{action}, target_scope:{scope}; "
        f"the response was DENY"
    )


@pytest.fixture(scope="class")
def rig():
    w = Worker().start(
        {
            "policies": {"type": "database"},
            "authorization": {
                "enabled": False,
                "enforce": False,
                "hrReqTimeout": 2000,
            },
        }
    )
    # mock identity service (reference: grpc-mock-server findByToken,
    # spec.ts:106-223)
    w.identity_client.register(
        "admin_token",
        {
            "id": "admin_user_id",
            "tokens": [{"token": "admin_token"}],
            "role_associations": role_associations("admin-r-id"),
        },
    )
    w.identity_client.register(
        "user_token",
        {
            "id": "user_id",
            "tokens": [{"token": "user_token"}],
            "role_associations": role_associations("user-r-id"),
        },
    )

    # HR-scope rendezvous loopback responder (spec.ts:286-322)
    auth_topic = w.bus.topic("io.restorecommerce.authentication")

    def responder(event_name, message, ctx):
        if event_name != "hierarchicalScopesRequest":
            return
        token_date = message["token"]
        token = token_date.split(":")[0]
        subject_id = {"admin_token": "admin_user_id",
                      "user_token": "user_id"}.get(token)
        if subject_id is None:
            return

        def reply():
            auth_topic.emit(
                "hierarchicalScopesResponse",
                {
                    "token": token_date,
                    "subject_id": subject_id,
                    "hierarchical_scopes": HR_TREE,
                },
            )

        threading.Thread(target=reply, daemon=True).start()

    auth_topic.on(responder)
    yield w
    w.stop()


def admin_subject(scope=None):
    subject = {"id": "admin_user_id", "token": "admin_token"}
    if scope:
        subject["scope"] = scope
    return subject


def user_subject(scope=None):
    subject = {"id": "user_id", "token": "user_token"}
    if scope:
        subject["scope"] = scope
    return subject


class TestSelfAuthorizedCrudGrid:
    """Tests run in definition order against one worker, mirroring the
    reference suite's stateful progression."""

    def test_insert_defaults_acs_disabled(self, rig):
        policy_sets, policies, rules = marshall_yaml_policies(
            fixture("default_policies.yml")
        )
        ps_srv = rig.store.get_resource_service("policy_set")
        pol_srv = rig.store.get_resource_service("policy")
        rule_srv = rig.store.get_resource_service("rule")
        result = ps_srv.create(policy_sets, subject=admin_subject())
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert len(result["items"]) == len(policy_sets)
        result = pol_srv.create(policies, subject=admin_subject())
        assert result["operation_status"]["code"] == 200
        assert len(result["items"]) == len(policies)
        result = rule_srv.create(rules, subject=admin_subject())
        assert result["operation_status"]["code"] == 200
        assert len(result["items"]) == len(rules)
        assert "PS1" in rig.engine.policy_sets

    def test_create_rule_valid_scope(self, rig):
        # runtime toggle (reference: cfg.set + updateConfig, spec.ts:379-382)
        rig.command_interface.command(
            "config_update",
            {"authorization:enabled": True, "authorization:enforce": True},
        )
        result = rig.store.get_resource_service("rule").create(
            [make_rule()], subject=admin_subject(scope="orgC")
        )
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert len(result["items"]) == 1

    def test_create_rule_without_scope(self, rig):
        result = rig.store.get_resource_service("rule").create(
            [make_rule(rule_id="test_rule_id2")], subject=admin_subject()
        )
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert len(result["items"]) == 1

    def test_create_two_multi_owner_rules_and_delete(self, rig):
        rules = rig.store.get_resource_service("rule")
        items = [
            make_rule(rule_id="", name="1 test rule", owner_instances=("orgA",)),
            make_rule(rule_id="", name="2 test rule", owner_instances=("orgB",)),
        ]
        for item in items:
            del item["id"]
        result = rules.create(items, subject=admin_subject(scope="mainOrg"))
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert len(result["items"]) == 2
        ids = [entry["payload"]["id"] for entry in result["items"]]
        deleted = rules.delete(ids=ids, subject=admin_subject(scope="mainOrg"))
        assert deleted["operation_status"] == {"code": 200, "message": "success"}

    def test_deny_create_invalid_owner(self, rig):
        items = [
            make_rule(rule_id="", name="1 test rule", owner_instances=("orgA",)),
            # INVALID is not in the subject's HR tree
            make_rule(rule_id="", name="2 test rule",
                      owner_instances=("INVALID",)),
        ]
        for item in items:
            del item["id"]
        result = rig.store.get_resource_service("rule").create(
            items, subject=admin_subject(scope="orgA")
        )
        assert "items" not in result
        assert result["operation_status"]["code"] == 403
        assert result["operation_status"]["message"] == denied_message(
            "admin_user_id", "rule", "CREATE", "orgA"
        )

    def test_deny_create_user_role(self, rig):
        result = rig.store.get_resource_service("rule").create(
            [make_rule(rule_id="test_rule_id3")],
            subject=user_subject(scope="orgC"),
        )
        assert "items" not in result
        assert result["operation_status"]["code"] == 403
        assert result["operation_status"]["message"] == denied_message(
            "user_id", "rule", "CREATE", "orgC"
        )

    def test_update_valid_scope(self, rig):
        item = make_rule(name="modified test rule for test entity")
        result = rig.store.get_resource_service("rule").update(
            [item], subject=admin_subject(scope="orgC")
        )
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert result["items"][0]["payload"]["name"] == (
            "modified test rule for test entity"
        )

    def test_deny_update_user_role(self, rig):
        result = rig.store.get_resource_service("rule").update(
            [make_rule(name="new test rule")],
            subject=user_subject(scope="orgC"),
        )
        assert "items" not in result
        assert result["operation_status"]["code"] == 403
        assert result["operation_status"]["message"] == denied_message(
            "user_id", "rule", "MODIFY", "orgC"
        )

    def test_upsert_valid_scope(self, rig):
        item = make_rule(name="upserted test rule for test entity")
        result = rig.store.get_resource_service("rule").upsert(
            [item], subject=admin_subject(scope="orgC")
        )
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert result["items"][0]["payload"]["name"] == (
            "upserted test rule for test entity"
        )

    def test_deny_upsert_user_role(self, rig):
        result = rig.store.get_resource_service("rule").upsert(
            [make_rule(name="new test rule")],
            subject=user_subject(scope="orgC"),
        )
        assert "items" not in result
        assert result["operation_status"]["code"] == 403
        assert result["operation_status"]["message"] == denied_message(
            "user_id", "rule", "MODIFY", "orgC"
        )

    def test_deny_delete_user_role(self, rig):
        result = rig.store.get_resource_service("rule").delete(
            ids=["test_rule_id"], subject=user_subject(scope="orgC")
        )
        assert result["operation_status"]["code"] == 403
        assert result["operation_status"]["message"] == denied_message(
            "user_id", "rule", "DELETE", "orgC"
        )
        assert rig.store.collections["rule"].get("test_rule_id") is not None

    def test_delete_valid_scope(self, rig):
        result = rig.store.get_resource_service("rule").delete(
            ids=["test_rule_id"], subject=admin_subject(scope="orgC")
        )
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert rig.store.collections["rule"].get("test_rule_id") is None

    def test_multi_instance_role_scoping(self, rig):
        """Same role assigned two scoping instances; each scope can create
        rules owned by that scope (spec.ts:879-971)."""
        subject = {
            "id": "admin_user_id",
            "scope": "org1",
            "role_associations": role_associations(
                "admin-r-id", instances=("org1", "org2")
            ),
            "hierarchical_scopes": [
                {"id": "org1", "role": "admin-r-id", "children": []},
                {"id": "org2", "role": "admin-r-id", "children": []},
            ],
        }
        rules = rig.store.get_resource_service("rule")
        item = make_rule(rule_id="", name="1 test rule",
                         owner_instances=("org1",))
        del item["id"]
        result = rules.create([item], subject=subject)
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert result["items"][0]["payload"]["name"] == "1 test rule"

        subject["scope"] = "org2"
        item = make_rule(rule_id="", name="2 test rule",
                         owner_instances=("org2",))
        del item["id"]
        result = rules.create([item], subject=subject)
        assert result["operation_status"] == {"code": 200, "message": "success"}
        assert result["items"][0]["payload"]["name"] == "2 test rule"

    def test_multi_owner_multi_instance_without_scope(self, rig):
        """Items owned by several orgs, subject scoped to a subset, no
        explicit scope in the subject (spec.ts:973-1075)."""
        subject = {
            "id": "admin_user_id",
            "role_associations": role_associations(
                "admin-r-id", instances=("org1", "org2")
            ),
            "hierarchical_scopes": [
                {"id": "org1", "role": "admin-r-id", "children": []},
                {"id": "org2", "role": "admin-r-id", "children": []},
            ],
        }
        rules = rig.store.get_resource_service("rule")
        for name in ("1 test rule", "2 test rule"):
            item = make_rule(rule_id="", name=name,
                             owner_instances=("org1", "org2", "org3"))
            del item["id"]
            result = rules.create([item], subject=subject)
            assert result["operation_status"] == {
                "code": 200, "message": "success",
            }
            assert result["items"][0]["payload"]["name"] == name
