"""Signature-plane kernel differential: the prefiltered kernel's fast
path precomputes stage A's resource/action planes per resource signature
(ops/prefilter.py _planes_for) and folds only the subject side per row.
Decisions must be bit-identical to the scalar oracle and the dense kernel
on every eligible shape: exact + regex entities (foreign-namespace prefix
resets), multi-entity ordered runs, operations, conditions and aborts,
all three combining algorithms.

Eligibility (use_sig): the batch carries no ACL pairs / request
properties; anything else must fall back to the full per-row matcher with
identical results.  HR-bearing trees ride the signature path too: their
collection state / op hits are per-signature planes, the owner checks are
per-request vocab matmuls.
"""

import copy
import random

import numpy as np

from access_control_srv_tpu.core import AccessController
from access_control_srv_tpu.core.loader import load_policy_sets
from access_control_srv_tpu.ops import (
    DecisionKernel,
    PrefilteredKernel,
    compile_policies,
    encode_requests,
)

from .test_kernel_differential import (
    ACTIONS,
    DEC_CODE,
    ENTITIES,
    ROLES,
    SUBJECTS,
    _random_policy_tree,
)
from .test_fuzz_extended import FOREIGN
from .test_prefilter import force_active
from .utils import URNS, build_request

ORG = "urn:restorecommerce:acs:model:organization.Organization"


def _strip_scoping(doc):
    """Remove role-scoping attributes so the tree is HR-trivial
    (tree_needs_hr False -> sig path eligible)."""
    drop = {URNS["roleScopingEntity"], URNS["hierarchicalRoleScoping"]}
    for ps in doc["policy_sets"]:
        for node in [ps] + list(ps.get("policies") or []):
            for rule in [node] + list(node.get("rules") or []):
                tgt = rule.get("target")
                if tgt and tgt.get("subjects"):
                    tgt["subjects"] = [
                        a for a in tgt["subjects"] if a["id"] not in drop
                    ]
    return doc


def _sig_tree(rng):
    doc = _strip_scoping(_random_policy_tree(rng))
    # swap some entities to foreign namespaces: regex prefix resets
    for ps in doc["policy_sets"]:
        for pol in ps.get("policies") or []:
            for node in [pol] + list(pol.get("rules") or []):
                tgt = node.get("target") or {}
                for attr in tgt.get("resources") or []:
                    if attr["id"] == URNS["entity"] and rng.random() < 0.3:
                        attr["value"] = rng.choice(FOREIGN)
    return doc


def _sig_requests(rng, n):
    """Prop-free, ACL-free requests: single and multi-entity (ordered runs
    matter for the sticky state machines), operations, all actions."""
    out = []
    pool = ENTITIES + FOREIGN
    for i in range(n):
        action = rng.choice(ACTIONS)
        if action == URNS["execute"]:
            rtype = rng.choice(["mutation.runPipeline", "mutation.other"])
            rid = rtype
        elif rng.random() < 0.4:
            k = rng.randint(2, 3)
            rtype = rng.sample(pool, k)
            rid = [f"id-{j}" for j in range(k)]
        else:
            rtype = rng.choice(pool)
            rid = "id-0"
        out.append(
            build_request(
                subject_id=rng.choice(SUBJECTS),
                subject_role=rng.choice(ROLES + ["other-role"]),
                resource_type=rtype,
                resource_id=rid,
                action_type=action,
            )
        )
    return out


def _run_differential(engine, compiled, kern, requests):
    batch = encode_requests(requests, compiled)
    dec, cach, status = kern.evaluate(batch)
    n_checked = 0
    for b, req in enumerate(requests):
        if not batch.eligible[b] or status[b] != 200:
            continue
        expected = engine.is_allowed(copy.deepcopy(req))
        assert dec[b] == DEC_CODE[expected.decision], (
            b, dec[b], expected.decision
        )
        n_checked += 1
    return n_checked, batch


def test_sig_path_engages_and_matches_oracle():
    rng = random.Random(1234)
    total = 0
    trees_with_sig = 0
    for round_i in range(12):
        doc = _sig_tree(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        kern = force_active(PrefilteredKernel(compiled))
        if kern.needs_hr:
            continue
        trees_with_sig += 1
        requests = _sig_requests(rng, 64)
        n, batch = _run_differential(engine, compiled, kern, requests)
        total += n
        # prop/ACL-free batch on an HR-trivial tree MUST take the sig path
        assert kern._bits, "signature planes were never built"
        assert any(
            isinstance(k, tuple) and k and k[0] == "sig"
            for k in kern._runs
        ), "sig runner never compiled"
    assert trees_with_sig >= 8
    assert total > 300


def test_sig_path_matches_dense_kernel_exactly():
    rng = random.Random(77)
    for _ in range(4):
        doc = _sig_tree(rng)
        engine = AccessController()
        for ps in load_policy_sets(doc):
            engine.update_policy_set(ps)
        compiled = compile_policies(engine.policy_sets, engine.urns)
        if not compiled.supported:
            continue
        dense = DecisionKernel(compiled)
        kern = force_active(PrefilteredKernel(compiled))
        if kern.needs_hr:
            continue
        requests = _sig_requests(rng, 96)
        batch = encode_requests(requests, compiled)
        d1, c1, s1 = dense.evaluate(batch)
        d2, c2, s2 = kern.evaluate(batch)
        el = np.asarray(batch.eligible)
        assert (d1[el] == d2[el]).all()
        assert (c1[el] == c2[el]).all()
        assert (s1[el] == s2[el]).all()


def test_prop_rows_fall_back_with_identical_results():
    """A single prop-bearing request disables the sig path for the batch;
    decisions stay oracle-identical either way."""
    rng = random.Random(9)
    doc = _sig_tree(rng)
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    if not compiled.supported:
        return
    kern = force_active(PrefilteredKernel(compiled))
    requests = _sig_requests(rng, 16)
    requests.append(
        build_request(
            subject_id=SUBJECTS[0],
            subject_role=ROLES[0],
            resource_type=ENTITIES[0],
            resource_id="id-p",
            action_type=URNS["read"],
            resource_property=["urn:restorecommerce:acs:model:location.Location#name"],
        )
    )
    n_bits_before = len(kern._bits)
    n, batch = _run_differential(engine, compiled, kern, requests)
    assert bool(np.asarray(batch.arrays["r_has_props"]).any())
    # fallback: no new signature planes were built for this batch
    assert len(kern._bits) == n_bits_before


def test_hr_tree_uses_sig_path_and_matches_oracle():
    """HR-bearing trees now take the signature path too: the collection
    state and op hits are per-signature planes, the owner checks are the
    shared per-request vocab matmuls.  Decisions must equal the oracle
    across owner placements (direct, hierarchical, miss) and both the
    dense kernel."""
    import random as _random

    from .utils import fixture
    from access_control_srv_tpu.core import populate

    engine = AccessController()
    populate(engine, fixture("role_scopes.yml"))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kern = force_active(PrefilteredKernel(compiled))
    assert kern.needs_hr

    LOC = "urn:restorecommerce:acs:model:location.Location"
    rng = _random.Random(21)
    requests = []
    owners = ["Org1", "Org2", "Org3", "SuperOrg1", "otherOrg"]
    for i in range(48):
        requests.append(
            build_request(
                subject_id=f"user-{i % 16}",
                subject_role=["member", "manager", "guest"][i % 3],
                role_scoping_entity=ORG,
                role_scoping_instance=rng.choice(owners),
                resource_type=LOC if i % 2 else ORG,
                resource_id=f"L{i}",
                action_type=(
                    "urn:restorecommerce:acs:names:action:read"
                    if i % 3 else
                    "urn:restorecommerce:acs:names:action:modify"
                ),
                owner_indicatory_entity=ORG,
                owner_instance=rng.choice(owners),
            )
        )
    n, batch = _run_differential(engine, compiled, kern, requests)
    assert n > 30
    assert kern._bits, "HR sig path must engage"
    dense = DecisionKernel(compiled)
    d1, c1, s1 = dense.evaluate(batch)
    d2, c2, s2 = kern.evaluate(batch)
    el = np.asarray(batch.eligible)
    assert (d1[el] == d2[el]).all()
    assert (c1[el] == c2[el]).all()
    assert (s1[el] == s2[el]).all()


def test_conditions_and_aborts_through_sig_path():
    """Condition-bearing rules (true/false/abort) evaluate through the sig
    runner with exact codes."""
    from .utils import fixture
    from access_control_srv_tpu.core import populate

    engine = AccessController()
    populate(engine, fixture("conditions.yml"))
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported
    kern = force_active(PrefilteredKernel(compiled))
    assert not kern.needs_hr, "conditions fixture must stay HR-trivial"
    rng = random.Random(3)
    requests = _sig_requests(rng, 48)
    # guaranteed abort row: matches r_self_modify's target but its context
    # lacks `subject`, so the condition raises -> DENY + error code
    # (reference: accessController.ts:259-270)
    from access_control_srv_tpu.models import Attribute, Request, Target

    USER = "urn:restorecommerce:acs:model:user.User"
    requests.append(
        Request(
            target=Target(
                subjects=[Attribute(id=URNS["role"], value="member")],
                resources=[Attribute(id=URNS["entity"], value=USER)],
                actions=[
                    Attribute(id=URNS["actionID"], value=URNS["modify"])
                ],
            ),
            context={
                "resources": [{"id": "someone-else"}],
                "subject": {
                    "role_associations": [
                        {"role": "member", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                },
            },
        )
    )
    batch = encode_requests(requests, compiled)
    dec, cach, status = kern.evaluate(batch)
    assert kern._bits
    n_aborts = 0
    for b, req in enumerate(requests):
        if not batch.eligible[b]:
            continue
        expected = engine.is_allowed(copy.deepcopy(req))
        if status[b] != 200:
            assert expected.operation_status.code == status[b]
            assert dec[b] == DEC_CODE["DENY"]
            n_aborts += 1
        else:
            assert dec[b] == DEC_CODE[expected.decision]
    # the abort wiring must actually be exercised, or this test proves
    # nothing about it
    assert n_aborts > 0


def test_cardinality_guard_bounds_groups_per_dispatch():
    """Adversarial traffic where every request names a novel entity set:
    the guard splits the batch into segments of at most max_groups
    signatures, results stay oracle-identical, and the split + cache-miss
    counters are recorded."""
    from access_control_srv_tpu.srv.telemetry import Telemetry

    rng = random.Random(42)
    doc = _sig_tree(rng)
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    if not compiled.supported:
        return
    telemetry = Telemetry()
    kern = force_active(
        PrefilteredKernel(compiled, max_groups=4, telemetry=telemetry)
    )
    pool = ENTITIES + FOREIGN
    requests = []
    for i in range(40):
        # pairs drawn to maximize distinct signatures
        rtype = [pool[i % len(pool)], pool[(i * 7 + 1) % len(pool)]]
        requests.append(
            build_request(
                subject_id=SUBJECTS[i % len(SUBJECTS)],
                subject_role=ROLES[i % len(ROLES)],
                resource_type=rtype,
                resource_id=[f"id-{i}-0", f"id-{i}-1"],
                action_type=ACTIONS[i % len(ACTIONS)],
            )
        )
    n, batch = _run_differential(engine, compiled, kern, requests)
    assert n > 20
    assert telemetry.paths.get("prefilter-guard-splits") >= 1
    assert telemetry.paths.get("prefilter-sub-miss") > 0
    # every cached stack obeys the group cap
    for stacked in kern._stacks.values():
        for v in stacked.values():
            assert v.shape[0] <= 4


def test_guard_cache_hits_on_repeat_traffic():
    from access_control_srv_tpu.srv.telemetry import Telemetry

    rng = random.Random(5)
    doc = _sig_tree(rng)
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    if not compiled.supported:
        return
    telemetry = Telemetry()
    kern = force_active(PrefilteredKernel(compiled, telemetry=telemetry))
    requests = _sig_requests(rng, 32)
    from access_control_srv_tpu.ops import encode_requests as enc

    kern.evaluate(enc(requests, compiled))
    misses = telemetry.paths.get("prefilter-sub-miss")
    assert misses > 0
    kern.evaluate(enc(requests, compiled))
    # steady-state repeat traffic: all signature lookups hit
    assert telemetry.paths.get("prefilter-sub-miss") == misses
    assert telemetry.paths.get("prefilter-sub-hit") >= misses
    assert telemetry.paths.get("prefilter-stack-hit") >= 1
