"""Serving-shell integration tests: worker boot, CRUD round-trips with hot
tree sync, micro-batching, command interface, HR-scope rendezvous (loopback
responder pattern), self-authorized CRUD, cache invalidation
(coverage model: the reference's microservice + acs-enabled suites)."""

import os
import threading
import time

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.srv import Config, Worker

from .utils import URNS, build_request, fixture

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
READ = URNS["read"]
MODIFY = URNS["modify"]

SEED = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "seed_data")

PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"


def seed_cfg(**overrides):
    cfg = {
        "policies": {"type": "database"},
        "seed_data": {
            "policy_sets": os.path.join(SEED, "policy_sets.yaml"),
            "policies": os.path.join(SEED, "policies.yaml"),
            "rules": os.path.join(SEED, "rules.yaml"),
        },
    }
    cfg.update(overrides)
    return cfg


def admin_request(role="superadministrator-r-id", action=READ):
    return build_request(
        subject_id="root",
        subject_role=role,
        role_scoping_entity=ORG,
        role_scoping_instance="system",
        resource_type=ORG,
        resource_id="O1",
        action_type=action,
    )


@pytest.fixture()
def worker():
    w = Worker().start(seed_cfg())
    yield w
    w.stop()


class TestWorkerBoot:
    def test_seed_policies_loaded(self, worker):
        assert "global_policy_set" in worker.engine.policy_sets

    def test_super_admin_permit(self, worker):
        response = worker.service.is_allowed(admin_request())
        assert response.decision == Decision.PERMIT
        assert response.operation_status.code == 200

    def test_ordinary_user_indeterminate(self, worker):
        response = worker.service.is_allowed(admin_request(role="nobody"))
        assert response.decision == Decision.INDETERMINATE

    def test_health_and_version(self, worker):
        health = worker.command_interface.command("health_check")
        assert health["status"] == "SERVING"
        version = worker.command_interface.command("version")
        assert version["version"]


class TestCrudHotSync:
    def rule_doc(self, rid="r_reader", role="reader-role"):
        return {
            "id": rid,
            "name": rid,
            "target": {
                "subjects": [{"id": URNS["role"], "value": role}],
                "resources": [{"id": URNS["entity"], "value": ORG}],
                "actions": [{"id": URNS["actionID"], "value": READ}],
            },
            "effect": "PERMIT",
        }

    def test_create_updates_decisions(self, worker):
        reader_req = admin_request(role="reader-role")
        assert worker.service.is_allowed(reader_req).decision == \
            Decision.INDETERMINATE

        rules = worker.store.get_resource_service("rule")
        policies = worker.store.get_resource_service("policy")
        sets = worker.store.get_resource_service("policy_set")
        assert rules.create([self.rule_doc()])["operation_status"]["code"] == 200
        policies.create(
            [{"id": "p_readers", "combining_algorithm": PO, "rules": ["r_reader"]}]
        )
        sets.create(
            [{"id": "ps_readers", "combining_algorithm": PO,
              "policies": ["p_readers"]}]
        )
        # hot sync: in-memory tree and kernel both updated
        assert "ps_readers" in worker.engine.policy_sets
        assert worker.service.is_allowed(reader_req).decision == Decision.PERMIT

    def test_update_rule_flips_effect(self, worker):
        self.test_create_updates_decisions(worker)
        rules = worker.store.get_resource_service("rule")
        doc = self.rule_doc()
        doc["effect"] = "DENY"
        rules.update([doc])
        response = worker.service.is_allowed(admin_request(role="reader-role"))
        assert response.decision == Decision.DENY

    def test_delete_rule_restores_indeterminate(self, worker):
        self.test_create_updates_decisions(worker)
        worker.store.get_resource_service("rule").delete(ids=["r_reader"])
        response = worker.service.is_allowed(admin_request(role="reader-role"))
        # the policy now has a missing (None) child and no effects
        assert response.decision == Decision.INDETERMINATE

    def test_crud_events_emitted(self, worker):
        topic = worker.bus.topic("io.restorecommerce.rules.resource")
        before = topic.offset
        worker.store.get_resource_service("rule").create([self.rule_doc("r_evt")])
        events = topic.read(before)
        assert ("ruleCreated", ) == tuple(e for e, _ in events)[:1]


class TestMicroBatcher:
    def test_concurrent_submits(self, worker):
        futures = [
            worker.batcher.submit(admin_request())
            for _ in range(32)
        ] + [
            worker.batcher.submit(admin_request(role="nobody"))
            for _ in range(32)
        ]
        results = [f.result(timeout=30) for f in futures]
        assert all(r.decision == Decision.PERMIT for r in results[:32])
        assert all(r.decision == Decision.INDETERMINATE for r in results[32:])


class TestCommandInterface:
    def test_reset_then_restore(self, worker):
        assert worker.service.is_allowed(admin_request()).decision == \
            Decision.PERMIT
        worker.command_interface.command("reset")
        assert worker.service.is_allowed(admin_request()).decision == \
            Decision.INDETERMINATE
        # re-seed + restore
        worker.store.seed(
            *[__import__("yaml").safe_load(open(os.path.join(SEED, f)))
              for f in ("policy_sets.yaml", "policies.yaml", "rules.yaml")]
        )
        worker.command_interface.command("restore")
        assert worker.service.is_allowed(admin_request()).decision == \
            Decision.PERMIT

    def test_config_update(self, worker):
        worker.command_interface.command(
            "config_update", {"authorization:hrReqTimeout": 1234}
        )
        assert worker.cfg.get("authorization:hrReqTimeout") == 1234

    def test_command_via_topic(self, worker):
        worker.bus.topic("io.restorecommerce.command").emit(
            "command", {"name": "set_api_key", "payload": {"apiKey": "k1"}}
        )
        assert worker.command_interface.api_key == "k1"


class TestHRScopeRendezvous:
    def test_cached_scopes_resolve_without_rendezvous(self, worker):
        worker.identity_client.register(
            "tok-1",
            {
                "id": "ada",
                "tokens": [{"token": "tok-1", "interactive": True}],
                "role_associations": [
                    {"role": "superadministrator-r-id", "attributes": []}
                ],
            },
        )
        worker.subject_cache.set("cache:ada:hrScopes", [{"id": "Org1"}])
        request = admin_request()
        request.context["subject"] = {"token": "tok-1"}
        response = worker.service.is_allowed(request)
        assert response.decision == Decision.PERMIT
        assert request.context["subject"]["hierarchical_scopes"] == [
            {"id": "Org1"}
        ]

    def test_rendezvous_loopback(self, worker):
        """The suite-3 pattern: a test responder consumes
        hierarchicalScopesRequest and emits the response back."""
        worker.identity_client.register(
            "tok-2",
            {
                "id": "ben",
                "tokens": [{"token": "tok-2", "interactive": True}],
                "role_associations": [
                    {"role": "superadministrator-r-id", "attributes": []}
                ],
            },
        )
        auth_topic = worker.bus.topic("io.restorecommerce.authentication")

        def responder(event_name, message, ctx):
            if event_name != "hierarchicalScopesRequest":
                return
            token_date = message["token"]

            def reply():
                auth_topic.emit(
                    "hierarchicalScopesResponse",
                    {
                        "token": token_date,
                        "subject_id": "ben",
                        "interactive": True,
                        "hierarchical_scopes": [{"id": "OrgB"}],
                    },
                )

            threading.Thread(target=reply, daemon=True).start()

        auth_topic.on(responder)
        request = admin_request()
        request.context["subject"] = {"token": "tok-2"}
        response = worker.service.is_allowed(request)
        assert response.decision == Decision.PERMIT
        assert worker.subject_cache.get("cache:ben:hrScopes") == [{"id": "OrgB"}]

    def test_rendezvous_timeout(self):
        w = Worker().start(seed_cfg(authorization={"hrReqTimeout": 50}))
        try:
            w.identity_client.register(
                "tok-3",
                {
                    "id": "eve",
                    "tokens": [{"token": "tok-3", "interactive": True}],
                    "role_associations": [],
                },
            )
            request = admin_request(role="nobody")
            request.context["subject"] = {"token": "tok-3"}
            t0 = time.time()
            response = w.service.is_allowed(request)
            assert time.time() - t0 < 5
            assert response.decision == Decision.INDETERMINATE
        finally:
            w.stop()


class TestSelfAuthorizedCrud:
    def test_unauthorized_create_denied(self):
        w = Worker().start(seed_cfg(authorization={
            "enabled": True, "enforce": True, "hrReqTimeout": 50,
        }))
        try:
            rules = w.store.get_resource_service("rule")
            result = rules.create(
                [{"id": "r_x", "effect": "PERMIT"}],
                subject={"id": "mallory", "scope": "otherOrg"},
            )
            assert result["operation_status"]["code"] == 403
            assert w.store.collections["rule"].get("r_x") is None
        finally:
            w.stop()

    def test_authorized_create_permitted(self):
        w = Worker().start(seed_cfg(authorization={
            "enabled": True, "enforce": True, "hrReqTimeout": 50,
        }))
        try:
            rules = w.store.get_resource_service("rule")
            result = rules.create(
                [{"id": "r_y", "effect": "PERMIT"}],
                subject={
                    "id": "root",
                    "scope": "system",
                    "role_associations": [
                        {"role": "superadministrator-r-id", "attributes": []}
                    ],
                    "hierarchical_scopes": [],
                },
            )
            assert result["operation_status"]["code"] == 200
            assert w.store.collections["rule"].get("r_y") is not None
        finally:
            w.stop()


class TestCacheInvalidation:
    def test_user_deleted_evicts(self, worker):
        worker.subject_cache.set("cache:u1:hrScopes", [{"id": "X"}])
        worker.bus.topic("io.restorecommerce.users.resource").emit(
            "userDeleted", {"id": "u1"}
        )
        assert worker.subject_cache.get("cache:u1:hrScopes") is None

    def test_user_modified_evicts_on_change(self, worker):
        worker.subject_cache.set(
            "cache:u2:subject",
            {"role_associations": [{"role": "a", "attributes": []}]},
        )
        worker.subject_cache.set("cache:u2:hrScopes", [{"id": "X"}])
        worker.bus.topic("io.restorecommerce.users.resource").emit(
            "userModified",
            {"id": "u2", "role_associations": [{"role": "b", "attributes": []}]},
        )
        assert worker.subject_cache.get("cache:u2:hrScopes") is None

    def test_user_modified_keeps_on_no_change(self, worker):
        assocs = [{"role": "a", "attributes": []}]
        worker.subject_cache.set("cache:u3:subject", {"role_associations": assocs})
        worker.subject_cache.set("cache:u3:hrScopes", [{"id": "X"}])
        worker.bus.topic("io.restorecommerce.users.resource").emit(
            "userModified", {"id": "u3", "role_associations": assocs}
        )
        assert worker.subject_cache.get("cache:u3:hrScopes") == [{"id": "X"}]


class TestLocalPolicyMode:
    def test_local_yaml_load(self):
        w = Worker().start(
            {
                "policies": {
                    "type": "local",
                    "paths": [fixture("basic_policies.yml")],
                }
            }
        )
        try:
            request = build_request(
                subject_id="ada", subject_role="member",
                role_scoping_entity=ORG, role_scoping_instance="Org1",
                resource_type=ORG, resource_id="X",
                resource_property=ORG + "#name", action_type=READ,
            )
            assert w.service.is_allowed(request).decision == Decision.PERMIT
        finally:
            w.stop()


class TestAdapterContextQuery:
    def test_graphql_context_query_drives_condition(self):
        import json

        def transport(url, body, headers):
            return json.dumps(
                {
                    "data": {
                        "getAllAddresses": {
                            "details": [{"payload": {"country_id": "DE"}}],
                            "operation_status": {"code": 200, "message": "ok"},
                        }
                    }
                }
            ).encode()

        w = Worker().start(
            {
                "policies": {"type": "local", "paths": []},
                "adapter": {
                    "graphql": {"url": "http://example/graphql",
                                "transport": transport}
                },
            }
        )
        try:
            from access_control_srv_tpu.core.loader import load_policy_sets

            doc = {
                "policy_sets": [{
                    "id": "ps_cq", "combining_algorithm": PO,
                    "policies": [{
                        "id": "p_cq", "combining_algorithm": PO,
                        "rules": [{
                            "id": "r_cq", "effect": "PERMIT",
                            "target": {
                                "subjects": [{"id": URNS["role"],
                                              "value": "member"}],
                            },
                            "context_query": {
                                "query": "query { getAllAddresses { ... } }",
                                "filters": [],
                            },
                            "condition": (
                                "any(r.country_id == 'DE' "
                                "for r in context._queryResult)"
                            ),
                        }],
                    }],
                }]
            }
            for ps in load_policy_sets(doc):
                w.engine.update_policy_set(ps)
            w.evaluator.refresh()
            request = build_request(
                subject_id="ada", subject_role="member",
                role_scoping_entity=ORG, role_scoping_instance="Org1",
                resource_type=ORG, resource_id="X", action_type=READ,
            )
            assert w.service.is_allowed(request).decision == Decision.PERMIT
        finally:
            w.stop()


    def test_context_query_rule_keeps_safe_candidate_rows_on_device(self):
        """VERDICT r2 item 6 / r5 item 4: one adapter-backed context-query
        rule must not push the whole batch to the oracle.  Non-candidate
        rows keep exact pre-pass results, and candidate rows whose walk
        provably never observes the reference's context merge get the
        query PREFETCHED host-side and stay on device too
        (ops/encode._prefetch_context_queries)."""
        import json

        def transport(url, body, headers):
            return json.dumps(
                {
                    "data": {
                        "getAllAddresses": {
                            "details": [{"payload": {"country_id": "DE"}}],
                            "operation_status": {"code": 200, "message": "ok"},
                        }
                    }
                }
            ).encode()

        w = Worker().start(
            {
                "policies": {"type": "local", "paths": []},
                "adapter": {
                    "graphql": {"url": "http://example/graphql",
                                "transport": transport}
                },
            }
        )
        try:
            from access_control_srv_tpu.core.loader import load_policy_sets
            from access_control_srv_tpu.ops import encode_requests

            doc = {
                "policy_sets": [{
                    "id": "ps_mix", "combining_algorithm": PO,
                    "policies": [{
                        "id": "p_mix", "combining_algorithm": PO,
                        "rules": [
                            {
                                "id": "r_cq", "effect": "PERMIT",
                                "target": {
                                    "resources": [{"id": URNS["entity"],
                                                   "value": ORG}],
                                },
                                "context_query": {
                                    "query": "query { getAllAddresses }",
                                    "filters": [],
                                },
                                "condition": (
                                    "any(r.country_id == 'DE' "
                                    "for r in context._queryResult)"
                                ),
                            },
                            {
                                "id": "r_plain", "effect": "PERMIT",
                                "target": {
                                    "resources": [{"id": URNS["entity"],
                                                   "value": USER}],
                                },
                            },
                        ],
                    }],
                }]
            }
            for ps in load_policy_sets(doc):
                w.engine.update_policy_set(ps)
            w.evaluator.refresh()

            def req(entity):
                return build_request(
                    subject_id="ada", subject_role="member",
                    resource_type=entity, resource_id="X", action_type=READ,
                )

            batch = encode_requests(
                [req(ORG), req(USER)], w.evaluator._compiled,
                w.engine.resource_adapter,
            )
            # cq-rule candidate row: the query is prefetched host-side and
            # the row rides the kernel (the merge provably stays invisible
            # — no later candidate rule reads context on this signature)
            assert batch.eligible[0]
            assert batch.eligible[1]      # plain row stays on device
            assert not batch.ineligible_reasons

            responses = w.evaluator.is_allowed_batch([req(ORG), req(USER)])
            assert responses[0].decision == Decision.PERMIT  # via prefetch
            assert responses[1].decision == Decision.PERMIT  # via kernel
        finally:
            w.stop()


class TestConcurrentMutationServing:
    """Policy mutation must never disturb in-flight serving: the tree swap
    is atomic, so every concurrent decision is either old-tree or new-tree
    valid, never an error or a transient of a half-built tree."""

    def test_serving_during_hot_mutation(self):
        w = Worker().start(seed_cfg())
        try:
            request = admin_request()  # super-admin PERMIT under every tree
            errors: list = []
            stop = threading.Event()

            def serve():
                while not stop.is_set():
                    resp = w.service.is_allowed(admin_request())
                    if resp.decision != Decision.PERMIT:
                        errors.append(resp)
                        return

            threads = [threading.Thread(target=serve) for _ in range(4)]
            for t in threads:
                t.start()
            rules = w.store.get_resource_service("rule")
            for i in range(10):
                rules.create([{
                    "id": f"r_noise_{i}",
                    "target": {
                        "subjects": [{"id": URNS["role"], "value": f"x{i}"}],
                    },
                    "effect": "DENY",
                }])
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[0]
        finally:
            w.stop()


def test_profile_command_captures_trace(tmp_path):
    """The profile command (SURVEY section 5 tracing substitute) starts and
    stops a JAX device trace at runtime and writes trace artifacts."""
    w = Worker().start(seed_cfg())
    try:
        trace_dir = str(tmp_path / "trace")
        out = w.command_interface.command(
            "profile", {"action": "start", "dir": trace_dir}
        )
        assert out == {"status": "tracing", "dir": trace_dir}
        # some device work while tracing
        w.service.is_allowed(admin_request())
        out = w.command_interface.command("profile", {"action": "stop"})
        assert out["status"] == "stopped" and out["dir"] == trace_dir
        files = [p for p in __import__("pathlib").Path(trace_dir).rglob("*")
                 if p.is_file()]
        assert files  # trace artifacts landed
        bad = w.command_interface.command("profile", {"action": "bogus"})
        assert "error" in bad
    finally:
        w.stop()


def test_abort_rows_recover_exact_message_without_oracle_rerun():
    """Condition-error rows on the batch path return the reference's exact
    operation_status.message from the pre-pass cache instead of
    re-evaluating on the oracle (round-2 weak #6)."""
    from access_control_srv_tpu.core.loader import load_policy_sets

    w = Worker().start({"policies": {"type": "local", "paths": []}})
    try:
        doc = {
            "policy_sets": [{
                "id": "ps_err", "combining_algorithm": PO,
                "policies": [{
                    "id": "p_err", "combining_algorithm": PO,
                    "rules": [{
                        "id": "r_err", "effect": "PERMIT",
                        "target": {
                            "resources": [{"id": URNS["entity"],
                                           "value": ORG}],
                        },
                        # missing attribute raises at evaluation time
                        "condition": "context.subject.nonexistent_field == 1",
                    }],
                }],
            }]
        }
        for ps in load_policy_sets(doc):
            w.engine.update_policy_set(ps)
        w.evaluator.refresh()

        request = build_request(
            subject_id="ada", subject_role="member",
            resource_type=ORG, resource_id="X", action_type=READ,
        )
        expected = w.engine.is_allowed(request)
        assert expected.operation_status.code != 200

        calls = []
        original = w.engine.is_allowed
        w.engine.is_allowed = lambda r: (calls.append(r) or original(r))
        try:
            responses = w.evaluator.is_allowed_batch([request])
        finally:
            w.engine.is_allowed = original
        assert responses[0].decision == expected.decision
        assert responses[0].operation_status.code == \
            expected.operation_status.code
        assert responses[0].operation_status.message == \
            expected.operation_status.message
        assert not calls  # no oracle re-run for the abort row
    finally:
        w.stop()


def test_meta_timestamps_stamped_and_preserved():
    """meta.created is set at CREATE and preserved across MODIFY;
    meta.modified updates on every mutation (reference: resource-base
    fieldHandlers timeStampFields, cfg/config.json:324-331)."""
    w = Worker().start({"policies": {"type": "database"}})
    try:
        rules = w.store.get_resource_service("rule")
        rules.create([{"id": "r_ts", "name": "ts", "effect": "PERMIT"}])
        doc = rules.read({"ids": ["r_ts"]})["items"][0]["payload"]
        created = doc["meta"]["created"]
        first_modified = doc["meta"]["modified"]
        assert created and first_modified
        time.sleep(0.01)
        rules.update([{"id": "r_ts", "name": "ts2", "effect": "PERMIT"}])
        doc = rules.read({"ids": ["r_ts"]})["items"][0]["payload"]
        assert doc["meta"]["created"] == created  # preserved
        assert doc["meta"]["modified"] > first_modified
        # a client-supplied meta.created on MODIFY must not overwrite the
        # server-stamped creation time (resource-base timeStampFields are
        # server-owned)
        rules.update([{"id": "r_ts", "name": "ts3", "effect": "PERMIT",
                       "meta": {"created": 1.0}}])
        doc = rules.read({"ids": ["r_ts"]})["items"][0]["payload"]
        assert doc["meta"]["created"] == created
    finally:
        w.stop()
