"""Observability-layer tests (srv/tracing.py + the telemetry/transport/
batcher/evaluator integration): the byte-identical differential with the
config absent, span-tree completeness at 1.0 sampling, trace-id
propagation + echo over gRPC, the sampled decision-audit log with
masking, the Prometheus /metrics endpoint, rate-limited hot-path logging,
and the tracing-overhead bound (slow-marked)."""

import json
import logging
import time
import urllib.request

import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.models.model import (
    Attribute,
    Request,
    Target,
)
from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.telemetry import SampledLogger, Telemetry
from access_control_srv_tpu.srv.tracing import (
    TRACE_ID_METADATA_KEY,
    DecisionAuditLog,
    Observability,
    Span,
    StageTracer,
)

from .test_srv import admin_request, seed_cfg
from .utils import URNS, build_request

ORG = "urn:restorecommerce:acs:model:organization.Organization"


def obs_cfg(sample_rate=1.0, audit_path=None, audit_rate=1.0,
            metrics_http=False, **overrides):
    cfg = seed_cfg(**overrides)
    cfg["observability"] = {
        "enabled": True,
        "tracing": {"enabled": True, "sample_rate": sample_rate},
        "audit_log": {"path": audit_path, "sample_rate": audit_rate},
        "metrics_http": {"enabled": metrics_http, "port": 0},
    }
    return cfg


def distinct_request(i: int) -> Request:
    """Distinct resource ids so the decision cache cannot absorb the
    batch (stage coverage needs rows that actually evaluate)."""
    return build_request(
        subject_id="root",
        subject_role="superadministrator-r-id",
        role_scoping_entity=ORG,
        role_scoping_instance="system",
        resource_type=ORG,
        resource_id=f"O-{i}",
        action_type=URNS["read"],
    )


# ------------------------------------------------------------ differential


class TestObservabilityDifferential:
    """With the observability config absent the worker must serve
    BYTE-identical responses to an observability-enabled run —
    observability watches the pipeline, it never changes a decision
    (the PR-5 admission differential pattern)."""

    def _responses(self, enabled):
        from access_control_srv_tpu.srv.transport_grpc import (
            response_to_pb,
            reverse_query_to_pb,
        )

        cfg = obs_cfg() if enabled else seed_cfg()
        worker = Worker().start(cfg)
        try:
            single = [
                response_to_pb(
                    worker.service.is_allowed(r)
                ).SerializeToString()
                for r in (admin_request(), admin_request(role="nobody"),
                          admin_request())
            ]
            batch = [
                response_to_pb(r).SerializeToString()
                for r in worker.service.is_allowed_batch(
                    [distinct_request(i) for i in range(12)]
                )
            ]
            reverse = reverse_query_to_pb(
                worker.service.what_is_allowed(admin_request())
            ).SerializeToString()
        finally:
            worker.stop()
        return single, batch, reverse

    def test_enabled_decisions_byte_identical_to_absent(self):
        assert self._responses(True) == self._responses(False)

    def test_absent_config_builds_no_hub(self):
        worker = Worker().start(seed_cfg())
        try:
            assert worker.obs is None
            response = worker.service.is_allowed(admin_request())
            assert response.decision == Decision.PERMIT
            # no span machinery touched the snapshot
            assert "stages" not in worker.telemetry.snapshot()
            out = worker.command_interface.command("traces", {})
            assert "error" in out
        finally:
            worker.stop()


# ------------------------------------------------------- span completeness


class TestSpanCompleteness:
    def test_single_request_span_tree_via_batcher(self):
        """1.0 sampling through the micro-batcher: the span carries the
        queue-wait and evaluation stages and its stage durations sum to
        <= the request wall clock."""
        worker = Worker().start(obs_cfg())
        try:
            worker.service.is_allowed(admin_request())
            traces = worker.command_interface.command("traces", {})["traces"]
            assert traces, "1.0 sampling produced no trace"
            trace = traces[-1]
            stages = {s["stage"] for s in trace["stages"]}
            assert "queue.wait" in stages
            # single requests take the oracle (or warm-cache) path
            assert stages & {"oracle", "cache.lookup"}
            total_ms = sum(s["ms"] for s in trace["stages"])
            assert total_ms <= trace["wall_ms"] + 1e-6
            assert trace["decision"] == Decision.PERMIT
        finally:
            worker.stop()

    def test_batch_stages_fan_out_to_histograms(self):
        """A kernel-sized batch populates the batch-level stage
        histograms (encode/device/decode) and every stage count is
        consistent with one batch having run."""
        cfg = obs_cfg()
        cfg["decision_cache"] = {"enabled": False}
        worker = Worker().start(cfg)
        try:
            worker.service.is_allowed_batch(
                [distinct_request(i) for i in range(16)]
            )
            stages = worker.telemetry.snapshot().get("stages", {})
            for stage in ("encode", "device", "decode"):
                assert stage in stages, (stage, sorted(stages))
                assert stages[stage]["count"] >= 1
        finally:
            worker.stop()

    def test_grpc_end_to_end_trace_and_echo(self):
        """Wire-level: x-acs-trace-id metadata forces sampling, the id
        echoes on the trailing metadata, and the retained span tree
        covers transport parse through serialize."""
        import grpc

        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
        from access_control_srv_tpu.srv.transport_grpc import (
            GrpcServer,
            request_to_pb,
        )

        worker = Worker().start(obs_cfg(sample_rate=0.0))
        server = GrpcServer(worker, "127.0.0.1:0").start()
        channel = grpc.insecure_channel(server.addr)
        try:
            fn = channel.unary_unary(
                "/acstpu.AccessControlService/IsAllowed",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.Response.FromString,
            )
            msg = request_to_pb(admin_request())
            response, call = fn.with_call(
                msg, metadata=((TRACE_ID_METADATA_KEY, "trace-e2e-1"),)
            )
            assert response.decision == pb.PERMIT
            trailing = dict(call.trailing_metadata() or ())
            assert trailing.get(TRACE_ID_METADATA_KEY) == "trace-e2e-1"
            traces = worker.command_interface.command("traces", {})["traces"]
            ours = [t for t in traces if t["trace_id"] == "trace-e2e-1"]
            assert ours, traces
            stages = {s["stage"] for s in ours[-1]["stages"]}
            assert "transport.parse" in stages
            assert "serialize" in stages
            assert "queue.wait" in stages
            total_ms = sum(s["ms"] for s in ours[-1]["stages"])
            assert total_ms <= ours[-1]["wall_ms"] + 1e-6
        finally:
            channel.close()
            server.stop()
            worker.stop()

    def test_grpc_batch_rpc_span(self):
        """IsAllowedBatch gets one RPC-level span; batch stages fan into
        it exactly once and serialize closes it."""
        import grpc

        from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
        from access_control_srv_tpu.srv.transport_grpc import (
            GrpcServer,
            request_to_pb,
        )

        cfg = obs_cfg(sample_rate=0.0)
        cfg["decision_cache"] = {"enabled": False}
        worker = Worker().start(cfg)
        server = GrpcServer(worker, "127.0.0.1:0").start()
        channel = grpc.insecure_channel(server.addr)
        try:
            fn = channel.unary_unary(
                "/acstpu.AccessControlService/IsAllowedBatch",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.BatchResponse.FromString,
            )
            batch = pb.BatchRequest(
                requests=[request_to_pb(distinct_request(i))
                          for i in range(16)]
            )
            response, call = fn.with_call(
                batch, metadata=((TRACE_ID_METADATA_KEY, "trace-batch-1"),)
            )
            assert len(response.responses) == 16
            trailing = dict(call.trailing_metadata() or ())
            assert trailing.get(TRACE_ID_METADATA_KEY) == "trace-batch-1"
            traces = worker.command_interface.command("traces", {})["traces"]
            ours = [t for t in traces if t["trace_id"] == "trace-batch-1"]
            assert ours
            names = [s["stage"] for s in ours[-1]["stages"]]
            assert names.count("serialize") == 1
            assert "transport.parse" in names
            # device evaluation reached through either the native wire
            # path or the pb batch path — both record the device stage
            assert "device" in names, names
            total_ms = sum(s["ms"] for s in ours[-1]["stages"])
            assert total_ms <= ours[-1]["wall_ms"] + 1e-6
        finally:
            channel.close()
            server.stop()
            worker.stop()

    def test_sampling_rate_zero_keeps_histograms_only(self):
        worker = Worker().start(obs_cfg(sample_rate=0.0))
        try:
            worker.service.is_allowed(admin_request())
            assert worker.command_interface.command(
                "traces", {}
            )["traces"] == []
            assert worker.telemetry.snapshot().get("stages")
        finally:
            worker.stop()


# ------------------------------------------------------------- audit log


class TestDecisionAuditLog:
    def test_audit_records_decision_with_masking(self, tmp_path):
        sink = tmp_path / "audit.jsonl"
        worker = Worker().start(obs_cfg(audit_path=str(sink)))
        try:
            request = admin_request()
            request.target.subjects.append(
                Attribute(id="token", value="sup3rsecret")
            )
            worker.service.is_allowed(request)
        finally:
            worker.stop()
        lines = [json.loads(line) for line in sink.read_text().splitlines()]
        assert lines, "audit sink empty at 1.0 sampling"
        audit = lines[-1]["audit"]
        assert audit["decision"] == Decision.PERMIT
        assert audit["code"] == 200
        assert audit["rule_id"] == "super_admin_rule"
        assert audit["path"] in ("oracle", "cache-hit", "kernel")
        assert audit["subject"] == {"id": "root"}
        token_attrs = [a for a in audit["subjects"] if a["id"] == "token"]
        assert token_attrs and token_attrs[0]["value"] == "***"
        assert "sup3rsecret" not in sink.read_text()

    def test_lattice_snapshot_masks_like_the_audit_log(self, tmp_path):
        """The exported permission-matrix schema (docs/AUDIT.md) obeys
        the SAME secret-field rule as the decision audit log above: a
        lattice axis bound to a secret-named attribute URN exports
        ``***``, cell lines are index-only, and the raw value never
        appears anywhere in the snapshot file."""
        from access_control_srv_tpu.ops.lattice import (
            LatticeSpec,
            SnapshotWriter,
            mask_value,
        )
        from access_control_srv_tpu.srv.telemetry import (
            _LOWERED_MASK_FIELDS,
        )

        # the two layers share one rule set, not two drifting copies
        for field in _LOWERED_MASK_FIELDS:
            assert mask_value(f"urn:acs:names:{field}", "sup3rsecret") \
                == "***"
        assert mask_value("urn:acs:names:role", "admin") == "admin"

        spec = LatticeSpec(
            subjects=(("sup3rsecret", "admin"),),
            resources=(("res0", "urn:restorecommerce:acs:model:a.A"),),
            actions=("urn:restorecommerce:acs:names:action:read",),
            subject_id_urn="urn:restorecommerce:acs:names:apiKey",
        )
        path = tmp_path / "snap.jsonl"
        writer = SnapshotWriter(str(path), spec)
        writer.close()
        text = path.read_text()
        assert "sup3rsecret" not in text
        header = json.loads(text.splitlines()[0])
        assert header["axes"]["subjects"][0]["id"] == "***"

    def test_audit_sampling_zero_emits_nothing(self, tmp_path):
        sink = tmp_path / "audit.jsonl"
        worker = Worker().start(
            obs_cfg(audit_path=str(sink), audit_rate=0.0)
        )
        try:
            for _ in range(20):
                worker.service.is_allowed(admin_request())
        finally:
            worker.stop()
        assert sink.read_text().strip() == ""

    def test_batch_rows_audited(self, tmp_path):
        sink = tmp_path / "audit.jsonl"
        worker = Worker().start(obs_cfg(audit_path=str(sink)))
        try:
            worker.service.is_allowed_batch(
                [distinct_request(i) for i in range(8)]
            )
        finally:
            worker.stop()
        lines = [json.loads(line) for line in sink.read_text().splitlines()]
        assert len(lines) >= 8

    def test_direct_audit_log_close_idempotent(self, tmp_path):
        sink = tmp_path / "a.jsonl"
        audit = DecisionAuditLog(str(sink), sample_rate=1.0)
        request = Request(target=Target(), context={"resources": []})
        from access_control_srv_tpu.models.model import Response

        audit.record(request, Response(decision=Decision.DENY))
        audit.close()
        audit.close()
        assert json.loads(sink.read_text())["audit"]["decision"] == "DENY"


# ------------------------------------------------------ metrics endpoint


class TestMetricsEndpoint:
    def test_http_metrics_serves_prometheus_text(self):
        worker = Worker().start(obs_cfg(metrics_http=True))
        try:
            worker.service.is_allowed(admin_request())
            port = worker.obs.exporter.port
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
                content_type = resp.headers["Content-Type"]
            assert "version=0.0.4" in content_type
            assert 'acs_decisions_total{decision="PERMIT"}' in body
            assert "acs_is_allowed_latency_seconds_bucket" in body
            assert "acs_stage_duration_seconds_bucket" in body
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/other", timeout=5
                )
        finally:
            worker.stop()

    def test_metrics_command_prometheus_format(self):
        worker = Worker().start(obs_cfg())
        try:
            worker.service.is_allowed(admin_request())
            out = worker.command_interface.command(
                "metrics", {"format": "prometheus"}
            )
            assert "version=0.0.4" in out["content_type"]
            assert 'acs_decisions_total{decision="PERMIT"} 1' in out["body"]
            assert 'acs_stage_duration_seconds_bucket{stage=' in out["body"]
        finally:
            worker.stop()


# ------------------------------------------------------- unit-level spans


class TestStageTracerUnit:
    def test_unsampled_requests_allocate_no_span(self):
        tracer = StageTracer(sample_rate=0.0)
        assert tracer.start_span() is None

    def test_explicit_trace_id_forces_sampling(self):
        tracer = StageTracer(sample_rate=0.0)
        span = tracer.start_span("given-id")
        assert isinstance(span, Span)
        assert span.trace_id == "given-id"

    def test_fan_out_dedups_shared_span(self):
        tracer = StageTracer(sample_rate=1.0)
        span = tracer.start_span("x")
        reqs = [Request(target=Target()) for _ in range(4)]
        for request in reqs:
            request._span = span
        tracer.fan_out(reqs, "encode", 0.001)
        assert [s for s, _ in span.stages] == ["encode"]

    def test_ring_buffer_bounded(self):
        tracer = StageTracer(sample_rate=1.0, max_traces=4)
        for i in range(10):
            tracer.finish(tracer.start_span(f"t{i}"))
        traces = tracer.traces()
        assert len(traces) == 4
        assert traces[-1]["trace_id"] == "t9"


# ------------------------------------------------- rate-limited logging


class TestSampledLogger:
    class ListHandler(logging.Handler):
        def __init__(self):
            super().__init__()
            self.records = []

        def emit(self, record):
            self.records.append(record.getMessage())

    def _logger(self, name):
        logger = logging.getLogger(name)
        logger.setLevel(logging.INFO)
        logger.propagate = False
        handler = self.ListHandler()
        logger.handlers = [handler]
        return logger, handler

    def test_10k_suppressed_warnings_emit_at_most_n_plus_1(self):
        """The satellite regression bar: 10k hot-path warnings in one
        interval emit <= N records; the interval roll adds exactly one
        summary line carrying the suppressed count."""
        logger, handler = self._logger("test-sampled-10k")
        clock = {"t": 0.0}
        slog = SampledLogger(logger, max_per_interval=5, interval_s=10.0,
                             time_fn=lambda: clock["t"])
        for i in range(10_000):
            slog.warning("token-unresolved", "row %d failed", i)
        assert len(handler.records) == 5
        assert slog.suppressed("token-unresolved") == 9_995
        # the window rolls: ONE summary line, then the next record flows
        clock["t"] = 11.0
        slog.warning("token-unresolved", "row again")
        assert len(handler.records) == 5 + 2  # summary + the new record
        assert "suppressed 9995" in handler.records[5]

    def test_keys_are_independent(self):
        logger, handler = self._logger("test-sampled-keys")
        slog = SampledLogger(logger, max_per_interval=1, interval_s=10.0)
        slog.warning("a", "a1")
        slog.warning("b", "b1")
        slog.warning("a", "a2")  # suppressed
        assert handler.records == ["a1", "b1"]

    def test_none_logger_is_noop(self):
        slog = SampledLogger(None)
        slog.warning("k", "msg")  # must not raise


# --------------------------------------------------------- overhead bound


@pytest.mark.slow
class TestTracingOverhead:
    def test_overhead_under_5_percent_on_serve_microbench(self):
        """Serve-latency microbench with tracing at 1.0 sampling vs
        disabled: median single-request latency through the full worker
        path must not regress more than 5% (satellite bar)."""

        def median_latency(cfg):
            worker = Worker().start(cfg)
            try:
                request = admin_request()
                for _ in range(100):
                    worker.service.is_allowed(request)
                samples = []
                for _ in range(600):
                    t0 = time.perf_counter()
                    worker.service.is_allowed(request)
                    samples.append(time.perf_counter() - t0)
            finally:
                worker.stop()
            samples.sort()
            return samples[len(samples) // 2]

        base = min(median_latency(seed_cfg()) for _ in range(3))
        traced = min(median_latency(obs_cfg()) for _ in range(3))
        assert traced <= base * 1.05, (traced, base)
