"""Tensorized verifyACL (kernel stage B2) vs the scalar oracle.

The ACL check is the quirkiest part of the reference
(reference: src/core/verifyACL.ts:11-251): early all-clear on the first
targeted resource without ACL metadata, malformed-ACL failure, the
create path's sequential role scan with a validated-instance list and a
valid flag CARRIED ACROSS scoping entities, the user.User exemption, and
a role->org flatten with per-node role override that differs from the HR
matcher's flatten.  Every case here runs the same request through the
oracle and the kernel and asserts bit-identical decisions.
"""

import random

import numpy as np
import pytest

from access_control_srv_tpu.models import Decision
from access_control_srv_tpu.ops import (
    DecisionKernel,
    compile_policies,
    encode_requests,
)

from .test_kernel_differential import DEC_CODE
from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
BUCKET = "urn:restorecommerce:acs:model:bucket.Bucket"


def rig(fixture="acl_policies.yml"):
    engine = make_engine(fixture)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported, compiled.unsupported_reason
    return engine, compiled, DecisionKernel(compiled)


def assert_differential(engine, compiled, kernel, requests, min_eligible=None):
    batch = encode_requests(requests, compiled)
    n_eligible = int(batch.eligible.sum())
    if min_eligible is not None:
        assert n_eligible >= min_eligible, (n_eligible, len(requests))
    decision, cacheable, status = kernel.evaluate(batch)
    checked = 0
    for b, request in enumerate(requests):
        if not batch.eligible[b]:
            continue
        expected = engine.is_allowed(request)
        assert decision[b] == DEC_CODE[expected.decision], (
            b, decision[b], expected.decision
        )
        assert status[b] == expected.operation_status.code, (
            b, status[b], expected.operation_status.code
        )
        checked += 1
    return checked


def test_acl_requests_are_kernel_eligible():
    """The core deliverable: meta.acls no longer forces oracle fallback."""
    engine, compiled, kernel = rig()
    request = build_request(
        subject_id="Alice", subject_role="Admin",
        role_scoping_entity=ORG, role_scoping_instance="Org1",
        resource_type=BUCKET, resource_id="test",
        action_type=URNS["create"],
        owner_indicatory_entity=ORG, owner_instance="Org1",
        acl_indicatory_entity=ORG, acl_instances=["Org1"],
    )
    batch = encode_requests([request], compiled)
    assert batch.eligible[0]
    assert_differential(engine, compiled, kernel, [request], min_eligible=1)


@pytest.mark.parametrize("action", ["create", "read", "modify", "delete",
                                    "execute"])
def test_actions_with_acl_meta(action):
    """All action kinds against in-scope and out-of-scope ACL instances;
    non-CRUD actions with ACL metadata fail verifyACL (:250)."""
    engine, compiled, kernel = rig()
    requests = []
    for instances in (["Org1"], ["Org3"], ["otherOrg"], ["Org1", "otherOrg"],
                      ["Alice"], ["SuperOrg1", "Org2"]):
        requests.append(build_request(
            subject_id="Alice", subject_role="Admin",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=BUCKET, resource_id="test",
            action_type=URNS[action],
            owner_indicatory_entity=ORG, owner_instance="Org1",
            acl_indicatory_entity=ORG, acl_instances=instances,
        ))
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))


def test_user_entity_acls():
    """user.User scoping entities: create-path exemption (:150-153) and
    the rmd subject-id membership check (:190-193)."""
    engine, compiled, kernel = rig()
    requests = []
    for action in ("create", "read", "modify", "delete"):
        for instances in (["Alice"], ["Bob"], ["Alice", "Bob"]):
            requests.append(build_request(
                subject_id="Alice", subject_role="Admin",
                role_scoping_entity=ORG, role_scoping_instance="Org1",
                resource_type=BUCKET, resource_id="test",
                action_type=URNS[action],
                owner_indicatory_entity=ORG, owner_instance="Org1",
                acl_indicatory_entity=USER, acl_instances=instances,
            ))
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))


def test_mixed_org_and_user_acl_entities():
    """Two scoping entities on one resource: the valid flag carries across
    entities in the create path (:146-175)."""
    engine, compiled, kernel = rig()
    requests = []
    for action in ("create", "read"):
        for orgs, users in ((["Org1"], ["Alice"]), (["otherOrg"], ["Alice"]),
                            (["Org2"], ["Bob"]), (["otherOrg"], ["Bob"])):
            requests.append(build_request(
                subject_id="Alice", subject_role="Admin",
                role_scoping_entity=ORG, role_scoping_instance="Org1",
                resource_type=BUCKET, resource_id="test",
                action_type=URNS[action],
                owner_indicatory_entity=ORG, owner_instance="Org1",
                multiple_acl_indicatory_entity=[ORG, USER],
                org_instances=orgs, subject_instances=users,
            ))
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))


def _with_acls(request, acls):
    """Overwrite the context resources' acls list in place."""
    for res in request.context["resources"]:
        res["meta"]["acls"] = acls
    return request


def test_malformed_acls_fail_closed():
    """Wrong attribute ids / missing instances make verifyACL return False
    (:72-82); the kernel must agree through the short=2 encoding."""
    engine, compiled, kernel = rig()
    base = dict(
        subject_id="Alice", subject_role="Admin",
        role_scoping_entity=ORG, role_scoping_instance="Org1",
        resource_type=BUCKET, resource_id="test",
        action_type=URNS["create"],
        owner_indicatory_entity=ORG, owner_instance="Org1",
    )
    malformed = [
        # wrong top-level id
        [{"id": "urn:wrong", "value": ORG,
          "attributes": [{"id": URNS["aclInstance"], "value": "Org1"}]}],
        # empty attributes
        [{"id": URNS["aclIndicatoryEntity"], "value": ORG, "attributes": []}],
        # wrong nested id
        [{"id": URNS["aclIndicatoryEntity"], "value": ORG,
          "attributes": [{"id": "urn:wrong", "value": "Org1"}]}],
    ]
    requests = [
        _with_acls(build_request(**base), acls) for acls in malformed
    ]
    checked = assert_differential(engine, compiled, kernel, requests,
                                  min_eligible=len(requests))
    assert checked == len(requests)
    # malformed ACLs make the PERMIT rule unmatched -> not PERMIT
    for request in requests:
        assert engine.is_allowed(request).decision != Decision.PERMIT


def test_first_resource_without_acl_short_circuits():
    """The FIRST targeted resource without ACL metadata passes the whole
    check (:56-59), even if a later resource carries a malformed ACL."""
    engine, compiled, kernel = rig()
    good_acl = [{"id": URNS["aclIndicatoryEntity"], "value": ORG,
                 "attributes": [{"id": URNS["aclInstance"], "value": "Org1"}]}]
    bad_acl = [{"id": "urn:wrong", "value": ORG, "attributes": []}]

    def two_resource_request(first_acls, second_acls):
        request = build_request(
            subject_id="Alice", subject_role="Admin",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=[BUCKET, BUCKET], resource_id=["r1", "r2"],
            action_type=URNS["read"],
            owner_indicatory_entity=ORG, owner_instance=["Org1", "Org1"],
        )
        ctx = request.context["resources"]
        assert ctx[0]["id"] == "r1" and ctx[1]["id"] == "r2"
        ctx[0]["meta"]["acls"] = first_acls
        ctx[1]["meta"]["acls"] = second_acls
        return request

    requests = [
        two_resource_request([], bad_acl),        # no-acl first -> pass
        two_resource_request(bad_acl, []),        # malformed first -> fail
        two_resource_request(good_acl, bad_acl),  # good then malformed
        two_resource_request(bad_acl, good_acl),
    ]
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))
    assert engine.is_allowed(requests[0]).decision == Decision.PERMIT
    assert engine.is_allowed(requests[1]).decision != Decision.PERMIT


def test_per_node_role_override_tree():
    """verifyACL's flatten honors per-node role overrides (:119-129) —
    unlike the HR matcher's top-level-role flatten; the create path must
    see orgs under the overriding role key."""
    engine, compiled, kernel = rig()
    tree = [{
        "id": "SuperOrg1", "role": "OtherRole",
        "children": [
            # this subtree's nodes belong to Admin in verifyACL's map
            {"id": "Org1", "role": "Admin",
             "children": [{"id": "Org2"}]},
            {"id": "OrgX"},  # stays under OtherRole
        ],
    }]
    requests = []
    for instances in (["Org2"], ["OrgX"], ["SuperOrg1"], ["Org1", "Org2"]):
        requests.append(build_request(
            subject_id="Alice", subject_role="Admin",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=BUCKET, resource_id="test",
            action_type=URNS["create"],
            owner_indicatory_entity=ORG, owner_instance="Org1",
            acl_indicatory_entity=ORG, acl_instances=instances,
            hierarchical_scopes=tree,
        ))
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))
    # Org2 inherits Admin via the Org1 override -> in eligible org scopes
    assert engine.is_allowed(requests[0]).decision == Decision.PERMIT
    # OrgX belongs to OtherRole (not a rule role) -> create fails
    assert engine.is_allowed(requests[1]).decision != Decision.PERMIT


def test_duplicate_and_repeated_instances():
    """Duplicate ACL instances exercise the validated-instance list
    semantics of the create scan (:164-171)."""
    engine, compiled, kernel = rig()
    requests = []
    for instances in (["Org1", "Org1"], ["Org1", "otherOrg", "Org1"],
                      ["otherOrg", "otherOrg"]):
        requests.append(build_request(
            subject_id="Alice", subject_role="Admin",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=BUCKET, resource_id="test",
            action_type=URNS["create"],
            owner_indicatory_entity=ORG, owner_instance="Org1",
            acl_indicatory_entity=ORG, acl_instances=instances,
        ))
    assert_differential(engine, compiled, kernel, requests,
                        min_eligible=len(requests))


def test_skip_acl_rule_passes_malformed_acls():
    """A rule subject carrying skipACL passes immediately (:21-24), even
    against a malformed ACL that would otherwise fail."""
    from access_control_srv_tpu.core import AccessController
    from access_control_srv_tpu.core.loader import load_policy_sets

    PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
    doc = {"policy_sets": [{
        "id": "ps", "combining_algorithm": PO,
        "policies": [{
            "id": "p", "combining_algorithm": PO,
            "rules": [{
                "id": "r_skip",
                "target": {
                    "subjects": [
                        {"id": URNS["role"], "value": "Admin"},
                        {"id": URNS["skipACL"], "value": "true"},
                    ],
                    "resources": [{"id": URNS["entity"], "value": BUCKET}],
                    "actions": [{"id": URNS["actionID"],
                                 "value": URNS["create"]}],
                },
                "effect": "PERMIT",
            }],
        }],
    }]}
    engine = AccessController()
    for ps in load_policy_sets(doc):
        engine.update_policy_set(ps)
    compiled = compile_policies(engine.policy_sets, engine.urns)
    assert compiled.supported, compiled.unsupported_reason
    kernel = DecisionKernel(compiled)

    request = _with_acls(
        build_request(
            subject_id="Alice", subject_role="Admin",
            role_scoping_entity=ORG, role_scoping_instance="Org1",
            resource_type=BUCKET, resource_id="test",
            action_type=URNS["create"],
        ),
        [{"id": "urn:wrong", "value": ORG, "attributes": []}],
    )
    assert engine.is_allowed(request).decision == Decision.PERMIT
    assert_differential(engine, compiled, kernel, [request], min_eligible=1)


def test_randomized_acl_differential():
    """Randomized ACL-heavy mix: entities, instance sets, per-node role
    override trees, all action kinds; kernel == oracle on every eligible
    row (and the mix must stay mostly eligible)."""
    engine, compiled, kernel = rig()
    rng = random.Random(17)
    OWNERS = ["SuperOrg1", "Org1", "Org2", "Org3", "otherOrg", "OrgX"]
    SUBJECTS = ["Alice", "Bob"]

    def random_tree():
        if rng.random() < 0.5:
            return None  # build_request default chain
        def node(d, idx):
            out = {"id": rng.choice(OWNERS) + (f"-{idx}" if rng.random() < 0.3
                                               else "")}
            if rng.random() < 0.4:
                out["role"] = rng.choice(["Admin", "SimpleUser", "Other"])
            if d < 3 and rng.random() < 0.6:
                out["children"] = [node(d + 1, i) for i in
                                   range(rng.randint(1, 2))]
            return out
        top = node(0, 0)
        top.setdefault("role", rng.choice(["Admin", "SimpleUser"]))
        return [top]

    requests = []
    for i in range(400):
        kw = dict(
            subject_id=rng.choice(SUBJECTS),
            subject_role=rng.choice(["Admin", "SimpleUser"]),
            role_scoping_entity=ORG,
            role_scoping_instance=rng.choice(OWNERS),
            resource_type=BUCKET, resource_id=f"res-{i % 7}",
            action_type=URNS[rng.choice(
                ["create", "read", "modify", "delete", "execute"])],
            owner_indicatory_entity=ORG,
            owner_instance=rng.choice(OWNERS),
            hierarchical_scopes=random_tree(),
        )
        mode = rng.random()
        if mode < 0.5:
            kw.update(
                acl_indicatory_entity=rng.choice([ORG, USER]),
                acl_instances=rng.sample(OWNERS + SUBJECTS,
                                         rng.randint(1, 4)),
            )
        elif mode < 0.7:
            kw.update(
                multiple_acl_indicatory_entity=[ORG, USER],
                org_instances=rng.sample(OWNERS, rng.randint(1, 2)),
                subject_instances=rng.sample(SUBJECTS, rng.randint(1, 2)),
            )
        requests.append(build_request(**kw))
    checked = assert_differential(engine, compiled, kernel, requests,
                                  min_eligible=int(0.9 * len(requests)))
    assert checked >= 360


def test_wire_acl_differential():
    """ACL rows through the NATIVE wire encoder: same arrays, same
    eligibility, same kernel decisions as the Python encoder."""
    from access_control_srv_tpu import native

    if not native.available():
        pytest.skip(f"native encoder unavailable: {native.build_error()}")
    from .test_native_encoder import wire_roundtrip

    engine, compiled, kernel = rig()
    rng = random.Random(23)
    OWNERS = ["SuperOrg1", "Org1", "Org2", "Org3", "otherOrg"]
    requests = []
    for i in range(80):
        requests.append(build_request(
            subject_id=rng.choice(["Alice", "Bob"]),
            subject_role=rng.choice(["Admin", "SimpleUser"]),
            role_scoping_entity=ORG,
            role_scoping_instance=rng.choice(OWNERS),
            resource_type=BUCKET, resource_id=f"res-{i % 5}",
            action_type=URNS[rng.choice(
                ["create", "read", "modify", "delete"])],
            owner_indicatory_entity=ORG, owner_instance=rng.choice(OWNERS),
            acl_indicatory_entity=rng.choice([ORG, USER]),
            acl_instances=rng.sample(OWNERS + ["Alice", "Bob"],
                                     rng.randint(1, 3)),
        ))
    enc = native.NativeBatchEncoder(compiled)
    messages, twins = wire_roundtrip(requests)
    nb = enc.encode_wire(messages)
    pb_batch = encode_requests(twins, compiled)
    assert np.array_equal(nb.eligible, pb_batch.eligible)
    assert nb.eligible.all()
    for name in ("r_acl_short", "r_acl_ent", "r_acl_inst", "r_acl_hr",
                 "r_hr_roles", "r_subject_id"):
        assert np.array_equal(nb.arrays[name], pb_batch.arrays[name]), name
    decision, _, status = kernel.evaluate(nb)
    for b, twin in enumerate(twins):
        expected = engine.is_allowed(twin)
        assert decision[b] == DEC_CODE[expected.decision], b
        assert status[b] == expected.operation_status.code, b
