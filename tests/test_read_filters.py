"""Resource read filter DSL (VERDICT r2 missing #4): the resource-base
FilterOperation surface (eq/neq/in/lt/lte/gt/gte/isEmpty/iLike, and/or
groups) on store reads, in-process and over the gRPC wire (reference:
resourceManager.ts:61-68 makeFilter + resource-base-interface)."""

import json

import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer


@pytest.fixture(scope="module")
def rig():
    w = Worker().start({"policies": {"type": "database"}})
    rules = w.store.get_resource_service("rule")
    rules.create([
        {"id": "r1", "name": "alpha rule", "effect": "PERMIT",
         "description": "one"},
        {"id": "r2", "name": "beta rule", "effect": "DENY",
         "description": "two"},
        {"id": "r3", "name": "ALPHA special", "effect": "PERMIT",
         "description": "three"},
        {"id": "r4", "name": "gamma", "effect": "PERMIT",
         "description": ""},
    ])
    server = GrpcServer(w, "127.0.0.1:0").start()
    client = GrpcClient(server.addr)
    yield w, client
    client.close()
    server.stop()
    w.stop()


def ids(result):
    return sorted(item["payload"]["id"] for item in result["items"])


def test_filter_operations(rig):
    worker, _ = rig
    rules = worker.store.get_resource_service("rule")

    def read(groups):
        return rules.read({"filters": groups})

    assert ids(read([{"filters": [
        {"field": "effect", "operation": "eq", "value": "PERMIT"}
    ]}])) == ["r1", "r3", "r4"]

    assert ids(read([{"filters": [
        {"field": "effect", "operation": "neq", "value": "PERMIT"}
    ]}])) == ["r2"]

    # the reference's makeFilter shape: id in [...] (JSON value)
    assert ids(read([{"filters": [
        {"field": "id", "operation": "in", "value": json.dumps(["r1", "r2"])}
    ]}])) == ["r1", "r2"]

    assert ids(read([{"filters": [
        {"field": "name", "operation": "iLike", "value": "alpha%"}
    ]}])) == ["r1", "r3"]

    assert ids(read([{"filters": [
        {"field": "description", "operation": "isEmpty"}
    ]}])) == ["r4"]

    # or-group + AND across groups
    assert ids(read([
        {"operator": "or", "filters": [
            {"field": "id", "operation": "eq", "value": "r1"},
            {"field": "id", "operation": "eq", "value": "r2"},
        ]},
        {"filters": [
            {"field": "effect", "operation": "eq", "value": "PERMIT"},
        ]},
    ])) == ["r1"]

    bad = read([{"filters": [
        {"field": "id", "operation": "regex", "value": "x"}
    ]}])
    assert bad["operation_status"]["code"] == 400


def test_filters_over_wire(rig):
    _, client = rig
    req = pb.ReadRequest()
    group = req.filters.add(operator="or")
    group.filters.add(field="id", operation="eq", value="r1")
    group.filters.add(field="name", operation="iLike", value="%special")
    resp = client.crud("rule", "Read", req, pb.RuleListResponse)
    assert sorted(i.id for i in resp.items) == ["r1", "r3"]
    assert resp.operation_status.code == 200


def test_eq_matches_json_looking_strings_and_bad_operator(rig):
    worker, _ = rig
    rules = worker.store.get_resource_service("rule")
    rules.create([{"id": "r5", "name": "2024", "effect": "PERMIT",
                   "description": "year"}])
    result = rules.read({"filters": [{"filters": [
        {"field": "name", "operation": "eq", "value": "2024"}
    ]}]})
    assert ids(result) == ["r5"]  # "2024" must not coerce away from the string
    bad = rules.read({"filters": [{"operator": "XOR", "filters": [
        {"field": "id", "operation": "eq", "value": "r5"}
    ]}]})
    assert bad["operation_status"]["code"] == 400
