"""Golden decision tests over no-target policies + combining algorithms
(scalar oracle; decision matrix mirrors the reference engine semantics,
src/core/accessController.ts:88-324)."""

import pytest

from access_control_srv_tpu.models import Decision

from .utils import URNS, build_request, make_engine

ORG = "urn:restorecommerce:acs:model:organization.Organization"
USER = "urn:restorecommerce:acs:model:user.User"
ADDR = "urn:restorecommerce:acs:model:address.Address"
READ = URNS["read"]
MODIFY = URNS["modify"]


@pytest.fixture(scope="module")
def engine():
    return make_engine("basic_policies.yml")


def check(engine, expected, **kwargs):
    defaults = dict(
        subject_role="member",
        role_scoping_entity=ORG,
        role_scoping_instance="Org1",
        resource_property=ORG + "#name",
    )
    defaults.update(kwargs)
    response = engine.is_allowed(build_request(**defaults))
    assert response.decision == expected
    assert response.operation_status.code == 200
    return response


def test_permit_subject_rule(engine):
    check(engine, Decision.PERMIT, subject_id="ada", resource_type=ORG,
          resource_id="Ada Inc", action_type=READ)


def test_deny_subject_rule(engine):
    check(engine, Decision.DENY, subject_id="ben", resource_type=ORG,
          resource_id="Ben Inc", action_type=READ)


def test_deny_modify_rule(engine):
    check(engine, Decision.DENY, subject_id="ada", resource_type=ORG,
          resource_id="Ada Inc", action_type=MODIFY)


def test_indeterminate_unmatched_action(engine):
    check(engine, Decision.INDETERMINATE, subject_id="ben", resource_type=ORG,
          resource_id="Ben Inc", action_type=MODIFY)


def test_indeterminate_unknown_subject(engine):
    check(engine, Decision.INDETERMINATE, subject_id="zoe", resource_type=ORG,
          resource_id="Zoe Inc", action_type=MODIFY)


def test_indeterminate_unknown_entity(engine):
    check(
        engine,
        Decision.INDETERMINATE,
        subject_id="ada",
        resource_type="urn:restorecommerce:acs:model:widget.Widget",
        resource_property="urn:restorecommerce:acs:model:widget.Widget#prop",
        resource_id="W1",
        action_type=READ,
    )


def test_permit_overrides(engine):
    check(engine, Decision.PERMIT, subject_id="gil", resource_type=ORG,
          resource_id="Gil GmbH", action_type=READ)


def test_deny_overrides(engine):
    check(engine, Decision.DENY, subject_id="dee", resource_type=USER,
          resource_property=USER + "#password", resource_id="dee", action_type=READ)


def test_first_applicable_deny(engine):
    check(engine, Decision.DENY, subject_id="eva", resource_type=ADDR,
          resource_property=ADDR + "#street", resource_id="Main St", action_type=READ)


def test_first_applicable_permit(engine):
    # the deny rule targets read; a modify only collects the blanket permit
    check(engine, Decision.PERMIT, subject_id="eva", resource_type=ADDR,
          resource_property=ADDR + "#street", resource_id="Main St",
          action_type=MODIFY)


def test_no_target_denies():
    from access_control_srv_tpu.models import Request

    engine = make_engine("basic_policies.yml")
    response = engine.is_allowed(Request(target=None, context={}))
    assert response.decision == Decision.DENY
    assert response.operation_status.code == 400
