"""Multi-tenant serving (ISSUE 14 tentpole): size-class packing onto
shared compiled programs, tenant-scoped epochs/caches/journal frames,
per-tenant admission quotas, bounded-cardinality tenant telemetry, and
the differential bar — with no tenant id anywhere, the worker's decision
stream is byte-identical to a build without the tenancy registry."""

import threading
import time

import pytest

from access_control_srv_tpu.models import (
    Attribute,
    Decision,
    Request,
    Response,
    Target,
    Urns,
)
from access_control_srv_tpu.models.model import OperationStatus
from access_control_srv_tpu.ops.delta import Capacities
from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.admission import (
    INTERACTIVE,
    AdmissionController,
    tenant_from_metadata,
    valid_tenant_id,
)
from access_control_srv_tpu.srv.decision_cache import (
    DecisionCache,
    key_tenant,
    request_fingerprint,
)
from access_control_srv_tpu.srv.tenancy import (
    SIZE_CLASSES,
    TenantRegistry,
    class_caps,
    class_for_live,
    unknown_tenant_response,
)

from .test_srv import admin_request, seed_cfg

URNS = Urns()
PO = ("urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:"
      "permit-overrides")
USERS_TOPIC = "io.restorecommerce.users.resource"


def t_entity(k):
    return f"urn:restorecommerce:acs:model:tthing{k}.TThing{k}"


def t_rule(rid, k, effect="PERMIT"):
    return {"id": rid, "target": {
        "subjects": [{"id": URNS["role"], "value": f"role-{k % 3}"}],
        "resources": [{"id": URNS["entity"], "value": t_entity(k % 4)}],
        "actions": [{"id": URNS["actionID"], "value": URNS["read"]}]},
        "effect": effect, "evaluation_cacheable": True}


def t_request(k):
    role = f"role-{k % 3}"
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value=role),
                      Attribute(id=URNS["subjectID"], value=f"u{k}")],
            resources=[Attribute(id=URNS["entity"], value=t_entity(k % 4))],
            actions=[Attribute(id=URNS["actionID"], value=URNS["read"])],
        ),
        context={"resources": [], "subject": {
            "id": f"u{k}",
            "role_associations": [{"role": role, "attributes": []}],
            "hierarchical_scopes": [],
        }},
    )


def onboard(registry, tid, n_rules=2, emit=False, effect="PERMIT"):
    for j in range(n_rules):
        registry.apply(tid, "rule", "upsert",
                       t_rule(f"r{j}", j, effect=effect), emit=emit)
    registry.apply(tid, "policy", "upsert",
                   {"id": "p0", "combining_algorithm": PO,
                    "rules": [f"r{j}" for j in range(n_rules)]}, emit=emit)
    registry.apply(tid, "policy_set", "upsert",
                   {"id": "ps0", "combining_algorithm": PO,
                    "policies": ["p0"]}, emit=emit)


def permit_response(message="success"):
    return Response(
        decision=Decision.PERMIT,
        obligations=[],
        evaluation_cacheable=True,
        operation_status=OperationStatus(code=200, message=message),
    )


# --------------------------------------------------- transport metadata


class FakeGrpcContext:
    def __init__(self, metadata):
        self._metadata = metadata

    def invocation_metadata(self):
        return self._metadata


class TestTenantMetadata:
    def test_valid_id_shapes(self):
        for tid in ("acme", "acme-corp", "t.1_x", "A" * 64):
            assert valid_tenant_id(tid) == tid

    def test_invalid_id_shapes_treated_as_absent(self):
        for bad in ("", " ", "a b", "a/b", "a\x1eb", "Ä", "A" * 65,
                    "x\nY"):
            assert valid_tenant_id(bad) is None

    def test_metadata_extraction_case_insensitive(self):
        ctx = FakeGrpcContext([("X-ACS-Tenant", "acme"), ("other", "v")])
        assert tenant_from_metadata(ctx) == "acme"

    def test_metadata_invalid_value_is_absent(self):
        assert tenant_from_metadata(
            FakeGrpcContext([("x-acs-tenant", "not valid!")])
        ) is None
        assert tenant_from_metadata(FakeGrpcContext([])) is None
        assert tenant_from_metadata(object()) is None


# ------------------------------------------------------ size-class ladder


class TestSizeClassLadder:
    def test_smallest_fitting_class_wins(self):
        assert class_for_live(Capacities(S=1, KP=1, KR=2, T=4, RV=4,
                                         W=4)) == "xs"
        assert class_for_live(Capacities(S=1, KP=1, KR=8, T=4, RV=4,
                                         W=4)) == "s"
        assert class_for_live(Capacities(S=16, KP=16, KR=32, T=1024,
                                         RV=256, W=256)) == "l"

    def test_overflow_falls_off_the_ladder(self):
        live = Capacities(S=1, KP=1, KR=2, T=4096, RV=4, W=4)
        assert class_for_live(live) is None
        assert class_caps(None) is None
        assert class_caps("no-such-class") is None

    def test_class_caps_roundtrip(self):
        for name, caps in SIZE_CLASSES:
            assert class_caps(name) is caps


# ----------------------------------------------------- registry lifecycle


class TestTenantRegistry:
    def _registry(self):
        return TenantRegistry(URNS, backend="oracle")

    def test_onboard_epoch_and_serving_isolation(self):
        registry = self._registry()
        try:
            onboard(registry, "t1", effect="PERMIT")
            onboard(registry, "t2", effect="DENY")
            assert "t1" in registry and "t2" in registry
            # 2 rules + 1 policy + 1 policy set = 4 frames per tenant
            assert registry.tenant_epoch("t1") == 4
            req = t_request(0)
            r1 = registry.evaluator_for("t1").is_allowed_batch([req])[0]
            r2 = registry.evaluator_for("t2").is_allowed_batch([req])[0]
            assert r1.decision == Decision.PERMIT
            assert r2.decision == Decision.DENY
        finally:
            registry.shutdown()

    def test_unknown_tenant_envelope(self):
        registry = self._registry()
        assert registry.evaluator_for("ghost") is None
        resp = unknown_tenant_response("ghost")
        assert resp.decision == Decision.INDETERMINATE
        assert resp.operation_status.code == 404
        assert not resp.evaluation_cacheable
        assert "ghost" in resp.operation_status.message

    def test_crud_validation(self):
        registry = self._registry()
        with pytest.raises(ValueError):
            registry.apply("t1", "nonsense-kind", "upsert", {"id": "x"})
        with pytest.raises(ValueError):
            registry.apply("t1", "rule", "upsert", {"effect": "PERMIT"})
        # a rejected doc must not have onboarded the tenant
        assert "t1" not in registry
        # deletes for unknown tenants are no-ops, not onboarding events
        registry.apply("t1", "rule", "delete", {"id": "r0"})
        assert "t1" not in registry
        # unknown ops are rejected once the tenant exists (for an unknown
        # tenant the non-upsert early return wins)
        registry.apply("t2", "rule", "upsert", t_rule("r0", 0))
        with pytest.raises(ValueError):
            registry.apply("t2", "rule", "frobnicate", {"id": "r0"})

    def test_offboard_is_journal_shaped_and_drops_cache(self):
        cache = DecisionCache()
        cache.put("t1\x1eu0\x1fk", permit_response())
        cache.put("u0\x1fk", permit_response())
        registry = TenantRegistry(URNS, backend="oracle",
                                  decision_cache=cache)
        try:
            onboard(registry, "t1")
            assert registry.offboard("t1") is True
            assert "t1" not in registry
            assert registry.stats()["offboarded"] == 1
            # the tenant namespace went with it; default domain untouched
            assert cache.get("t1\x1eu0\x1fk") is None
            assert cache.get("u0\x1fk") is not None
            assert registry.offboard("t1") is False
        finally:
            registry.shutdown()

    def test_auto_offboard_when_collections_empty(self):
        registry = self._registry()
        try:
            registry.apply("t1", "rule", "upsert", t_rule("r0", 0))
            registry.apply("t1", "rule", "delete", {"id": "r0"})
            assert "t1" not in registry
        finally:
            registry.shutdown()

    def test_max_tenants_guard(self):
        registry = TenantRegistry(URNS, backend="oracle", max_tenants=1)
        try:
            registry.apply("t1", "rule", "upsert", t_rule("r0", 0))
            with pytest.raises(RuntimeError):
                registry.apply("t2", "rule", "upsert", t_rule("r0", 0))
        finally:
            registry.shutdown()

    def test_epoch_digest_order_independent(self):
        a, b = self._registry(), self._registry()
        try:
            onboard(a, "t1")
            onboard(a, "t2")
            onboard(b, "t2")  # same frames, different arrival order
            onboard(b, "t1")
            assert a.epoch_digest() == b.epoch_digest()
            before = a.epoch_digest()
            a.apply("t1", "rule", "upsert", t_rule("r9", 1))
            assert a.epoch_digest() != before
        finally:
            a.shutdown()
            b.shutdown()


# -------------------------------------------------------- program packing


class TestProgramPacking:
    """The packing claim at unit scale (tpu_compat_audit.py runs it at
    1k tenants): same-class tenants serve from ONE shared program and a
    tenant's CRUD patches only its own tables with zero new compiles."""

    def test_same_class_tenants_share_compiled_programs(self):
        registry = TenantRegistry(URNS)  # hybrid: real shared-jit table
        try:
            reqs = [t_request(k) for k in range(4)]
            onboard(registry, "t1")
            registry.evaluator_for("t1").is_allowed_batch(reqs)
            first_of_class = registry.compiled_program_count()
            assert first_of_class >= 1
            for tid in ("t2", "t3"):
                onboard(registry, tid)
                registry.evaluator_for(tid).is_allowed_batch(reqs)
            assert registry.compiled_program_count() == first_of_class
            hist = registry.class_histogram()
            assert hist.get("xs") == 3
        finally:
            registry.shutdown()

    def test_crud_patch_scoped_to_one_tenant_zero_new_compiles(self):
        registry = TenantRegistry(URNS)
        try:
            reqs = [t_request(k) for k in range(4)]
            for tid in ("t1", "t2"):
                onboard(registry, tid)
                registry.evaluator_for(tid).is_allowed_batch(reqs)
            sibling_before = [
                r.decision for r in
                registry.evaluator_for("t1").is_allowed_batch(reqs)
            ]
            fp_before = registry.fingerprints()
            programs_before = registry.compiled_program_count()
            # mutate a rule the tenant tree REFERENCES (r0 is in p0)
            registry.apply("t2", "rule", "upsert",
                           t_rule("r0", 0, effect="DENY"))
            fp_after = registry.fingerprints()
            changed = sorted(
                t for t in fp_before if fp_before[t] != fp_after[t]
            )
            assert changed == ["t2"]
            assert registry.compiled_program_count() == programs_before
            assert registry.evaluator_for("t2").is_allowed_batch(
                [t_request(0)]
            )[0].decision == Decision.DENY
            sibling_after = [
                r.decision for r in
                registry.evaluator_for("t1").is_allowed_batch(reqs)
            ]
            assert sibling_after == sibling_before
        finally:
            registry.shutdown()


# ------------------------------------------------------ cache scoping


class TestTenantCacheScoping:
    def test_fingerprint_carries_tenant_namespace(self):
        plain = t_request(0)
        tagged = t_request(0)
        tagged._tenant = "acme"
        k_plain = request_fingerprint(plain)
        k_tagged = request_fingerprint(tagged)
        assert key_tenant(k_plain) is None
        assert key_tenant(k_tagged) == "acme"
        assert k_tagged == f"acme\x1e{k_plain}"

    def test_tenant_bump_spares_other_namespaces(self):
        cache = DecisionCache()
        cache.put("a\x1eu0\x1fk", permit_response())
        cache.put("b\x1eu0\x1fk", permit_response())
        cache.put("u0\x1fk", permit_response())
        cache.bump_epoch(tenant="a")
        assert cache.get("a\x1eu0\x1fk") is None
        assert cache.get("b\x1eu0\x1fk") is not None
        assert cache.get("u0\x1fk") is not None

    def test_untenanted_bump_is_a_global_flush(self):
        # an untenanted epoch bump (config_update, restore, reset) is a
        # GLOBAL logical flush — the tenant guard lives on the targeted
        # eviction paths (evict_subject / evict_pattern), not here
        cache = DecisionCache()
        cache.put("a\x1eu0\x1fk", permit_response())
        cache.put("u0\x1fk", permit_response())
        cache.bump_epoch()
        assert cache.get("u0\x1fk") is None
        assert cache.get("a\x1eu0\x1fk") is None

    def test_evict_subject_tenant_scoped(self):
        cache = DecisionCache()
        cache.put("a\x1eu0\x1fk", permit_response())
        cache.put("b\x1eu0\x1fk", permit_response())
        cache.put("u0\x1fk", permit_response())
        assert cache.evict_subject("u0", tenant="a") == 1
        assert cache.get("a\x1eu0\x1fk") is None
        assert cache.get("b\x1eu0\x1fk") is not None
        # untenanted eviction walks only the default domain
        assert cache.evict_subject("u0") == 1
        assert cache.get("u0\x1fk") is None
        assert cache.get("b\x1eu0\x1fk") is not None

    def test_evict_pattern_prefix_collision_guard(self):
        cache = DecisionCache()
        # tenant id sharing a string prefix with a default-domain subject
        cache.put("u1-corp\x1eu9\x1fk", permit_response())
        cache.put("u1\x1fk", permit_response())
        cache.put("u12\x1fk", permit_response())
        assert cache.evict_pattern("u1") == 2
        assert cache.get("u1-corp\x1eu9\x1fk") is not None
        # tenant-scoped empty pattern drops exactly that tenant
        cache.put("u1\x1fk", permit_response())
        assert cache.evict_pattern("", tenant="u1-corp") == 1
        assert cache.get("u1\x1fk") is not None


# ------------------------------------------- worker invalidation paths


class TestWorkerTenantInvalidation:
    """Satellite 3: flush_cache and userModified/userDeleted must scope
    to the originating tenant's cache namespace."""

    @pytest.fixture()
    def worker(self):
        w = Worker().start(seed_cfg(
            tenancy={"enabled": True},
            decision_cache={"enabled": True},
        ))
        yield w
        w.stop()

    def _seed_entries(self, worker):
        cache = worker.decision_cache
        cache.put("acme\x1eu0\x1fk", permit_response())
        cache.put("globex\x1eu0\x1fk", permit_response())
        cache.put("u0\x1fk", permit_response())
        return cache

    def test_flush_cache_command_tenant_scoped(self, worker):
        cache = self._seed_entries(worker)
        worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 5, "pattern": "",
                                     "tenant": "acme"}}
        )
        assert cache.get("acme\x1eu0\x1fk") is None
        assert cache.get("globex\x1eu0\x1fk") is not None
        assert cache.get("u0\x1fk") is not None

    def test_flush_cache_command_untenanted_pattern_spares_tenants(
        self, worker
    ):
        # an untenanted PATTERN flush walks only default-domain keys; a
        # pattern-less untenanted flush stays a full physical flush
        # (operator semantics), so only the pattern form is scoped
        cache = self._seed_entries(worker)
        worker.command_interface.command(
            "flush_cache", {"data": {"db_index": 5, "pattern": "u0"}}
        )
        assert cache.get("u0\x1fk") is None
        assert cache.get("acme\x1eu0\x1fk") is not None
        assert cache.get("globex\x1eu0\x1fk") is not None

    def test_user_events_tenant_scoped(self, worker):
        cache = self._seed_entries(worker)
        topic = worker.bus.topic(USERS_TOPIC)
        topic.emit("userModified", {"id": "u0", "tenant": "acme"})
        assert cache.get("acme\x1eu0\x1fk") is None
        assert cache.get("globex\x1eu0\x1fk") is not None
        assert cache.get("u0\x1fk") is not None
        topic.emit("userDeleted", {"id": "u0", "tenant": "globex"})
        assert cache.get("globex\x1eu0\x1fk") is None
        assert cache.get("u0\x1fk") is not None

    def test_user_events_untenanted_spare_tenants(self, worker):
        cache = self._seed_entries(worker)
        worker.bus.topic(USERS_TOPIC).emit("userDeleted", {"id": "u0"})
        assert cache.get("u0\x1fk") is None
        assert cache.get("acme\x1eu0\x1fk") is not None
        assert cache.get("globex\x1eu0\x1fk") is not None


# --------------------------------------------------- per-tenant quotas


class TestTenantQuotas:
    def _controller(self, **overrides):
        kwargs = dict(
            enabled=True, tenant_enabled=True,
            max_queue_interactive=8, tenant_max_inflight=4,
            tenant_contention_ratio=0.5,
        )
        kwargs.update(overrides)
        return AdmissionController(**kwargs)

    def test_inflight_cap_sheds_then_releases(self):
        ctrl = self._controller(max_queue_interactive=64)
        for _ in range(4):
            assert ctrl.admit(INTERACTIVE, tenant="a") is None
        shed = ctrl.admit(INTERACTIVE, tenant="a")
        assert shed is not None
        assert shed.operation_status.code == 429
        assert "inflight cap" in shed.operation_status.message
        assert ctrl.stats()["shed_tenant_quota"] == 1
        # an untenanted request is untouched by the quota machinery
        assert ctrl.admit(INTERACTIVE) is None
        ctrl.release(INTERACTIVE, 1, tenant="a")
        assert ctrl.admit(INTERACTIVE, tenant="a") is None

    def test_fair_share_only_under_contention(self):
        ctrl = self._controller(tenant_max_inflight=64)
        # depth 3 < 8*0.5: uncontended, tenant "a" may hog the queue
        for _ in range(3):
            assert ctrl.admit(INTERACTIVE, tenant="a") is None
        # depth 4 >= 4: contended; "a" holds all slots, weight share with
        # a second active tenant bounds it to 8/2 = 4
        assert ctrl.admit(INTERACTIVE, tenant="a") is None
        assert ctrl.admit(INTERACTIVE, tenant="b") is None
        shed = ctrl.admit(INTERACTIVE, tenant="a")
        assert shed is not None
        assert "fair share" in shed.operation_status.message
        assert ctrl.stats()["shed_tenant_fair_share"] == 1
        # the lighter tenant still gets in
        assert ctrl.admit(INTERACTIVE, tenant="b") is None

    def test_weighted_share(self):
        ctrl = self._controller(
            tenant_max_inflight=64, max_queue_interactive=8,
            tenant_weights={"vip": 3.0},
        )
        for _ in range(4):
            assert ctrl.admit(INTERACTIVE, tenant="vip") is None
        assert ctrl.admit(INTERACTIVE, tenant="b") is None
        # vip's share is 3/4 of 8 = 6: two more slots before the bound
        assert ctrl.admit(INTERACTIVE, tenant="vip") is None
        assert ctrl.admit(INTERACTIVE, tenant="vip") is None
        assert ctrl.admit(INTERACTIVE, tenant="vip") is not None

    def test_release_drops_empty_tenant_slots(self):
        ctrl = self._controller()
        ctrl.admit(INTERACTIVE, tenant="a")
        assert ctrl._tenant_depth == {"a": 1}
        ctrl.release(INTERACTIVE, 1, tenant="a")
        # offboarded tenants must not pin dict slots forever
        assert ctrl._tenant_depth == {}


# -------------------------------------------------- bounded telemetry


class TestTenantTelemetry:
    def test_ten_thousand_ids_cannot_grow_the_registry(self):
        """Satellite 1 regression: tenant ids are attacker-controlled
        label values — cardinality must stay bounded."""
        from access_control_srv_tpu.srv.telemetry import (
            MetricsRegistry,
            TenantCounter,
        )

        counter = TenantCounter(max_tracked=64)
        for i in range(10_000):
            counter.inc("decision", f"tenant-{i}")
        assert counter.tracked() <= 64
        snap = counter.prom_snapshot()
        # 64 tracked ids + the __other__ overflow bucket, one event kind
        assert len(snap) <= 65
        assert snap[("decision", "__other__")] == 10_000 - 64
        registry = MetricsRegistry()
        registry.multi_counter(
            "acs_tenant_events_total", "per-tenant events",
            counter.prom_snapshot, labels=("event", "tenant"),
        )
        lines = [ln for ln in registry.render().splitlines()
                 if ln.startswith("acs_tenant_events_total{")]
        assert 0 < len(lines) <= 65
        assert any('tenant="__other__"' in ln for ln in lines)

    def test_snapshot_top_k_folds_tail(self):
        from access_control_srv_tpu.srv.telemetry import TenantCounter

        counter = TenantCounter(max_tracked=64)
        for i in range(40):
            counter.inc("shed", f"t{i}", by=i + 1)
        snap = counter.snapshot(top_k=4)["shed"]
        assert len(snap) == 5  # 4 ranked + __other__ fold
        assert snap["t39"] == 40
        assert snap["__other__"] == sum(range(1, 37))

    def test_tenant_inc_threads_through_telemetry(self):
        from access_control_srv_tpu.srv.telemetry import Telemetry

        telemetry = Telemetry()
        telemetry.tenant_inc("decision", "acme", by=3)
        snap = telemetry.snapshot()
        assert snap["tenants"]["decision"]["acme"] == 3
        rendered = telemetry.registry.render()
        assert 'acs_tenant_events_total{event="decision",tenant="acme"} 3' \
            in rendered


# ------------------------------------------------- worker serving path


class TestWorkerTenantServing:
    @pytest.fixture()
    def worker(self):
        w = Worker().start(seed_cfg(
            tenancy={"enabled": True},
            evaluator={"backend": "oracle"},
        ))
        yield w
        w.stop()

    def _submit(self, worker, req, tenant=None):
        if tenant is not None:
            req._tenant = tenant
        return worker.batcher.submit(req).result(timeout=10)

    def test_mixed_batch_routes_by_tenant(self, worker):
        onboard(worker.tenancy, "acme", emit=True, effect="PERMIT")
        onboard(worker.tenancy, "globex", emit=True, effect="DENY")
        results = {}
        threads = [
            threading.Thread(target=lambda t=t: results.update(
                {t: self._submit(worker, t_request(0), tenant=t)}
            )) for t in ("acme", "globex")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert results["acme"].decision == Decision.PERMIT
        assert results["globex"].decision == Decision.DENY
        # default domain still serves the seeded tree
        assert self._submit(worker, admin_request()).decision \
            == Decision.PERMIT

    def test_unknown_tenant_gets_404_not_default_domain(self, worker):
        resp = self._submit(worker, admin_request(), tenant="ghost")
        assert resp.decision == Decision.INDETERMINATE
        assert resp.operation_status.code == 404

    def test_health_and_program_identity_tenancy_blocks(self, worker):
        onboard(worker.tenancy, "acme", emit=True)
        self._submit(worker, t_request(0), tenant="acme")
        health = worker.command_interface.command("health_check")
        block = health["tenancy"]
        assert block["tenant_count"] == 1
        assert block["evaluators_built"] == 1
        assert block["epoch_top_k"] == {"acme": 4}
        assert block["epoch_digest"]
        assert "size_classes" in block
        # program_identity is what the router polls into cluster_status
        identity = worker.command_interface.command("program_identity")
        assert identity["tenancy"]["tenant_count"] == 1
        assert identity["tenancy"]["epoch_digest"] == \
            block["epoch_digest"]


# --------------------------------------------- noisy-neighbor latency


class TestNoisyNeighborBound:
    def test_quiet_tenant_admitted_p99_inside_deadline_bound(self):
        """One tenant flooding the interactive queue must not push
        another tenant's ADMITTED p99 past the deadline bound (sheds are
        the release valve; admitted work keeps its latency contract)."""
        deadline_ms = 100.0
        worker = Worker().start(seed_cfg(
            tenancy={"enabled": True},
            decision_cache={"enabled": False},
            evaluator={"backend": "oracle"},
            admission={
                "enabled": True,
                "max_queue_interactive": 128,
                "deadline_bound_ms": deadline_ms,
                "min_batch": 8,
                # the p99 bound is a queueing bound: cap the flood's
                # queue occupancy so admitted quiet work never waits
                # behind it past the deadline
                "tenant": {"max_inflight_per_tenant": 32},
            },
        ))
        try:
            for tid in ("noisy", "quiet"):
                onboard(worker.tenancy, tid, emit=True)
            stop = threading.Event()

            def flood():
                i = 0
                while not stop.is_set():
                    req = t_request(i)
                    req._tenant = "noisy"
                    try:
                        worker.batcher.submit(req)
                    except Exception:  # noqa: BLE001 — open loop
                        pass
                    i += 1
                    if i % 64 == 0:
                        time.sleep(0.001)

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(2)]
            for t in threads:
                t.start()
            latencies = []
            t_end = time.monotonic() + 1.2
            i = 0
            while time.monotonic() < t_end:
                req = t_request(i)
                req._tenant = "quiet"
                t0 = time.perf_counter()
                resp = worker.batcher.submit(
                    req, deadline=time.monotonic() + deadline_ms / 1e3
                ).result(timeout=10)
                if resp.operation_status.code == 200:
                    latencies.append(time.perf_counter() - t0)
                i += 1
            stop.set()
            for t in threads:
                t.join(timeout=5)
        finally:
            worker.stop()
        assert latencies, "quiet tenant was starved outright"
        latencies.sort()
        p99_ms = latencies[
            min(len(latencies) - 1, int(len(latencies) * 0.99))
        ] * 1e3
        assert p99_ms <= deadline_ms, (
            f"quiet tenant admitted p99 {p99_ms:.1f} ms blew the "
            f"{deadline_ms} ms bound"
        )


# --------------------------------------------------- router aggregation


class TestRouterTenancyAggregation:
    def test_status_reports_tenant_convergence(self):
        from access_control_srv_tpu.srv.router import ClusterRouter

        router = ClusterRouter(["127.0.0.1:1", "127.0.0.1:2"])
        try:
            a, b = router.replicas
            a.tenancy = {"tenant_count": 3, "epoch_digest": "d1"}
            b.tenancy = {"tenant_count": 3, "epoch_digest": "d1"}
            status = router.status()
            assert status["tenancy"] == {
                "replicas_reporting": 2,
                "tenant_count": 3,
                "tenant_converged": True,
            }
            b.tenancy = {"tenant_count": 2, "epoch_digest": "d2"}
            assert router.status()["tenancy"]["tenant_converged"] is False
        finally:
            router.stop()

    def test_status_without_tenancy_blocks_is_unchanged(self):
        from access_control_srv_tpu.srv.router import ClusterRouter

        router = ClusterRouter(["127.0.0.1:1"])
        try:
            assert "tenancy" not in router.status()
        finally:
            router.stop()


# ---------------------------------------------------- journal replication


class TestTenantReplication:
    def test_tenants_converge_and_boot_by_replay(self):
        """Tenant CRUD is a journaled stream: a peer replica applies live
        frames and a late-booting replica onboards every journaled tenant
        by replay — per-tenant epochs and the epoch digest converge."""
        from access_control_srv_tpu.srv.broker import BrokerServer

        broker = BrokerServer().start()
        workers = []
        try:
            def boot():
                w = Worker().start(seed_cfg(
                    tenancy={"enabled": True},
                    evaluator={"backend": "oracle"},
                    events={"broker": {"address": broker.address}},
                ))
                workers.append(w)
                return w

            a = boot()
            b = boot()
            onboard(a.tenancy, "acme", emit=True)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if b.tenancy.tenant_epoch("acme") == 4:
                    break
                time.sleep(0.05)
            assert b.tenancy.tenant_epoch("acme") == 4
            assert b.tenancy.epoch_digest() == a.tenancy.epoch_digest()
            req = t_request(0)
            req._tenant = "acme"
            resp = b.batcher.submit(req).result(timeout=10)
            assert resp.decision == Decision.PERMIT
            # late joiner: replays the journal at boot, no live frames
            c = boot()
            assert c.tenancy.tenant_epoch("acme") == 4
            assert c.tenancy.epoch_digest() == a.tenancy.epoch_digest()
        finally:
            for w in workers:
                w.stop()
            broker.stop()


# ------------------------------------------------ byte-identity differential


class TestWorkerTenancyDifferential:
    """Acceptance bar: with no tenant id anywhere in the traffic, a
    worker with the tenancy registry wired answers byte-for-byte what a
    worker without it answers."""

    def _responses(self, tenancy_enabled):
        from access_control_srv_tpu.srv.transport_grpc import (
            response_to_pb,
            reverse_query_to_pb,
        )

        cfg = seed_cfg()
        if tenancy_enabled:
            cfg["tenancy"] = {"enabled": True}
        worker = Worker().start(cfg)
        try:
            assert (worker.tenancy is not None) is tenancy_enabled
            requests = [admin_request(), admin_request(role="nobody"),
                        admin_request()]
            single = [
                response_to_pb(
                    worker.service.is_allowed(r)
                ).SerializeToString()
                for r in requests
            ]
            batch = [
                response_to_pb(r).SerializeToString()
                for r in worker.service.is_allowed_batch(
                    [admin_request(), admin_request(role="nobody")]
                )
            ]
            reverse = reverse_query_to_pb(
                worker.service.what_is_allowed(admin_request())
            ).SerializeToString()
        finally:
            worker.stop()
        return single, batch, reverse

    def test_no_tenant_traffic_byte_identical(self):
        assert self._responses(True) == self._responses(False)
