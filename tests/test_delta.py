"""Differential suite for the incremental policy-update subsystem
(ops/delta.py): capacity-bucketed tables, in-place CRUD patching, scoped
decision-cache invalidation, refresh debounce.

Table-identity bar: after every mutation the patched tables must decode
to EXACTLY the same policy semantics as a from-scratch
``compile_policies`` of the final tree — compared through
:func:`canonical_tables`, which maps interner ids / vocab rows / target
rows back to strings (those numberings are representation, not
semantics: the kernel only ever consumes them through the same
indirections the canonicalizer follows).  Decision bar: kernel decisions
bit-identical to the scalar oracle on every corpus row, cache on AND off.
"""

from __future__ import annotations

import random
import threading
import time

import numpy as np
import pytest

from access_control_srv_tpu.core.engine import AccessController
from access_control_srv_tpu.models import Attribute, Request, Target, Urns
from access_control_srv_tpu.ops import compile_policies
from access_control_srv_tpu.ops import delta as delta_mod
from access_control_srv_tpu.ops.compile import TARGET_COLUMNS
from access_control_srv_tpu.srv.decision_cache import (
    DecisionCache,
    request_features,
)
from access_control_srv_tpu.srv.evaluator import HybridEvaluator
from access_control_srv_tpu.srv.store import PolicyStore

URNS = Urns()
PO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:permit-overrides"
DO = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:deny-overrides"
FA = "urn:oasis:names:tc:xacml:3.0:rule-combining-algorithm:first-applicable"


def entity(k: int) -> str:
    return f"urn:restorecommerce:acs:model:thing{k}.Thing{k}"


def rule_doc(rid: str, k: int, effect: str = "PERMIT",
             cacheable: bool = True, action: str = "read") -> dict:
    return {
        "id": rid,
        "target": {
            "subjects": [{"id": URNS["role"], "value": f"role-{k % 5}"}],
            "resources": [{"id": URNS["entity"], "value": entity(k)}],
            "actions": [{"id": URNS["actionID"], "value": URNS[action]}],
        },
        "effect": effect,
        "evaluation_cacheable": cacheable,
    }


def make_request(k: int, who: str = "u1", action: str = "read") -> Request:
    role = f"role-{k % 5}"
    return Request(
        target=Target(
            subjects=[Attribute(id=URNS["role"], value=role),
                      Attribute(id=URNS["subjectID"], value=who)],
            resources=[Attribute(id=URNS["entity"], value=entity(k))],
            actions=[Attribute(id=URNS["actionID"], value=URNS[action])],
        ),
        context={"resources": [], "subject": {
            "id": who,
            "role_associations": [{"role": role, "attributes": []}],
            "hierarchical_scopes": [],
        }},
    )


def build_stack(n_rules: int = 12, n_policies: int = 2, cache: bool = True):
    engine = AccessController()
    decision_cache = DecisionCache() if cache else None
    evaluator = HybridEvaluator(engine, decision_cache=decision_cache)
    store = PolicyStore(engine, evaluator=evaluator)
    rules = [rule_doc(f"r{i}", i) for i in range(n_rules)]
    per = max(1, n_rules // n_policies)
    pols = [
        {"id": f"p{p}", "combining_algorithm": PO,
         "rules": [f"r{i}" for i in range(p * per, min((p + 1) * per,
                                                       n_rules))]}
        for p in range(n_policies)
    ]
    sets_ = [{"id": "s0", "combining_algorithm": DO,
              "policies": [p["id"] for p in pols]}]
    store.seed(sets_, pols, rules)
    return engine, evaluator, store, decision_cache


# ------------------------------------------------------- table canonicalizer

_T_BOOL = {"t_has_role", "t_has_scoping", "t_hr_check", "t_skip_acl",
           "t_has_props"}
_T_INT = {"t_n_subjects", "t_n_res"}
_T_VOCAB = {"t_ent_w"}


def _canon_target_row(compiled, idx: int):
    a = compiled.arrays
    interner = compiled.interner

    def s(i):
        i = int(i)
        return None if i < 0 else interner.string(i)

    out = {}
    for name, key, _dtype in TARGET_COLUMNS:
        v = np.asarray(a[name][int(idx)])
        if name in _T_BOOL:
            out[key] = bool(v)
        elif name in _T_INT:
            out[key] = int(v)
        elif name in _T_VOCAB:
            out[key] = tuple(
                None if int(w) < 0 else compiled.entity_vocab[int(w)]
                for w in v
            )
        elif v.ndim:
            out[key] = tuple(s(x) for x in v)
        else:
            out[key] = s(v)
    rs = int(a["t_rs_idx"][int(idx)])
    out["rs"] = (s(a["hrv_role"][rs]), s(a["hrv_scope"][rs]))
    return tuple(sorted(out.items()))


def _rstrip_none(items: list) -> tuple:
    while items and items[-1] is None:
        items.pop()
    return tuple(items)


def canonical_tables(compiled):
    """Representation-free decode of the compiled tables: slot layout,
    intern ids, target-row numbering, vocab ordering and padding are all
    erased; everything the kernel can observe is kept."""
    a = compiled.arrays
    out = []
    for s in range(compiled.S):
        if not a["set_valid"][s]:
            continue
        pols = []
        for kp in range(compiled.KP):
            if not a["pol_valid"][s, kp]:
                pols.append(None)
                continue
            rules = []
            for kr in range(compiled.KR):
                if not a["rule_valid"][s, kp, kr]:
                    rules.append(None)
                    continue
                cond = None
                ci = int(a["rule_cond"][s, kp, kr])
                if ci >= 0:
                    cc = compiled.conditions[ci]
                    cond = (cc.condition, repr(cc.context_query))
                rules.append((
                    int(a["rule_effect"][s, kp, kr]),
                    bool(a["rule_cacheable_raw"][s, kp, kr]),
                    bool(a["rule_cacheable_eff"][s, kp, kr]),
                    _canon_target_row(compiled, a["rule_target"][s, kp, kr])
                    if a["rule_has_target"][s, kp, kr] else None,
                    cond,
                ))
            pols.append((
                int(a["pol_ca"][s, kp]),
                int(a["pol_effect"][s, kp]),
                int(a["pol_eff_ctx"][s, kp]),
                bool(a["pol_cacheable"][s, kp]),
                bool(a["pol_has_subjects"][s, kp]),
                bool(a["pol_has_props"][s, kp]),
                int(a["pol_n_rules"][s, kp]),
                _canon_target_row(compiled, a["pol_target"][s, kp])
                if a["pol_has_target"][s, kp] else None,
                _rstrip_none(rules),
            ))
        out.append((
            int(a["set_ca"][s]),
            _canon_target_row(compiled, a["set_target"][s])
            if a["set_has_target"][s] else None,
            _rstrip_none(pols),
        ))
    return tuple(out)


def assert_tables_match_full_compile(engine, evaluator):
    patched = evaluator._compiled
    fresh = compile_policies(engine.policy_sets, engine.urns)
    assert fresh.supported
    assert canonical_tables(patched) == canonical_tables(fresh)


def assert_decisions_match_oracle(engine, evaluator, corpus_keys,
                                  subjects=("u1", "u2")):
    requests = [make_request(k, who) for k in corpus_keys
                for who in subjects]
    got = evaluator.is_allowed_batch([make_request(k, who)
                                      for k in corpus_keys
                                      for who in subjects])
    want = [engine.is_allowed(r) for r in requests]
    for g, w, r in zip(got, want, requests):
        assert g.decision == w.decision, (r.target, g, w)
        assert g.evaluation_cacheable == w.evaluation_cacheable, (g, w)


# ------------------------------------------------------------------- tests


class TestDeltaPatch:
    def test_rule_modify_patches_without_full_compile(self):
        engine, ev, store, _ = build_stack()
        base_full = ev.delta_stats()["full_compiles"]
        svc = store.get_resource_service("rule")
        svc.update([rule_doc("r0", 0, effect="DENY")])
        stats = ev.delta_stats()
        assert stats["patches"] == 1
        assert stats["full_compiles"] == base_full
        assert stats["recompiles_avoided"] == 1
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(12))

    def test_rule_create_attach_and_delete(self):
        engine, ev, store, _ = build_stack()
        rule_svc = store.get_resource_service("rule")
        pol_svc = store.get_resource_service("policy")
        # create an unreferenced rule: certified no-op (no flush, no patch)
        rule_svc.create([rule_doc("rx", 3, effect="DENY")])
        stats = ev.delta_stats()
        assert stats["noops"] >= 1
        # attach it: a real patch
        p0 = store.collections["policy"].get("p0")
        p0["rules"] = p0["rules"] + ["rx"]
        pol_svc.update([p0])
        assert ev.delta_stats()["patches"] >= 1
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(12))
        # detach + delete: target row goes to the free list, then reuse it
        state = ev._delta_state
        t_live = state.t_live
        p0 = store.collections["policy"].get("p0")
        p0["rules"] = [r for r in p0["rules"] if r != "rx"]
        pol_svc.update([p0])
        rule_svc.delete(ids=["rx"])
        state = ev._delta_state
        assert state.free_rows, "deleted rule's target row must be freed"
        assert state.t_live == t_live
        p0 = store.collections["policy"].get("p0")
        p0["rules"] = p0["rules"] + ["r1"]  # r1 now in both policies? no: dup
        # attach a fresh rule instead: reuses the freed row slot
        rule_svc.create([rule_doc("ry", 7, effect="DENY")])
        p0["rules"][-1] = "ry"
        pol_svc.update([p0])
        state = ev._delta_state
        assert not state.free_rows, "freed row slot must be reused"
        assert state.t_live == t_live
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(12))

    def test_capacity_overflow_falls_back_to_full_recompile(self):
        engine, ev, store, _ = build_stack(n_rules=8, n_policies=1)
        caps = ev._caps
        rule_svc = store.get_resource_service("rule")
        pol_svc = store.get_resource_service("policy")
        extra = [rule_doc(f"ov{i}", i, effect="DENY")
                 for i in range(caps.KR + 4)]
        rule_svc.create(extra)
        p0 = store.collections["policy"].get("p0")
        p0["rules"] = p0["rules"] + [r["id"] for r in extra]
        base_full = ev.delta_stats()["full_compiles"]
        pol_svc.update([p0])
        stats = ev.delta_stats()
        assert stats["full_compiles"] == base_full + 1
        assert "capacity-rules" in stats["fallback_reasons"]
        assert ev._caps.KR > caps.KR  # buckets grew
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(8))

    def test_combining_algorithm_change_falls_back(self):
        engine, ev, store, _ = build_stack()
        pol_svc = store.get_resource_service("policy")
        p0 = store.collections["policy"].get("p0")
        p0["combining_algorithm"] = FA
        base_full = ev.delta_stats()["full_compiles"]
        pol_svc.update([p0])
        stats = ev.delta_stats()
        assert stats["full_compiles"] == base_full + 1
        assert "combining-algorithm-changed" in stats["fallback_reasons"]
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(12))

    def test_condition_change_falls_back(self):
        engine, ev, store, _ = build_stack()
        rule_svc = store.get_resource_service("rule")
        doc = rule_doc("r2", 2)
        doc["condition"] = "True"
        base_full = ev.delta_stats()["full_compiles"]
        rule_svc.update([doc])
        stats = ev.delta_stats()
        assert stats["full_compiles"] == base_full + 1
        assert "condition-added" in stats["fallback_reasons"]
        assert_tables_match_full_compile(engine, ev)

    def test_set_membership_change_falls_back(self):
        engine, ev, store, _ = build_stack()
        set_svc = store.get_resource_service("policy_set")
        base_full = ev.delta_stats()["full_compiles"]
        set_svc.create([{"id": "s1", "combining_algorithm": DO,
                         "policies": ["p1"]}])
        stats = ev.delta_stats()
        assert stats["full_compiles"] == base_full + 1
        assert "set-list-changed" in stats["fallback_reasons"]
        assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(12))

    def test_noop_update_skips_flush_and_compile(self):
        engine, ev, store, cache = build_stack()
        ev.is_allowed_batch([make_request(0), make_request(5)])
        epoch = cache.epoch
        stores = cache.stats()["stores"]
        svc = store.get_resource_service("rule")
        svc.update([rule_doc("r0", 0)])  # identical payload (meta restamped)
        assert cache.epoch == epoch, "no-op delta must not bump the epoch"
        stats = ev.delta_stats()
        assert stats["noops"] >= 1
        # warm entries survive untouched
        ev.is_allowed_batch([make_request(0), make_request(5)])
        post = cache.stats()
        assert post["stores"] == stores
        assert post["hits"] >= 2


class TestProgramReuse:
    def test_in_capacity_patch_compiles_no_new_programs(self):
        # decision cache OFF: post-patch cache hits would shrink the miss
        # batch and legitimately enter a new (smaller) batch bucket —
        # this test isolates mutation-caused recompiles
        engine, ev, store, _ = build_stack(cache=False)
        # warm every jitted program for this traffic shape
        ev.is_allowed_batch([make_request(k) for k in range(12)])
        kernel_before = ev._kernel
        shared = ev._shared_jits
        assert shared, "delta mode must register shared jits"
        sizes_before = {k: f._cache_size() for k, f in shared.items()}
        svc = store.get_resource_service("rule")
        svc.update([rule_doc("r3", 3, effect="DENY")])
        assert ev.delta_stats()["patches"] == 1
        assert ev._kernel is not kernel_before  # swapped object...
        ev.is_allowed_batch([make_request(k) for k in range(12)])
        sizes_after = {k: f._cache_size() for k, f in ev._shared_jits.items()}
        assert sizes_after == sizes_before, (
            "an in-capacity mutation must not add XLA compilations"
        )

    def test_patched_tables_share_shapes_with_bucketed_full_compile(self):
        engine, ev, store, _ = build_stack()
        svc = store.get_resource_service("rule")
        svc.update([rule_doc("r1", 1, effect="DENY")])
        assert ev.delta_stats()["patches"] == 1
        patched = ev._compiled
        full, caps, _state = delta_mod.full_bucketed_compile(
            engine.policy_sets, engine.urns, prev_caps=ev._caps
        )
        assert caps == ev._caps
        for name, arr in patched.arrays.items():
            assert np.asarray(arr).shape == np.asarray(
                full.arrays[name]).shape, name
            assert np.asarray(arr).dtype == np.asarray(
                full.arrays[name]).dtype, name


class TestScopedInvalidation:
    def test_disjoint_entries_survive_rule_mutation(self):
        engine, ev, store, cache = build_stack()
        ev.is_allowed_batch([make_request(0), make_request(1),
                             make_request(6)])
        assert cache.stats()["stores"] == 3
        svc = store.get_resource_service("rule")
        svc.update([rule_doc("r0", 0, effect="DENY")])
        out = ev.is_allowed_batch([make_request(0), make_request(1),
                                   make_request(6)])
        assert out[0].decision == "DENY"  # the mutation is visible
        stats = cache.stats()
        # entity-1 and entity-6 entries survived both scoped bumps
        assert stats["scoped_survivors"] >= 2
        assert stats["hits"] >= 2
        assert_decisions_match_oracle(engine, ev, range(12))

    def test_scoped_put_refusal_preserves_epoch_race_invariant(self):
        from access_control_srv_tpu.models.model import (
            OperationStatus,
            Response,
        )

        cache = DecisionCache()
        permit = Response(decision="PERMIT", evaluation_cacheable=True,
                          operation_status=OperationStatus())
        affected = request_features(
            make_request(0), URNS["entity"], URNS["operation"]
        )
        disjoint = request_features(
            make_request(1), URNS["entity"], URNS["operation"]
        )
        footprint = delta_mod.Footprint(scopes=[delta_mod.RuleScope(
            entities=(entity(0),), acts=(URNS["read"],),
        )])
        epoch = cache.epoch
        cache.bump_scoped(footprint)  # mutation lands mid-evaluation
        # affected writer: refused exactly as a global bump would
        assert not cache.put("a\x1fk", permit, epoch=epoch,
                             features=affected)
        # disjoint writer: provably unaffected, stored fresh
        assert cache.put("b\x1fk", permit, epoch=epoch, features=disjoint)
        assert cache.get("b\x1fk") is not None
        # feature-less writer: pre-delta semantics verbatim
        assert not cache.put("c\x1fk", permit, epoch=epoch)
        # global bump still flushes everything
        cache.bump_epoch()
        assert cache.get("b\x1fk") is None

    def test_regex_entity_pattern_widens_footprint(self):
        # pattern tail "Thing" regex-matches entity tail "Thing1" under a
        # shared "sub" namespace (core/hierarchical_scope semantics)
        footprint = delta_mod.Footprint(scopes=[delta_mod.RuleScope(
            entities=("urn:restorecommerce:acs:model:sub.Thing",),
        )])
        req = Request(target=Target(
            resources=[Attribute(
                id=URNS["entity"],
                value="urn:restorecommerce:acs:model:sub.Thing1",
            )],
        ))
        hit = request_features(req, URNS["entity"], URNS["operation"])
        assert footprint.affects(hit)
        miss = request_features(
            make_request(2), URNS["entity"], URNS["operation"]
        )
        assert not footprint.affects(miss)


class TestRefreshDebounce:
    def test_refresh_storm_coalesces_compiles(self):
        engine = AccessController()
        ev = HybridEvaluator(engine, async_compile=True)
        store = PolicyStore(engine, evaluator=ev)
        rules = [rule_doc(f"r{i}", i) for i in range(6)]
        store.seed(
            [{"id": "s0", "combining_algorithm": DO, "policies": ["p0"]}],
            [{"id": "p0", "combining_algorithm": PO,
              "rules": [r["id"] for r in rules]}],
            rules,
        )
        base = ev.delta_stats()["full_compiles"]
        for _ in range(20):
            ev.refresh()  # no events: always the full path
        deadline = time.time() + 30
        while time.time() < deadline:
            with ev._compile_state_lock:
                idle = (not ev._compile_pending
                        and (ev._compile_thread is None
                             or not ev._compile_thread.is_alive()))
            if idle:
                break
            time.sleep(0.02)
        ran = ev.delta_stats()["full_compiles"] - base
        assert 1 <= ran <= 3, f"20 refreshes ran {ran} compiles"
        with ev._lock:
            assert ev._compiled.version == ev._version  # converged
        ev.shutdown()
        assert ev._compile_thread is None or not ev._compile_thread.is_alive()

    def test_shutdown_joins_compile_thread(self):
        engine = AccessController()
        ev = HybridEvaluator(engine, async_compile=True)
        store = PolicyStore(engine, evaluator=ev)
        rules = [rule_doc(f"r{i}", i) for i in range(4)]
        store.seed(
            [{"id": "s0", "combining_algorithm": DO, "policies": ["p0"]}],
            [{"id": "p0", "combining_algorithm": PO,
              "rules": [r["id"] for r in rules]}],
            rules,
        )
        ev.refresh()
        ev.shutdown(timeout=30)
        thread = ev._compile_thread
        assert thread is None or not thread.is_alive()
        # a post-shutdown refresh must not spawn a new worker
        ev.refresh()
        assert ev._compile_thread is None or not ev._compile_thread.is_alive()


def _apply_random_op(rng, store, next_id):
    rule_svc = store.get_resource_service("rule")
    pol_svc = store.get_resource_service("policy")
    pol_ids = [d["id"] for d in store.collections["policy"].all()]
    op = rng.choice(["modify", "modify", "modify", "create", "delete",
                     "toggle_cacheable"])
    if op == "modify":
        docs = store.collections["rule"].all()
        doc = rng.choice(docs)
        k = rng.randrange(16)
        effect = rng.choice(["PERMIT", "DENY"])
        rule_svc.update([rule_doc(doc["id"], k, effect=effect,
                                  cacheable=doc.get(
                                      "evaluation_cacheable", True))])
    elif op == "toggle_cacheable":
        docs = store.collections["rule"].all()
        doc = rng.choice(docs)
        new = dict(doc)
        new["evaluation_cacheable"] = not doc.get(
            "evaluation_cacheable", False
        )
        rule_svc.update([new])
    elif op == "create":
        rid = f"f{next_id[0]}"
        next_id[0] += 1
        k = rng.randrange(16)
        rule_svc.create([rule_doc(rid, k,
                                  effect=rng.choice(["PERMIT", "DENY"]))])
        pid = rng.choice(pol_ids)
        p = store.collections["policy"].get(pid)
        rules = p["rules"]
        rules.insert(rng.randrange(len(rules) + 1), rid)
        pol_svc.update([p])
    else:  # delete
        pid = rng.choice(pol_ids)
        p = store.collections["policy"].get(pid)
        if len(p["rules"]) <= 1:
            return
        victim = rng.choice(p["rules"])
        p["rules"] = [r for r in p["rules"] if r != victim]
        pol_svc.update([p])
        if not any(victim in (d.get("rules") or [])
                   for d in store.collections["policy"].all()):
            rule_svc.delete(ids=[victim])


@pytest.mark.parametrize("seed", [11, 29, 47])
def test_differential_fuzz_random_crud_sequences(seed):
    """Random create/modify/delete sequences across rules and policies
    (including mid-list inserts and free-slot reuse): after EVERY
    mutation the patched tables canonically equal a from-scratch compile
    of the final tree, and kernel decisions equal the oracle."""
    rng = random.Random(seed)
    engine, ev, store, _cache = build_stack(n_rules=10, n_policies=2)
    next_id = [0]
    for step in range(14):
        _apply_random_op(rng, store, next_id)
        assert_tables_match_full_compile(engine, ev)
        if step % 4 == 3:
            assert_decisions_match_oracle(engine, ev, range(16))
    assert_decisions_match_oracle(engine, ev, range(16))
    stats = ev.delta_stats()
    assert stats["patches"] >= 5, stats  # the delta path actually engaged


def test_differential_fuzz_with_capacity_growth():
    """The same fuzz with bursts large enough to overflow KR/T buckets:
    full-recompile fallbacks interleave with patches and the tables stay
    canonically exact throughout."""
    rng = random.Random(7)
    engine, ev, store, _cache = build_stack(n_rules=6, n_policies=1)
    rule_svc = store.get_resource_service("rule")
    pol_svc = store.get_resource_service("policy")
    next_id = [1000]
    for burst in range(3):
        grow = ev._caps.KR  # guaranteed overflow of the current bucket
        docs = [rule_doc(f"g{next_id[0] + i}", i % 16,
                         effect=rng.choice(["PERMIT", "DENY"]))
                for i in range(grow)]
        next_id[0] += grow
        rule_svc.create(docs)
        p0 = store.collections["policy"].get("p0")
        p0["rules"] = p0["rules"] + [d["id"] for d in docs]
        pol_svc.update([p0])
        assert_tables_match_full_compile(engine, ev)
        for _ in range(3):
            _apply_random_op(rng, store, next_id)
            assert_tables_match_full_compile(engine, ev)
        assert_decisions_match_oracle(engine, ev, range(16))
    stats = ev.delta_stats()
    assert stats["fallbacks"] >= 1 and stats["patches"] >= 1, stats


@pytest.mark.slow
def test_churn_soak_serving_concurrent_with_mutations():
    """Sustained CRUD churn concurrent with serving: no exceptions, every
    decision matches a post-hoc oracle run, and the final tables equal a
    from-scratch compile."""
    engine, ev, store, cache = build_stack(n_rules=24, n_policies=3)
    stop = threading.Event()
    errors: list = []

    def mutate():
        rng = random.Random(3)
        next_id = [5000]
        while not stop.is_set():
            try:
                _apply_random_op(rng, store, next_id)
            except Exception as err:  # noqa: BLE001
                errors.append(err)
                return
            time.sleep(0.002)

    def serve():
        rng = random.Random(4)
        while not stop.is_set():
            keys = [rng.randrange(16) for _ in range(16)]
            try:
                out = ev.is_allowed_batch([make_request(k) for k in keys])
            except Exception as err:  # noqa: BLE001
                errors.append(err)
                return
            for resp in out:
                if resp.decision not in ("PERMIT", "DENY",
                                         "INDETERMINATE"):
                    errors.append(AssertionError(resp))
                    return

    threads = [threading.Thread(target=mutate)] + [
        threading.Thread(target=serve) for _ in range(2)
    ]
    for t in threads:
        t.start()
    time.sleep(4.0)
    stop.set()
    for t in threads:
        t.join(30)
    assert not errors, errors[:3]
    assert_tables_match_full_compile(engine, ev)
    assert_decisions_match_oracle(engine, ev, range(16))
    assert ev.delta_stats()["patches"] >= 5
