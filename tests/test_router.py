"""ClusterRouter unit/integration tests over IN-PROCESS replicas (two
Worker + GrpcServer pairs — no broker, no subprocesses, so these stay
fast): load balancing, drain semantics, breaker-backed failover, shed
migration, stream routing with mid-stream failover, and policy-epoch
trailer tracking.  The multi-process convergence story lives in
tests/test_cluster_chaos.py."""

import os
import threading
import time

import grpc
import pytest

from access_control_srv_tpu.srv import Worker
from access_control_srv_tpu.srv.gen import access_control_pb2 as pb
from access_control_srv_tpu.srv.router import (
    POLICY_EPOCH_METADATA_KEY,
    SHED_METADATA_KEY,
    ClusterRouter,
)
from access_control_srv_tpu.srv.transport_grpc import GrpcClient, GrpcServer

from .cluster_util import command_over, seed_paths, wire_request

pytestmark = pytest.mark.cluster


def _worker_cfg(**overrides):
    cfg = {
        "policies": {"type": "database"},
        "seed_data": seed_paths(),
    }
    cfg.update(overrides)
    return cfg


@pytest.fixture()
def replica_pair():
    workers, servers = [], []
    for _ in range(2):
        worker = Worker().start(_worker_cfg())
        server = GrpcServer(worker, "127.0.0.1:0").start()
        workers.append(worker)
        servers.append(server)
    router = ClusterRouter(
        [s.addr for s in servers],
        cfg={"health_interval_s": 0.2, "max_retries": 1},
    ).start()
    client = GrpcClient(router.addr)
    yield workers, servers, router, client
    client.close()
    router.stop()
    for server in servers:
        server.stop()
    for worker in workers:
        worker.stop()


class TestUnaryRouting:
    def test_decisions_and_load_balancing(self, replica_pair):
        workers, servers, router, client = replica_pair
        for _ in range(10):
            resp = client.is_allowed(wire_request())
            assert resp.operation_status.code == 200
            assert resp.decision == pb.PERMIT
        status = router.status()
        calls = {r["addr"]: r["calls"] for r in status["replicas"]}
        # least-inflight on sequential traffic alternates; both serve
        assert all(c > 0 for c in calls.values()), calls

    def test_epoch_trailer_tracked(self, replica_pair):
        workers, servers, router, client = replica_pair
        for _ in range(4):
            client.is_allowed(wire_request())
        status = router.status()
        # seeded single workers have no CRUD frames: epoch 0, stamped
        # on every response and observed by the router
        assert [r["policy_epoch"] for r in status["replicas"]] == [0, 0]
        assert status["converged"] is True

    def test_trailer_stamp_on_direct_replica(self, replica_pair):
        workers, servers, router, client = replica_pair
        direct = GrpcClient(servers[0].addr)
        try:
            fn = direct.channel.unary_unary(
                "/acstpu.AccessControlService/IsAllowed",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.Response.FromString,
            )
            resp, call = fn.with_call(wire_request())
            trailers = dict(call.trailing_metadata() or ())
            assert trailers.get(POLICY_EPOCH_METADATA_KEY) == "0"
            assert SHED_METADATA_KEY not in trailers
        finally:
            direct.close()

    def test_drain_and_undrain(self, replica_pair):
        workers, servers, router, client = replica_pair
        addr0 = servers[0].addr
        result = command_over(client.channel, "cluster_drain",
                              {"addr": addr0})
        assert result["status"] == "draining"
        before = {r["addr"]: r["calls"] for r in router.status()["replicas"]}
        for _ in range(6):
            assert client.is_allowed(
                wire_request()
            ).operation_status.code == 200
        after = {r["addr"]: r["calls"] for r in router.status()["replicas"]}
        assert after[addr0] == before[addr0]  # drained: no new calls
        assert after[servers[1].addr] == before[servers[1].addr] + 6
        result = command_over(client.channel, "cluster_undrain",
                              {"addr": addr0})
        assert result["status"] == "serving"

    def test_all_drained_is_unavailable(self, replica_pair):
        workers, servers, router, client = replica_pair
        command_over(client.channel, "cluster_drain", {})
        with pytest.raises(grpc.RpcError) as excinfo:
            client.is_allowed(wire_request())
        assert excinfo.value.code() == grpc.StatusCode.UNAVAILABLE
        command_over(client.channel, "cluster_undrain", {})

    def test_replica_failure_retries_on_other(self, replica_pair):
        workers, servers, router, client = replica_pair
        servers[0].stop(grace=0)
        # every call succeeds: calls picked for the dead replica fail
        # fast at transport and retry on the live one
        for _ in range(8):
            resp = client.is_allowed(wire_request())
            assert resp.operation_status.code == 200
        status = router.status()
        by = {r["addr"]: r for r in status["replicas"]}
        assert by[servers[1].addr]["calls"] >= 8 - by[
            servers[0].addr
        ]["failures"]
        # the health poll marks the dead replica unhealthy shortly
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            by = {r["addr"]: r for r in router.status()["replicas"]}
            if not by[servers[0].addr]["healthy"]:
                break
            time.sleep(0.1)
        assert not by[servers[0].addr]["healthy"]

    def test_command_forwarding(self, replica_pair):
        workers, servers, router, client = replica_pair
        health = command_over(client.channel, "health_check")
        assert health["status"] == "SERVING"
        assert health["policy_epoch"] == 0
        identity = command_over(client.channel, "program_identity")
        assert identity["table_fingerprint"]


class TestShedMigration:
    def test_shed_request_retries_on_other_replica(self):
        """Replica A sheds everything (admission queue bound 0); the
        router must migrate the request to replica B instead of
        surfacing A's 429."""
        worker_a = Worker().start(_worker_cfg(
            admission={"enabled": True, "max_queue_interactive": 0,
                       "max_queue_bulk": 0},
        ))
        worker_b = Worker().start(_worker_cfg())
        server_a = GrpcServer(worker_a, "127.0.0.1:0").start()
        server_b = GrpcServer(worker_b, "127.0.0.1:0").start()
        router = ClusterRouter(
            [server_a.addr, server_b.addr],
            cfg={"health_interval_s": 0.5, "max_retries": 1},
        ).start()
        client = GrpcClient(router.addr)
        try:
            # drain B so the first attempt must land on the shedding A
            router.set_drain(server_b.addr, True)
            direct = GrpcClient(server_a.addr)
            shed = direct.is_allowed(wire_request())
            assert shed.operation_status.code == 429
            direct.close()
            router.set_drain(server_b.addr, False)
            router.set_drain(server_a.addr, False)
            # through the router: A sheds with the x-acs-shed trailer,
            # the router retries on B and the caller sees a decision
            ok = 0
            for _ in range(6):
                resp = client.is_allowed(wire_request())
                if resp.operation_status.code == 200:
                    ok += 1
            assert ok == 6
            by = {r["addr"]: r for r in router.status()["replicas"]}
            assert by[server_a.addr]["sheds"] >= 1
            assert by[server_b.addr]["retries_absorbed"] >= 1
        finally:
            client.close()
            router.stop()
            server_a.stop()
            server_b.stop()
            worker_a.stop()
            worker_b.stop()

    def test_all_replicas_shedding_returns_honest_shed(self):
        """When every replica sheds, the caller gets the shed response
        (429) — never a fabricated decision, never a transport error."""
        workers = [
            Worker().start(_worker_cfg(
                admission={"enabled": True, "max_queue_interactive": 0,
                           "max_queue_bulk": 0},
            ))
            for _ in range(2)
        ]
        servers = [GrpcServer(w, "127.0.0.1:0").start() for w in workers]
        router = ClusterRouter(
            [s.addr for s in servers], cfg={"max_retries": 1},
        ).start()
        client = GrpcClient(router.addr)
        try:
            resp = client.is_allowed(wire_request())
            assert resp.operation_status.code == 429
        finally:
            client.close()
            router.stop()
            for s in servers:
                s.stop()
            for w in workers:
                w.stop()


class TestStreamRouting:
    def test_stream_through_router(self, replica_pair):
        workers, servers, router, client = replica_pair
        frames = [
            pb.BatchRequest(requests=[wire_request(), wire_request()])
            for _ in range(4)
        ]
        responses = list(client.is_allowed_stream(iter(frames), timeout=60))
        assert len(responses) == 4
        for frame in responses:
            assert len(frame.responses) == 2
            assert all(
                r.operation_status.code == 200 for r in frame.responses
            )

    def test_stream_failover_replays_unanswered_tail(self, replica_pair):
        """Kill the replica serving a stream between frames: the router
        replays the unanswered frames on the other replica and the
        client sees every response, in order, with no error."""
        workers, servers, router, client = replica_pair
        # pin the stream to replica 0
        router.set_drain(servers[1].addr, True)

        import queue

        frame_q: "queue.Queue" = queue.Queue()
        results: list = []
        errors: list = []

        def gen():
            while True:
                item = frame_q.get()
                if item is None:
                    return
                yield item

        def consume():
            try:
                for resp in client.is_allowed_stream(gen(), timeout=120):
                    results.append(resp)
            except BaseException as err:  # noqa: BLE001
                errors.append(err)

        consumer = threading.Thread(target=consume, daemon=True)
        consumer.start()
        frame = pb.BatchRequest(requests=[wire_request()])
        frame_q.put(frame)
        deadline = time.monotonic() + 30
        while not results and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(results) == 1  # stream is live on replica 0
        # open the fallback path, then kill the serving replica
        router.set_drain(servers[1].addr, False)
        servers[0].stop(grace=0)
        for _ in range(3):
            frame_q.put(frame)
        frame_q.put(None)
        consumer.join(timeout=60)
        assert not consumer.is_alive()
        assert not errors, errors
        assert len(results) == 4
        for resp in results:
            assert resp.responses[0].operation_status.code == 200


class TestLocalClusterCli:
    def test_router_cli_mode(self):
        """--router over one in-process replica: the CLI binds, reports
        its address and proxies traffic."""
        import subprocess
        import sys

        worker = Worker().start(_worker_cfg())
        server = GrpcServer(worker, "127.0.0.1:0").start()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.Popen(
            [sys.executable, "-m", "access_control_srv_tpu", "--router",
             "--replica", server.addr, "--addr", "127.0.0.1:0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo,
        )
        try:
            line = proc.stdout.readline()
            assert line.startswith("routing on "), line
            addr = line.split("routing on ", 1)[1].strip()
            client = GrpcClient(addr)
            resp = client.is_allowed(wire_request())
            assert resp.operation_status.code == 200
            client.close()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()
                proc.wait(timeout=10)
            server.stop()
            worker.stop()
