"""Zanzibar-style relationship-tuple store: the serving-side ReBAC
substrate.

``core/relation_path.py`` is the deliberately naive scalar oracle; this
module is the production store the evaluator serves from:

- an in-memory :class:`~..core.relation_path.RelationGraph` holding
  ``object#relation@subject`` tuples and userset-rewrite configs, mutated
  through a CRUD surface that journals every change;
- a **memoized closure cache** with a dependency index: every cached
  (path, object) reachable-user set records exactly which graph nodes
  ``(ns, oid, rel)`` and rewrite configs ``(ns, rel)`` its expansion
  consulted, so a tuple write invalidates ONLY the closure entries whose
  traversal touched the mutated node — the rest of the cache (and the
  flat tables built from it) survives churn untouched;
- ``tables_for(compiled)``: the flat verdict tables
  (ops/relation.pack_relation_bitplanes) in the compiled tree's
  relation-vocab order — two sorted int64 arrays + an offset table, so a
  batch verdict is two binary searches.  Rebuilt lazily per store
  generation from the (mostly cached) closure sets; identical to a
  from-scratch build by construction, which the differential suite
  asserts (tests/test_relations.py);
- **replication**: every mutation emits a CrudEvent-style frame
  (``origin``-stamped, ``tenant``-taggable) on a broker topic — the same
  journaled CRC-framed log policy CRUD rides (srv/broker.py), so tuple
  state inherits the broker's torn-tail truncation, snapshotting and
  journal compaction for free.  Peers replay the topic at boot and apply
  live frames from OTHER origins (PolicyReplicator's origin-skip
  discipline), converging to byte-identical ``fingerprint()``s;
- ``witness()``: the tuple-path provenance behind a relation-decided
  row, surfaced by explain mode (srv/explain.py).

Tuple churn never touches the compiled policy tensors: the kernel
consumes relations only through the per-batch bitplanes packed from
these tables, so an in-capacity tuple write costs a scoped closure
invalidation + a decision-cache bump — zero new XLA compilations
(tpu_compat_audit rebac-zero-matmul-program-identity).
"""

from __future__ import annotations

import threading
import uuid
from hashlib import blake2b
from typing import Callable, Optional

import numpy as np

from ..core.relation_path import (
    RelationGraph,
    _reach_objects,
    _reach_users,
    normalize_rule,
    normalize_subject,
    parse_path,
    OBJECT,
    USER,
    USERSET,
)

# the broker topic relation-tuple CRUD frames ride (the policy-CRUD
# topics are io.restorecommerce.{rules,policies,policy_sets}.resource)
RELATION_TOPIC = "io.restorecommerce.relation-tuples.resource"


def _subject_wire(norm: tuple):
    """Normalized subject -> wire/journal form."""
    if norm[0] == USER:
        return norm[1]
    out = {"object": {"entity": norm[1], "id": norm[2]}}
    if norm[0] == USERSET:
        out["relation"] = norm[3]
    return out


def tuple_doc(namespace: str, object_id: str, relation: str, subject
              ) -> dict:
    """Canonical wire doc for one relation tuple."""
    return {
        "object": {"entity": namespace, "id": object_id},
        "relation": relation,
        "subject": _subject_wire(normalize_subject(subject)),
    }


def _tuple_from_doc(doc: dict) -> tuple:
    obj = doc["object"]
    return (str(obj["entity"]), str(obj["id"]), str(doc["relation"]),
            normalize_subject(doc["subject"]))


class _RecordingGraph:
    """Duck-typed RelationGraph view that records every node and rewrite
    the traversal consults — the dependency set of one closure entry.
    Sound for incremental invalidation because _reach_users/_reach_objects
    read the graph ONLY through these two methods: a mutation at a node
    no entry consulted cannot change that entry's result."""

    __slots__ = ("_g", "node_deps", "rule_deps")

    def __init__(self, graph: RelationGraph):
        self._g = graph
        self.node_deps: set = set()
        self.rule_deps: set = set()

    def subjects_of(self, ns, oid, rel):
        self.node_deps.add((ns, oid, rel))
        return self._g.subjects_of(ns, oid, rel)

    def rules_of(self, ns, rel):
        self.rule_deps.add((ns, rel))
        return self._g.rules_of(ns, rel)


def _path_users(graph, alts, ns: str, oid: str, direct: bool) -> set:
    """Users reaching (ns, oid) through any alternative — the set-valued
    form of core.relation_path.check_relation_path (subject in result
    <=> check passes), shared by the closure cache and the tables."""
    out: set[str] = set()
    for alt in alts:
        frontier = {(ns, oid)}
        for step in alt[:-1]:
            visited: set = set()
            nxt: set = set()
            for n, o in frontier:
                nxt |= _reach_objects(graph, n, o, step, direct, visited)
            frontier = nxt
            if not frontier:
                break
        if not frontier:
            continue
        visited = set()
        for n, o in frontier:
            out |= _reach_users(graph, n, o, alt[-1], direct, visited)
    return out


class RelationTupleStore:
    """The serving tuple store; attach as ``engine.relation_store`` (the
    oracle reads ``.graph``) and the evaluator pulls ``tables_for`` at
    encode time.

    ``bus``: optional EventBus (in-process srv/events.py or broker-backed
    srv/broker.SocketEventBus) — mutations emit journal frames on
    ``topic`` and :meth:`start_replication` applies remote peers' frames.
    ``tenant``: stamps frames with a tenant tag; a store only applies
    frames whose tag matches its own (tenant isolation on a shared log).
    """

    def __init__(self, bus=None, topic: str = RELATION_TOPIC,
                 tenant: Optional[str] = None, logger=None,
                 telemetry=None):
        self._graph = RelationGraph()
        self._lock = threading.RLock()
        self.origin = uuid.uuid4().hex
        self.tenant = tenant
        self.logger = logger
        self.telemetry = telemetry
        self._gen = 0
        self._stopped = False
        # closure cache: (alts, direct, ns, oid) -> frozenset(users)
        self._memo: dict = {}
        self._entry_deps: dict = {}   # memo key -> (node deps, rule deps)
        self._node_index: dict = {}   # (ns, oid, rel) -> {memo keys}
        self._rule_index: dict = {}   # (ns, rel) -> {memo keys}
        self._invalidated = 0         # lifetime scoped-invalidation count
        self._tables_cache: dict = {}  # id space -> (sig, tables)
        self._fp_cache: Optional[tuple] = None      # (gen, hexdigest)
        self._listeners: list[Callable[[int], None]] = []
        self._topic = bus.topic(topic) if bus is not None else None
        self._bus = bus

    # ------------------------------------------------------------- oracle
    @property
    def graph(self) -> RelationGraph:
        return self._graph

    def on_change(self, callback: Callable[[int], None]) -> None:
        """Register a change listener, called with the new generation
        after every applied mutation (local or replicated) — the
        evaluator's decision-cache bump rides this."""
        self._listeners.append(callback)

    def _notify(self, gen: int) -> None:
        for callback in list(self._listeners):
            try:
                callback(gen)
            except Exception:  # noqa: BLE001 — listeners must not kill CRUD
                if self.logger:
                    self.logger.exception("relation change listener failed")

    # --------------------------------------------------------------- CRUD
    def create(self, tuples: list[dict]) -> int:
        """Insert tuples (wire docs or (ns, oid, rel, subject) 4-tuples);
        returns how many were new.  Emits one journal frame per applied
        tuple."""
        applied = 0
        for item in tuples:
            ns, oid, rel, subj = self._coerce(item)
            with self._lock:
                if not self._graph.add(ns, oid, rel, subj):
                    continue
                self._invalidate_node((ns, oid, rel))
                gen = self._bump()
            applied += 1
            self._count("tuples_created")
            self._emit("relationTupleCreated",
                       tuple_doc(ns, oid, rel, subj))
            self._notify(gen)
        return applied

    def delete(self, tuples: list[dict]) -> int:
        """Remove tuples; returns how many existed."""
        applied = 0
        for item in tuples:
            ns, oid, rel, subj = self._coerce(item)
            with self._lock:
                if not self._graph.remove(ns, oid, rel, subj):
                    continue
                self._invalidate_node((ns, oid, rel))
                gen = self._bump()
            applied += 1
            self._count("tuples_deleted")
            self._emit("relationTupleDeleted",
                       tuple_doc(ns, oid, rel, subj))
            self._notify(gen)
        return applied

    def set_rewrite(self, namespace: str, relation: str, rules) -> None:
        """Install the userset-rewrite config for (namespace, relation) —
        e.g. ``[("this",), ("computed_userset", "owner")]``."""
        normalized = [normalize_rule(r) for r in rules]
        with self._lock:
            self._graph.set_rewrite(namespace, relation, normalized)
            self._invalidate_rule((namespace, relation))
            gen = self._bump()
        self._count("rewrites_modified")
        self._emit("relationRewriteModified", {
            "namespace": namespace, "relation": relation,
            "rules": [list(r) for r in normalized],
        })
        self._notify(gen)

    @staticmethod
    def _coerce(item) -> tuple:
        if isinstance(item, dict):
            return _tuple_from_doc(item)
        ns, oid, rel, subj = item
        return (ns, oid, rel, normalize_subject(subj))

    def _bump(self) -> int:  # holds: _lock
        self._gen += 1
        self._tables_cache.clear()
        self._fp_cache = None
        return self._gen

    def _count(self, key: str) -> None:
        if self.telemetry is not None:
            self.telemetry.relations.inc(key)

    # -------------------------------------------------- closure cache
    def _invalidate_node(self, node: tuple) -> None:  # holds: _lock
        for key in self._node_index.pop(node, set()):
            self._drop_entry(key)
            self._invalidated += 1

    def _invalidate_rule(self, rule_key: tuple) -> None:  # holds: _lock
        for key in self._rule_index.pop(rule_key, set()):
            self._drop_entry(key)
            self._invalidated += 1

    def _drop_entry(self, key: tuple) -> None:  # holds: _lock
        self._memo.pop(key, None)
        node_deps, rule_deps = self._entry_deps.pop(key, ((), ()))
        for node in node_deps:
            bucket = self._node_index.get(node)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._node_index[node]
        for rk in rule_deps:
            bucket = self._rule_index.get(rk)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._rule_index[rk]

    def _users(self, alts: tuple, direct: bool, ns: str, oid: str
               ) -> frozenset:  # holds: _lock
        key = (alts, direct, ns, oid)
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        recorder = _RecordingGraph(self._graph)
        out = frozenset(_path_users(recorder, alts, ns, oid, direct))
        self._memo[key] = out
        deps = (frozenset(recorder.node_deps),
                frozenset(recorder.rule_deps))
        self._entry_deps[key] = deps
        for node in deps[0]:
            self._node_index.setdefault(node, set()).add(key)
        for rk in deps[1]:
            self._rule_index.setdefault(rk, set()).add(key)
        return out

    # ------------------------------------------------------- flat tables
    def tables_for(self, compiled, intern=None, space: str = "host"
                   ) -> dict[str, np.ndarray]:
        """The flat verdict tables for ``compiled``'s relation vocab
        (padded entries get empty segments — fail-closed, and no target
        row references them).  Cached per (generation, vocab, id space);
        the closure sets underneath are cached much longer
        (dependency-scoped invalidation), so steady-state churn rebuilds
        only the sort/pack of segments whose closures actually changed
        inputs.

        ``intern`` overrides the string->id mapping (default: the
        compiled tree's interner).  The native wire encoder passes its
        C++ interner here (``space="native"``) — its post-preload ids can
        diverge from the Python interner's, so the tables must be built
        in the id space of whichever encoder consumes them."""
        relv = int(np.asarray(compiled.arrays["relv_path"]).shape[0])
        vocab = list(compiled.rel_vocab)
        sig = (self._gen, relv, tuple(vocab), space)
        with self._lock:
            cached = self._tables_cache.get(space)
            if cached is not None and cached[0] == sig:
                return cached[1]
            if intern is None:
                intern = compiled.interner.intern
            candidates = sorted({
                (ns, oid) for (ns, oid, _rel) in self._graph.tuples
            })
            obj_offs = np.zeros((2 * relv + 1,), np.int64)
            keys_out: list[int] = []
            pairs_out: list[int] = []
            for v in range(relv):
                path = None
                if v < len(vocab):
                    try:
                        path = parse_path(vocab[v])
                    except ValueError:
                        path = None
                for plane in range(2):
                    idx = v * 2 + plane
                    if path is not None:
                        seg = []
                        for ns, oid in candidates:
                            users = self._users(
                                path.alts, plane == 1, ns, oid
                            )
                            if users:
                                key = (
                                    (np.int64(intern(ns)) << 32)
                                    | np.int64(intern(oid))
                                )
                                seg.append((int(key), users))
                        seg.sort(key=lambda kv: kv[0])
                        for key, users in seg:
                            row = len(keys_out)
                            keys_out.append(key)
                            for sid in sorted(intern(u) for u in users):
                                pairs_out.append((row << 32) | sid)
                    obj_offs[idx + 1] = len(keys_out)
            tables = {
                "obj_offs": obj_offs,
                "obj_keys": np.array(keys_out, np.int64),
                "pairs": np.array(pairs_out, np.int64),
            }
            self._tables_cache[space] = (sig, tables)
            return tables

    # ------------------------------------------------------- replication
    def _emit(self, event_name: str, payload: dict) -> None:
        if self._topic is None:
            return
        message = {"payload": payload, "origin": self.origin}
        if self.tenant is not None:
            message["tenant"] = self.tenant
        try:
            self._topic.emit(event_name, message)
        except Exception:  # noqa: BLE001 — the local write already landed
            if self.logger:
                self.logger.exception("relation frame emit failed")

    def replay(self) -> int:
        """Boot replay: apply the full topic log (idempotent adds/removes
        converge to the log's final state).  Returns frames applied."""
        if self._topic is None:
            return 0
        applied = 0
        for event_name, message in self._topic.read(0):
            if self._apply_frame(event_name, message):
                applied += 1
        return applied

    def start_replication(self) -> "RelationTupleStore":
        """Subscribe live (after :meth:`replay`): frames from OTHER
        origins apply to the local graph; own frames were applied at CRUD
        time and are skipped."""
        if self._topic is not None:
            self._topic.on(self._on_event,
                           starting_offset=self._topic.offset)
        return self

    def _on_event(self, event_name: str, message, ctx: dict) -> None:
        if self._stopped:
            return
        if self._apply_frame(event_name, message):
            self._count("frames_replicated")

    def _apply_frame(self, event_name: str, message) -> bool:
        """One journal frame -> local mutation; False for own-origin,
        other-tenant, or malformed frames (all skipped, never fatal)."""
        if not isinstance(message, dict):
            return False
        if message.get("origin") == self.origin:
            return False
        if message.get("tenant") != self.tenant:
            return False  # another tenant's tuples: isolation on a shared log
        payload = message.get("payload")
        if not isinstance(payload, dict):
            return False
        try:
            if event_name == "relationTupleCreated":
                ns, oid, rel, subj = _tuple_from_doc(payload)
                with self._lock:
                    if not self._graph.add(ns, oid, rel, subj):
                        return False
                    self._invalidate_node((ns, oid, rel))
                    gen = self._bump()
            elif event_name == "relationTupleDeleted":
                ns, oid, rel, subj = _tuple_from_doc(payload)
                with self._lock:
                    if not self._graph.remove(ns, oid, rel, subj):
                        return False
                    self._invalidate_node((ns, oid, rel))
                    gen = self._bump()
            elif event_name == "relationRewriteModified":
                ns = str(payload["namespace"])
                rel = str(payload["relation"])
                rules = [normalize_rule(r) for r in payload["rules"]]
                with self._lock:
                    self._graph.set_rewrite(ns, rel, rules)
                    self._invalidate_rule((ns, rel))
                    gen = self._bump()
            else:
                return False
        except (KeyError, TypeError, ValueError):
            if self.logger:
                self.logger.warning(
                    "malformed relation frame skipped",
                    extra={"event": event_name},
                )
            return False
        self._notify(gen)
        return True

    def stop(self) -> None:
        self._stopped = True

    # ------------------------------------------------------ observability
    def fingerprint(self) -> str:
        """Digest of the full tuple/rewrite state: two replicas that
        applied the same journal converge to equal fingerprints (the
        relation analog of evaluator.table_fingerprint, which folds this
        in when a store is attached)."""
        with self._lock:
            cached = self._fp_cache
            if cached is not None and cached[0] == self._gen:
                return cached[1]
            h = blake2b(digest_size=16)
            for key in sorted(self._graph.tuples):
                for subj in sorted(self._graph.tuples[key]):
                    h.update(repr((key, subj)).encode())
            for rk in sorted(self._graph.rewrites):
                h.update(repr((rk, self._graph.rewrites[rk])).encode())
            out = h.hexdigest()
            self._fp_cache = (self._gen, out)
            return out

    @property
    def generation(self) -> int:
        return self._gen

    def stats(self) -> dict:
        with self._lock:
            return {
                "tuples": sum(
                    len(b) for b in self._graph.tuples.values()
                ),
                "rewrites": len(self._graph.rewrites),
                "generation": self._gen,
                "closure_entries": len(self._memo),
                "closure_invalidated": self._invalidated,
                "fingerprint": self.fingerprint(),
            }

    def check(self, expr: str, namespace: str, object_id: str,
              subject_id: str) -> bool:
        """One cached-closure verdict (the API-level check endpoint);
        bit-identical to core.relation_path.check_relation_path."""
        path = parse_path(expr)
        with self._lock:
            return subject_id in self._users(
                path.alts, path.direct, namespace, object_id
            )

    def witness(self, expr: str, namespace: str, object_id: str,
                subject_id: str) -> Optional[list[str]]:
        """The tuple-path provenance for a passing relation check: a
        human-readable hop list from the object to the subject, or None
        when the check fails.  Explain mode attaches this to
        relation-decided rows (srv/explain.py)."""
        try:
            path = parse_path(expr)
        except ValueError:
            return None
        with self._lock:
            graph = self._graph
            for alt in path.alts:
                frontier: dict[tuple, list[str]] = {
                    (namespace, object_id): []
                }
                for step in alt[:-1]:
                    nxt: dict[tuple, list[str]] = {}
                    for (n, o), hops in frontier.items():
                        visited: set = set()
                        for tgt in _reach_objects(
                            graph, n, o, step, path.direct, visited
                        ):
                            if tgt not in nxt:
                                nxt[tgt] = hops + [
                                    f"{n}:{o}#{step} -> {tgt[0]}:{tgt[1]}"
                                ]
                    frontier = nxt
                    if not frontier:
                        break
                if not frontier:
                    continue
                last = alt[-1]
                for (n, o), hops in frontier.items():
                    visited = set()
                    if subject_id in _reach_users(
                        graph, n, o, last, path.direct, visited
                    ):
                        return hops + [f"{n}:{o}#{last}@{subject_id}"]
        return None
