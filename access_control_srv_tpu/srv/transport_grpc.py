"""gRPC transport: protobuf services over the worker.

Exposes the same five-service surface as the reference
(reference: src/worker.ts:161-194 binds access-control, rule / policy /
policy_set CRUD, command interface and health): protobuf messages are
compiled from proto/access_control.proto; service registration uses
generic method handlers (this image ships protoc but not the gRPC python
stub generator).  ``IsAllowedBatch`` is the framework extension feeding
the batched TPU evaluation path directly.
"""

from __future__ import annotations

import json
import time
from concurrent import futures
from typing import Optional

import grpc

from ..models.model import (
    Attribute,
    ContextQuery,
    Decision,
    Request,
    Response,
    ReverseQuery,
    Target,
)
from ..ops.compile import DECISION_NAMES
from .admission import deadline_from_context, tenant_from_metadata
from .gen import access_control_pb2 as pb
from .tracing import (
    STAGE_DECODE,
    STAGE_ORACLE,
    STAGE_SERIALIZE,
    STAGE_TRANSPORT_PARSE,
    TRACE_ID_METADATA_KEY,
    trace_id_from_metadata,
)


def split_batch_request(data: bytes) -> Optional[list[bytes]]:
    """Split a serialized BatchRequest envelope (field 1: repeated Request)
    into per-request message bytes without protobuf deserialization.
    Returns None on any unexpected field (caller falls back to pb)."""
    messages: list[bytes] = []
    i, n = 0, len(data)
    while i < n:
        key = 0
        shift = 0
        while True:
            if i >= n:
                return None
            byte = data[i]
            i += 1
            key |= (byte & 0x7F) << shift
            shift += 7
            if not byte & 0x80:
                break
        if key >> 3 != 1 or key & 7 != 2:
            return None
        length = 0
        shift = 0
        while True:
            if i >= n:
                return None
            byte = data[i]
            i += 1
            length |= (byte & 0x7F) << shift
            shift += 7
            if not byte & 0x80:
                break
        if i + length > n:
            return None
        messages.append(data[i:i + length])
        i += length
    return messages


DECISION_TO_PB = {
    Decision.PERMIT: pb.PERMIT,
    Decision.DENY: pb.DENY,
    Decision.INDETERMINATE: pb.INDETERMINATE,
}
PB_TO_DECISION = {v: k for k, v in DECISION_TO_PB.items()}


# ------------------------------------------------------------- converters

def attr_to_pb(attr: Attribute) -> pb.Attribute:
    return pb.Attribute(
        id=attr.id or "",
        value=attr.value or "",
        attributes=[attr_to_pb(a) for a in attr.attributes or []],
    )


def attr_from_pb(msg: pb.Attribute) -> Attribute:
    return Attribute(
        id=msg.id,
        value=msg.value,
        attributes=[attr_from_pb(a) for a in msg.attributes],
    )


def target_to_pb(target: Optional[Target]) -> Optional[pb.Target]:
    if target is None:
        return None
    return pb.Target(
        subjects=[attr_to_pb(a) for a in target.subjects],
        resources=[attr_to_pb(a) for a in target.resources],
        actions=[attr_to_pb(a) for a in target.actions],
    )


def target_from_pb(msg: Optional[pb.Target]) -> Optional[Target]:
    if msg is None:
        return None
    return Target(
        subjects=[attr_from_pb(a) for a in msg.subjects],
        resources=[attr_from_pb(a) for a in msg.resources],
        actions=[attr_from_pb(a) for a in msg.actions],
    )


def _ctx_value_from_pb(msg: pb.ContextValue):
    if not msg.value:
        return None
    return {"type_url": msg.type_url, "value": bytes(msg.value)}


def request_from_pb(msg: pb.Request) -> Request:
    context = None
    if msg.HasField("context"):
        context = {}
        if msg.context.HasField("subject"):
            context["subject"] = _ctx_value_from_pb(msg.context.subject)
        context["resources"] = [
            _ctx_value_from_pb(r) for r in msg.context.resources
        ]
        if msg.context.HasField("security"):
            context["security"] = _ctx_value_from_pb(msg.context.security)
    target = target_from_pb(msg.target) if msg.HasField("target") else None
    return Request(target=target, context=context)


def request_to_pb(request: Request) -> pb.Request:
    msg = pb.Request()
    if request.target is not None:
        msg.target.CopyFrom(target_to_pb(request.target))
    context = request.context
    if context is not None:
        subject = context.get("subject")
        if subject is not None:
            msg.context.subject.value = json.dumps(subject).encode()
        for res in context.get("resources") or []:
            entry = msg.context.resources.add()
            entry.value = json.dumps(res).encode()
        security = context.get("security")
        if security is not None:
            msg.context.security.value = json.dumps(security).encode()
    return msg


def response_to_pb(response: Response) -> pb.Response:
    return pb.Response(
        decision=DECISION_TO_PB.get(response.decision, pb.INDETERMINATE),
        obligations=[attr_to_pb(a) for a in response.obligations or []],
        evaluation_cacheable=bool(response.evaluation_cacheable),
        operation_status=pb.OperationStatus(
            code=response.operation_status.code,
            message=response.operation_status.message,
        ),
    )


def reverse_query_to_pb(rq: ReverseQuery) -> pb.ReverseQuery:
    out = pb.ReverseQuery(
        obligations=[attr_to_pb(a) for a in rq.obligations or []],
        operation_status=pb.OperationStatus(
            code=rq.operation_status.code, message=rq.operation_status.message
        ),
    )
    for ps in rq.policy_sets:
        ps_msg = out.policy_sets.add(
            id=ps.id or "",
            effect=ps.effect or "",
            combining_algorithm=ps.combining_algorithm or "",
        )
        if ps.target is not None:
            ps_msg.target.CopyFrom(target_to_pb(ps.target))
        for pol in ps.policies:
            p_msg = ps_msg.policies.add(
                id=pol.id or "",
                effect=pol.effect or "",
                combining_algorithm=pol.combining_algorithm or "",
                evaluation_cacheable=bool(pol.evaluation_cacheable),
                has_rules=bool(pol.has_rules),
            )
            if pol.target is not None:
                p_msg.target.CopyFrom(target_to_pb(pol.target))
            for rule in pol.rules:
                r_msg = p_msg.rules.add(
                    id=rule.id or "",
                    effect=rule.effect or "",
                    condition=rule.condition or "",
                    evaluation_cacheable=bool(rule.evaluation_cacheable),
                )
                if rule.target is not None:
                    r_msg.target.CopyFrom(target_to_pb(rule.target))
                if rule.context_query is not None:
                    r_msg.context_query.query = rule.context_query.query or ""
                    for f in rule.context_query.filters or []:
                        r_msg.context_query.filters.add(
                            field=str(f.get("field") or ""),
                            operation=str(f.get("operation") or ""),
                            value=str(f.get("value") or ""),
                        )
    return out


def _meta_to_dict(msg: pb.Meta) -> dict:
    out = {
        "owners": [_attr_dict(a) for a in msg.owners],
        "acls": [_attr_dict(a) for a in msg.acls],
    }
    if msg.created:
        out["created"] = msg.created
    if msg.modified:
        out["modified"] = msg.modified
    return out


def _attr_dict(msg: pb.Attribute) -> dict:
    return {
        "id": msg.id,
        "value": msg.value,
        "attributes": [_attr_dict(a) for a in msg.attributes],
    }


def _target_dict(msg: pb.Target) -> dict:
    return {
        "subjects": [_attr_dict(a) for a in msg.subjects],
        "resources": [_attr_dict(a) for a in msg.resources],
        "actions": [_attr_dict(a) for a in msg.actions],
    }


def rule_doc_from_pb(msg: pb.Rule) -> dict:
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "effect": msg.effect or None,
        "condition": msg.condition,
        "evaluation_cacheable": msg.evaluation_cacheable,
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict(msg.target)
    if msg.HasField("context_query"):
        doc["context_query"] = {
            "query": msg.context_query.query,
            "filters": [
                {"field": f.field, "operation": f.operation, "value": f.value}
                for f in msg.context_query.filters
            ],
        }
    if msg.HasField("meta"):
        doc["meta"] = _meta_to_dict(msg.meta)
    return doc


def policy_doc_from_pb(msg: pb.Policy) -> dict:
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "effect": msg.effect or None,
        "combining_algorithm": msg.combining_algorithm,
        "rules": list(msg.rules),
        "evaluation_cacheable": msg.evaluation_cacheable,
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict(msg.target)
    if msg.HasField("meta"):
        doc["meta"] = _meta_to_dict(msg.meta)
    return doc


def policy_set_doc_from_pb(msg: pb.PolicySet) -> dict:
    doc = {
        "id": msg.id,
        "name": msg.name,
        "description": msg.description,
        "combining_algorithm": msg.combining_algorithm,
        "policies": list(msg.policies),
    }
    if msg.HasField("target"):
        doc["target"] = _target_dict(msg.target)
    if msg.HasField("meta"):
        doc["meta"] = _meta_to_dict(msg.meta)
    return doc


def _subject_from_pb(msg: pb.Subject) -> Optional[dict]:
    if not (msg.id or msg.token or msg.scope or msg.data):
        return None
    subject = {"id": msg.id or None, "token": msg.token or None,
               "scope": msg.scope or None}
    if msg.data:
        subject.update(json.loads(msg.data))
    return subject


# ----------------------------------------------------------------- server

# batched envelopes exceed gRPC's 4 MB default well before the batcher's
# max_batch (an 8192-row BatchRequest is ~3.9 MB); 64 MB covers the
# largest configured batch with headroom
_MESSAGE_SIZE_OPTIONS = (
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
    ("grpc.max_send_message_length", 64 * 1024 * 1024),
)

# batch-reply serialization runs on this shared pool, NOT the gRPC
# dispatch thread: an 8192-row BatchResponse costs ~8 ms of protobuf
# SerializeToString, which previously serialized the whole envelope on the
# handler thread after the kernel was already done (part of the
# wire-to-wire gap vs kernel-only throughput).  Chunks serialize
# concurrently and the dispatch thread only joins the length-delimited
# frames — BatchResponse is `repeated Response responses = 1`, so the
# frame concatenation IS the envelope encoding.
_SER_POOL = futures.ThreadPoolExecutor(
    max_workers=4, thread_name_prefix="acs-pb-ser"
)
_SER_CHUNK = 512


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n & 0x7F
        n >>= 7
        if n:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _response_frames(chunk: list) -> bytes:
    parts = []
    for resp in chunk:
        body = resp.SerializeToString()
        parts.append(b"\x0a" + _varint(len(body)) + body)
    return b"".join(parts)


def serialize_batch_response(responses: list) -> bytes:
    """BatchResponse wire bytes from per-row pb.Response messages; chunked
    across the serializer pool for large batches (identical bytes to
    ``pb.BatchResponse(responses=...).SerializeToString()`` — asserted by
    tests/test_grpc_transport.py)."""
    if len(responses) <= _SER_CHUNK:
        return _response_frames(responses)
    chunks = [
        responses[i:i + _SER_CHUNK]
        for i in range(0, len(responses), _SER_CHUNK)
    ]
    return b"".join(_SER_POOL.map(_response_frames, chunks))


def decode_native_rows(messages: list[bytes], out) -> tuple:
    """Per-row pb.Response assembly from a native wire result
    ``(batch, decision, cacheable, status)``.  Ineligible / non-200 rows
    are parsed back to Request models and returned for ONE batched
    fallback call (resolve_fallback_rows) instead of per-row service
    round-trips.  Shared by the unary IsAllowedBatch handler and the
    streaming pipeline (srv/pipeline.py)."""
    batch, decision, cacheable, status = out
    responses: list = [None] * len(messages)
    fallback_rows: list[int] = []
    fallback_reqs: list = []
    for b, message in enumerate(messages):
        if not batch.eligible[b] or status[b] != 200:
            try:
                req = request_from_pb(pb.Request.FromString(message))
            except Exception as err:
                responses[b] = pb.Response(
                    decision=pb.DENY,
                    operation_status=pb.OperationStatus(
                        code=500, message=str(err)
                    ),
                )
                continue
            fallback_rows.append(b)
            fallback_reqs.append(req)
            continue
        cach = (
            False if cacheable[b] < 0 else bool(cacheable[b])
        )
        responses[b] = pb.Response(
            decision=DECISION_TO_PB[
                DECISION_NAMES[int(decision[b])]
            ],
            evaluation_cacheable=cach,
            operation_status=pb.OperationStatus(
                code=200, message="success"
            ),
        )
    return responses, fallback_rows, fallback_reqs


def resolve_fallback_rows(worker, responses: list, fallback_rows: list,
                          fallback_reqs: list, deadline, span=None) -> None:
    """Resolve the rows decode_native_rows could not serve natively with
    one batched service call (observe=False: the caller records
    batch-level telemetry for ALL rows itself)."""
    if not fallback_reqs:
        return
    if span is not None:
        for req in fallback_reqs:
            req._span = span
            req._sampling_done = True
    for b, resp in zip(
        fallback_rows,
        worker.service.is_allowed_batch(
            fallback_reqs, observe=False, deadline=deadline,
        ),
    ):
        responses[b] = response_to_pb(resp)


POLICY_EPOCH_METADATA_KEY = "x-acs-policy-epoch"
SHED_METADATA_KEY = "x-acs-shed"
EXPLAIN_METADATA_KEY = "x-acs-explain"
# admission-control shed statuses (srv/admission.py): 429 queue-full,
# 503 breaker-open, 504 deadline-infeasible
SHED_CODES = frozenset((429, 503, 504))


def explain_trailer(response) -> Optional[str]:
    """Compact JSON of the deciding-node provenance (srv/explain.py)
    when explain mode stamped the response, else None.  The
    io.restorecommerce Response proto has no provenance field, so the
    wire surface is a trailer — additive metadata keeps the response
    bytes identical for every consumer that doesn't opt in."""
    info = getattr(response, "_explain", None)
    if info is None:
        return None
    try:
        return json.dumps(info, separators=(",", ":"), sort_keys=True)
    except Exception:  # noqa: BLE001 — stamping never fails a request
        return None


def stamp_trailers(context, worker, trace_id=None, shed=False,
                   explain=None):
    """Set the response's trailing metadata in ONE call (grpc's
    set_trailing_metadata overwrites, so every stamp merges here):
    ``x-acs-policy-epoch`` — the replica's policy epoch, letting the
    cluster router (srv/router.py) track per-replica convergence from
    live traffic without polling; ``x-acs-shed`` — the whole request
    was shed by admission control, so the router may retry it on
    another replica without parsing response bytes; ``x-acs-explain``
    — deciding-node provenance JSON when explain mode is on
    (docs/EXPLAIN.md); plus the trace-id echo (srv/tracing.py) when
    the request was sampled."""
    md = []
    if explain:
        md.append((EXPLAIN_METADATA_KEY, explain))
    epoch_fn = getattr(worker, "policy_epoch", None)
    if epoch_fn is not None:
        try:
            md.append((POLICY_EPOCH_METADATA_KEY, str(epoch_fn())))
        except Exception:  # noqa: BLE001 — stamping never fails a request
            pass
    if shed:
        md.append((SHED_METADATA_KEY, "1"))
    if trace_id:
        md.append((TRACE_ID_METADATA_KEY, trace_id))
    if not md:
        return
    try:
        context.set_trailing_metadata(tuple(md))
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        pass


def _unary(handler, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        handler,
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


class GrpcServer:
    """Binds the worker's services to a grpc.Server."""

    def __init__(self, worker, addr: str = "127.0.0.1:0", max_workers: int = 16):
        self.worker = worker
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_MESSAGE_SIZE_OPTIONS,
        )
        self._register()
        self.port = self.server.add_insecure_port(addr)
        self.addr = addr.rsplit(":", 1)[0] + f":{self.port}"

    def start(self):
        self.server.start()
        return self

    def stop(self, grace: float = 0.5):
        self.server.stop(grace)

    # ------------------------------------------------------------- handlers

    def _register(self):
        worker = self.worker
        # observability hub: None (config absent/disabled) keeps every
        # handler on the exact pre-observability path
        obs = getattr(worker, "obs", None)

        def is_allowed(request, context):
            # deadline propagation (srv/admission.py): the client's gRPC
            # deadline (or x-acs-timeout-ms metadata) becomes the
            # request's budget — rejected at submit when infeasible,
            # dropped at dispatch when expired
            tenant = tenant_from_metadata(context)
            if obs is None or obs.tracer is None:
                req = request_from_pb(request)
                if tenant is not None:
                    req._tenant = tenant
                response = worker.service.is_allowed(
                    req, deadline=deadline_from_context(context),
                )
                stamp_trailers(
                    context, worker,
                    shed=response.operation_status.code in SHED_CODES,
                    explain=explain_trailer(response),
                )
                return response_to_pb(response)
            # traced path: span at transport receive (trace id from the
            # x-acs-trace-id metadata key — an explicit id forces
            # sampling), parse + serialize stages recorded here, the
            # pipeline stages downstream; the id echoes on the trailer
            tracer = obs.tracer
            t0 = time.perf_counter()
            span = tracer.start_span(trace_id_from_metadata(context))
            req = request_from_pb(request)
            if tenant is not None:
                req._tenant = tenant
            tracer.record(span, STAGE_TRANSPORT_PARSE,
                          time.perf_counter() - t0)
            req._sampling_done = True
            if span is not None:
                req._span = span
            response = worker.service.is_allowed(
                req, deadline=deadline_from_context(context)
            )
            t_ser = time.perf_counter()
            msg = response_to_pb(response)
            tracer.record(span, STAGE_SERIALIZE,
                          time.perf_counter() - t_ser)
            stamp_trailers(
                context, worker,
                trace_id=span.trace_id if span is not None else None,
                shed=response.operation_status.code in SHED_CODES,
                explain=explain_trailer(response),
            )
            if span is not None:
                tracer.finish(span, decision=response.decision,
                              code=response.operation_status.code)
            return msg

        def is_allowed_batch(raw, context):
            # raw BatchRequest bytes: try the native wire fast path (C++
            # encoder + kernel, no python deserialization for eligible
            # rows); fall back to full pb parse + service path
            import time as _time

            t0 = _time.perf_counter()
            deadline = deadline_from_context(context)
            tenant = tenant_from_metadata(context)
            tracer = obs.tracer if obs is not None else None
            span = None
            t_stage = t0
            if tracer is not None:
                # one RPC-level span for the whole batch: batch stages
                # fan into it once (srv/tracing.StageTracer.fan_out)
                span = tracer.start_span(trace_id_from_metadata(context))
            messages = split_batch_request(raw)
            if tracer is not None:
                now = _time.perf_counter()
                tracer.record(span, STAGE_TRANSPORT_PARSE, now - t_stage)
                t_stage = now

            def _shed_all(resps) -> bool:
                # whole-batch shed (every row an admission status):
                # stamped so the router may retry the batch elsewhere
                return bool(resps) and all(
                    r.operation_status.code in SHED_CODES for r in resps
                )

            def finish_rpc(payload: bytes, shed: bool = False) -> bytes:
                stamp_trailers(
                    context, worker,
                    trace_id=span.trace_id if span is not None else None,
                    shed=shed,
                )
                if tracer is not None and span is not None:
                    tracer.finish(span, code=200)
                return payload

            evaluator = worker.service.evaluator
            # tenanted batches must resolve against the tenant's own
            # tables (srv/tenancy.py) — the native wire fast path binds
            # the default-domain program, so route through the service
            # path where the batcher partitions by tenant
            if tenant is not None:
                messages = None
            if messages is not None and evaluator is not None:
                out = None
                try:
                    out = evaluator.is_allowed_batch_wire(
                        messages, span=span
                    )
                except Exception:
                    out = None
                if out is not None:
                    if tracer is not None:
                        t_stage = _time.perf_counter()
                    # per-row assembly + ONE batched fallback call for
                    # ineligible rows (per-row service.is_allowed would
                    # wait out a micro-batch window each); observe=False
                    # on the fallback: this handler records batch_latency
                    # and decision counts for ALL rows below
                    responses, fallback_rows, fallback_reqs = \
                        decode_native_rows(messages, out)
                    if tracer is not None:
                        now = _time.perf_counter()
                        tracer.record(span, STAGE_DECODE, now - t_stage)
                        t_stage = now
                    resolve_fallback_rows(
                        worker, responses, fallback_rows, fallback_reqs,
                        deadline, span=span,
                    )
                    telemetry = getattr(worker, "telemetry", None)
                    if telemetry is not None:
                        telemetry.batch_latency.observe(
                            _time.perf_counter() - t0
                        )
                        for resp in responses:
                            telemetry.decisions.inc(
                                PB_TO_DECISION.get(resp.decision, "DENY")
                            )
                    if tracer is None:
                        stamp_trailers(context, worker,
                                       shed=_shed_all(responses))
                        return serialize_batch_response(responses)
                    t_stage = _time.perf_counter()
                    payload = serialize_batch_response(responses)
                    tracer.record(span, STAGE_SERIALIZE,
                                  _time.perf_counter() - t_stage)
                    return finish_rpc(payload, shed=_shed_all(responses))
            if tracer is not None:
                t_stage = _time.perf_counter()
            request = pb.BatchRequest.FromString(raw)
            reqs = [request_from_pb(r) for r in request.requests]
            if tenant is not None:
                for req in reqs:
                    req._tenant = tenant
            if tracer is not None:
                now = _time.perf_counter()
                tracer.record(span, STAGE_TRANSPORT_PARSE, now - t_stage)
                if span is not None:
                    for req in reqs:
                        req._span = span
                        req._sampling_done = True
            responses = worker.service.is_allowed_batch(
                reqs, deadline=deadline,
            )
            if tracer is None:
                stamp_trailers(context, worker, shed=_shed_all(responses))
                return serialize_batch_response(
                    [response_to_pb(r) for r in responses]
                )
            t_stage = _time.perf_counter()
            payload = serialize_batch_response(
                [response_to_pb(r) for r in responses]
            )
            tracer.record(span, STAGE_SERIALIZE,
                          _time.perf_counter() - t_stage)
            return finish_rpc(payload, shed=_shed_all(responses))

        def is_allowed_stream(request_iterator, context):
            """Streaming batch endpoint: a stream of BatchRequest
            envelopes in, a stream of BatchResponse frames out — one
            response frame per request frame, IN FRAME ORDER per stream,
            while frames from ALL streams share one depth-bounded device
            pipeline (srv/pipeline.py).  A feeder thread consumes the
            request iterator (submit's backpressure bounds it at the
            pipeline depth) so response frames flush the moment they
            complete instead of waiting for the next request frame —
            a client that awaits response i before sending i+1 cannot
            deadlock."""
            import queue as _queue
            import threading as _threading

            pipeline = getattr(worker, "wire_pipeline", None)
            deadline = deadline_from_context(context)
            tracer = obs.tracer if obs is not None else None
            if pipeline is None:
                for raw in request_iterator:
                    yield is_allowed_batch(raw, context)
                stamp_trailers(context, worker)
                return
            frames: "_queue.Queue" = _queue.Queue()

            def feed():
                try:
                    for raw in request_iterator:
                        span = None
                        if tracer is not None:
                            span = tracer.start_span(
                                trace_id_from_metadata(context)
                            )
                        frames.put(
                            (pipeline.submit(raw, deadline, span=span),
                             span)
                        )
                except BaseException as err:  # noqa: BLE001
                    frames.put(err)
                frames.put(None)

            _threading.Thread(target=feed, daemon=True).start()
            while True:
                item = frames.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                future, span = item
                payload = future.result()
                if tracer is not None and span is not None:
                    tracer.finish(span, code=200)
                yield payload
            # stream-level trailer: the epoch as of stream completion
            # (per-frame epochs would need in-band stamping; the router
            # refreshes epochs from unary traffic and health polls)
            stamp_trailers(context, worker)

        def what_is_allowed(request, context):
            req = request_from_pb(request)
            tenant = tenant_from_metadata(context)
            if tenant is not None:
                req._tenant = tenant
            rq = worker.service.what_is_allowed(
                req, deadline=deadline_from_context(context),
            )
            return reverse_query_to_pb(rq)

        def what_is_allowed_batch(request, context):
            reqs = [request_from_pb(m) for m in request.requests]
            tenant = tenant_from_metadata(context)
            if tenant is not None:
                for req in reqs:
                    req._tenant = tenant
            rqs = worker.service.what_is_allowed_batch(
                reqs, deadline=deadline_from_context(context),
            )
            return pb.BatchReverseQuery(
                responses=[reverse_query_to_pb(rq) for rq in rqs]
            )

        ac_handlers = {
            "IsAllowed": _unary(is_allowed, pb.Request, pb.Response),
            # raw-bytes deserializer AND serializer: the handler splits
            # the envelope itself so eligible rows never touch python
            # protobuf, and replies arrive pre-serialized off-thread
            # (serialize_batch_response)
            "IsAllowedBatch": grpc.unary_unary_rpc_method_handler(
                is_allowed_batch,
                request_deserializer=lambda raw: raw,
                response_serializer=lambda msg: (
                    msg if isinstance(msg, bytes)
                    else msg.SerializeToString()
                ),
            ),
            # streaming twin of IsAllowedBatch: raw frames in/out, one
            # shared device pipeline behind every stream
            "IsAllowedStream": grpc.stream_stream_rpc_method_handler(
                is_allowed_stream,
                request_deserializer=lambda raw: raw,
                response_serializer=lambda msg: (
                    msg if isinstance(msg, bytes)
                    else msg.SerializeToString()
                ),
            ),
            "WhatIsAllowed": _unary(what_is_allowed, pb.Request, pb.ReverseQuery),
            # framework extension: batched reverse query through the
            # device-assisted path (ops/reverse.py)
            "WhatIsAllowedBatch": _unary(
                what_is_allowed_batch, pb.BatchRequest, pb.BatchReverseQuery
            ),
        }
        self.server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "acstpu.AccessControlService", ac_handlers
                ),
            )
        )

        for kind, doc_from, list_cls, list_resp_cls, fill in (
            ("rule", rule_doc_from_pb, pb.RuleList, pb.RuleListResponse,
             self._fill_rule),
            ("policy", policy_doc_from_pb, pb.PolicyList,
             pb.PolicyListResponse, self._fill_policy),
            ("policy_set", policy_set_doc_from_pb, pb.PolicySetList,
             pb.PolicySetListResponse, self._fill_policy_set),
        ):
            handlers = self._crud_handlers(kind, doc_from, list_cls,
                                           list_resp_cls, fill)
            name = {
                "rule": "acstpu.RuleService",
                "policy": "acstpu.PolicyService",
                "policy_set": "acstpu.PolicySetService",
            }[kind]
            self.server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(name, handlers),)
            )

        def command(request, context):
            payload = json.loads(request.payload) if request.payload else {}
            result = worker.command_interface.command(request.name, payload)
            return pb.CommandResponse(payload=json.dumps(result).encode())

        self.server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "acstpu.CommandInterface",
                    {"Command": _unary(command, pb.CommandRequest,
                                       pb.CommandResponse)},
                ),
            )
        )

        def health(request, context):
            result = worker.command_interface.command("health_check")
            return pb.HealthCheckResponse(status=result["status"])

        self.server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "acstpu.Health",
                    {"Check": _unary(health, pb.HealthCheckRequest,
                                     pb.HealthCheckResponse)},
                ),
            )
        )

        # reference-wire aliases: the same handlers under the
        # restorecommerce service names + message shapes, so stock
        # restorecommerce clients (acs-client et al.) call this service
        # unmodified (srv/transport_rc.py)
        from .transport_rc import register_rc_services

        register_rc_services(self.server, worker)

    def _crud_handlers(self, kind, doc_from_pb, list_cls, list_resp_cls, fill):
        service = self.worker.store.get_resource_service(kind)

        def create(request, context):
            return self._mutation_response(
                service.create([doc_from_pb(i) for i in request.items],
                               subject=_subject_from_pb(request.subject))
            )

        def update(request, context):
            return self._mutation_response(
                service.update([doc_from_pb(i) for i in request.items],
                               subject=_subject_from_pb(request.subject))
            )

        def upsert(request, context):
            return self._mutation_response(
                service.upsert([doc_from_pb(i) for i in request.items],
                               subject=_subject_from_pb(request.subject))
            )

        def delete(request, context):
            return self._mutation_response(
                service.delete(ids=list(request.ids),
                               collection=request.collection,
                               subject=_subject_from_pb(request.subject))
            )

        def read(request, context):
            filters = None
            if request.ids:
                filters = {"ids": list(request.ids)}
            elif request.filters:
                filters = {"filters": [
                    {
                        "operator": group.operator or "and",
                        "filters": [
                            {"field": f.field, "operation": f.operation,
                             "value": f.value}
                            for f in group.filters
                        ],
                    }
                    for group in request.filters
                ]}
            result = service.read(filters)
            resp = list_resp_cls()
            for item in result.get("items", []):
                payload = item.get("payload")
                if payload is not None:
                    fill(resp.items.add(), payload)
            status = result["operation_status"]
            resp.operation_status.code = status["code"]
            resp.operation_status.message = status["message"]
            return resp

        return {
            "Create": _unary(create, list_cls, pb.MutationResponse),
            "Update": _unary(update, list_cls, pb.MutationResponse),
            "Upsert": _unary(upsert, list_cls, pb.MutationResponse),
            "Delete": _unary(delete, pb.DeleteRequest, pb.MutationResponse),
            "Read": _unary(read, pb.ReadRequest, list_resp_cls),
        }

    # ---------------------------------------------------- doc -> pb fillers

    @staticmethod
    def _fill_attr(msg: pb.Attribute, doc: dict):
        msg.id = doc.get("id") or ""
        msg.value = str(doc.get("value") or "")
        for child in doc.get("attributes") or []:
            GrpcServer._fill_attr(msg.attributes.add(), child)

    @staticmethod
    def _fill_target(msg: pb.Target, doc: dict):
        for key, field in (("subjects", msg.subjects),
                           ("resources", msg.resources),
                           ("actions", msg.actions)):
            for attr in doc.get(key) or []:
                GrpcServer._fill_attr(field.add(), attr)

    @staticmethod
    def _fill_meta(msg: pb.Meta, doc: dict):
        for owner in doc.get("owners") or []:
            GrpcServer._fill_attr(msg.owners.add(), owner)
        for acl in doc.get("acls") or []:
            GrpcServer._fill_attr(msg.acls.add(), acl)
        msg.created = float(doc.get("created") or 0.0)
        msg.modified = float(doc.get("modified") or 0.0)

    @classmethod
    def _fill_rule(cls, msg: pb.Rule, doc: dict):
        msg.id = doc.get("id") or ""
        msg.name = doc.get("name") or ""
        msg.description = doc.get("description") or ""
        msg.effect = doc.get("effect") or ""
        msg.condition = doc.get("condition") or ""
        msg.evaluation_cacheable = bool(doc.get("evaluation_cacheable"))
        if doc.get("target"):
            cls._fill_target(msg.target, doc["target"])
        if doc.get("context_query"):
            cq = doc["context_query"]
            msg.context_query.query = cq.get("query") or ""
            for f in cq.get("filters") or []:
                msg.context_query.filters.add(
                    field=str(f.get("field") or ""),
                    operation=str(f.get("operation") or ""),
                    value=str(f.get("value") or ""),
                )
        if doc.get("meta"):
            cls._fill_meta(msg.meta, doc["meta"])

    @classmethod
    def _fill_policy(cls, msg: pb.Policy, doc: dict):
        msg.id = doc.get("id") or ""
        msg.name = doc.get("name") or ""
        msg.description = doc.get("description") or ""
        msg.effect = doc.get("effect") or ""
        msg.combining_algorithm = doc.get("combining_algorithm") or ""
        msg.evaluation_cacheable = bool(doc.get("evaluation_cacheable"))
        msg.rules.extend(doc.get("rules") or [])
        if doc.get("target"):
            cls._fill_target(msg.target, doc["target"])
        if doc.get("meta"):
            cls._fill_meta(msg.meta, doc["meta"])

    @classmethod
    def _fill_policy_set(cls, msg: pb.PolicySet, doc: dict):
        msg.id = doc.get("id") or ""
        msg.name = doc.get("name") or ""
        msg.description = doc.get("description") or ""
        msg.combining_algorithm = doc.get("combining_algorithm") or ""
        msg.policies.extend(doc.get("policies") or [])
        if doc.get("target"):
            cls._fill_target(msg.target, doc["target"])
        if doc.get("meta"):
            cls._fill_meta(msg.meta, doc["meta"])

    @staticmethod
    def _mutation_response(result: dict) -> pb.MutationResponse:
        resp = pb.MutationResponse()
        for item in result.get("items", []):
            status = item.get("status", {})
            payload = item.get("payload") or {}
            resp.statuses.add(
                id=payload.get("id", ""),
                code=status.get("code", 200),
                message=status.get("message", "success"),
            )
        op = result.get("operation_status", {})
        resp.operation_status.code = op.get("code", 200)
        resp.operation_status.message = op.get("message", "success")
        return resp


# ----------------------------------------------------------------- client

class GrpcClient:
    """Typed client over the generic channel (test + SDK use)."""

    def __init__(self, addr: str):
        self.channel = grpc.insecure_channel(
            addr, options=_MESSAGE_SIZE_OPTIONS
        )

    def close(self):
        self.channel.close()

    def _call(self, service: str, method: str, request, resp_cls):
        fn = self.channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return fn(request)

    def is_allowed(self, request: pb.Request) -> pb.Response:
        return self._call("acstpu.AccessControlService", "IsAllowed",
                          request, pb.Response)

    def is_allowed_batch(self, request: pb.BatchRequest) -> pb.BatchResponse:
        return self._call("acstpu.AccessControlService", "IsAllowedBatch",
                          request, pb.BatchResponse)

    def is_allowed_stream(self, batches, timeout=None):
        """Streaming batch call: ``batches`` is an iterable of
        pb.BatchRequest messages (or pre-serialized envelope bytes);
        yields one pb.BatchResponse per frame, in frame order."""
        fn = self.channel.stream_stream(
            "/acstpu.AccessControlService/IsAllowedStream",
            request_serializer=lambda m: (
                m if isinstance(m, (bytes, bytearray))
                else m.SerializeToString()
            ),
            response_deserializer=pb.BatchResponse.FromString,
        )
        return fn(batches, timeout=timeout)

    def what_is_allowed(self, request: pb.Request) -> pb.ReverseQuery:
        return self._call("acstpu.AccessControlService", "WhatIsAllowed",
                          request, pb.ReverseQuery)

    def crud(self, kind: str, method: str, request, resp_cls=None):
        service = {
            "rule": "acstpu.RuleService",
            "policy": "acstpu.PolicyService",
            "policy_set": "acstpu.PolicySetService",
        }[kind]
        if resp_cls is None:
            resp_cls = pb.MutationResponse
        return self._call(service, method, request, resp_cls)

    def command(self, name: str, payload: dict | None = None) -> dict:
        resp = self._call(
            "acstpu.CommandInterface",
            "Command",
            pb.CommandRequest(
                name=name, payload=json.dumps(payload or {}).encode()
            ),
            pb.CommandResponse,
        )
        return json.loads(resp.payload)

    def health(self) -> str:
        resp = self._call("acstpu.Health", "Check", pb.HealthCheckRequest(),
                          pb.HealthCheckResponse)
        return resp.status
