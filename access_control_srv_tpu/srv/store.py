"""Policy store: document collections + CRUD services + hot tree sync.

Framework analog of the reference's resource layer
(reference: src/resourceManager.ts): three CRUD services (rule / policy /
policy_set) persisting flat documents (children referenced by id), each
mutation stamping owner metadata, optionally self-authorizing through the
engine, emitting a CRUD event, and hot-syncing the in-memory evaluation
tree (+ kernel recompile via the evaluator).

Persistence is pluggable: the default collection is in-memory with an
optional JSON snapshot directory (the ArangoDB role is durability +
queries; decision semantics never depended on it, SURVEY.md L6).

CRUD topic contract: frames on ``io.restorecommerce.{kind}s.resource``
are ``{"payload": <resource doc | {"id"} | {"collection": true}>,
"origin": <emitting store id>}`` — the envelope lets PolicyReplicator
skip a worker's own echoes; consumers wanting the raw resource read
``message["payload"]``.  (The reference's Kafka frames carry the bare
resource proto; this bus is framework-internal, the reference-wire
surface is gRPC — docs/WIRE_COMPAT.md.)"""

from __future__ import annotations

import copy
import json
import os
import re
import threading
import time
import uuid
from typing import Callable, Optional

from .clock import monotonic_wall
from ..core.engine import AccessController
from ..core.loader import policy_from_dict, policy_set_from_dict, rule_from_dict
from ..models.model import Decision
from ..ops.delta import CrudEvent, footprint_from_events


class Collection:
    """An ordered id -> document map with optional durable persistence.

    Persistence is snapshot + journal (the per-document-write cost model
    of the reference's ArangoDB, not rewrite-the-world): single-document
    mutations append one JSON-lines record to ``{name}.journal`` — O(doc),
    independent of corpus size — and the full ``{name}.json`` snapshot is
    rewritten only on bulk loads, clears, or when the journal exceeds
    ``compact_every`` records (then the journal is truncated).  Startup
    loads the snapshot and replays the journal; a torn trailing record
    (crash mid-append) is skipped.

    Durability caveat (same contract as the broker journal): records are
    flushed per append but NOT fsynced — a process crash loses nothing
    already flushed; a host-level crash can drop the flushed tail still
    in the page cache.  Call ``close()`` on shutdown so the handle does
    not rely on GC."""

    def __init__(self, name: str, snapshot_dir: Optional[str] = None,
                 compact_every: int = 1024):
        self.name = name
        self._docs: dict[str, dict] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.snapshot_dir = snapshot_dir
        self.compact_every = compact_every
        self._journal_fh = None       # guarded-by: _lock
        self._journal_records = 0     # guarded-by: _lock
        if snapshot_dir:
            path = os.path.join(snapshot_dir, f"{name}.json")
            if os.path.exists(path):
                with open(path) as fh:
                    for doc in json.load(fh):
                        self._docs[doc["id"]] = doc
            jpath = self._journal_path()
            if os.path.exists(jpath):
                with open(jpath) as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue  # torn tail record
                        if rec.get("op") == "upsert":
                            self._docs[rec["doc"]["id"]] = rec["doc"]
                        elif rec.get("op") == "delete":
                            self._docs.pop(rec["id"], None)
                        self._journal_records += 1

    def _journal_path(self) -> str:
        return os.path.join(self.snapshot_dir, f"{self.name}.journal")

    def _append(self, rec: dict) -> None:  # holds: _lock
        """One O(doc) journal record; caller holds self._lock.  Rolls the
        journal into a fresh snapshot past the compaction threshold."""
        if not self.snapshot_dir:
            return
        if self._journal_records >= self.compact_every:
            self._snapshot()
            return
        if self._journal_fh is None:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            self._journal_fh = open(self._journal_path(), "a",
                                    encoding="utf-8")
        self._journal_fh.write(json.dumps(rec) + "\n")
        self._journal_fh.flush()
        self._journal_records += 1

    def _snapshot(self):  # holds: _lock
        """Full rewrite + journal truncation; caller holds self._lock."""
        if not self.snapshot_dir:
            return
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = os.path.join(self.snapshot_dir, f"{self.name}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(list(self._docs.values()), fh, indent=1)
        os.replace(tmp, path)
        if self._journal_fh is not None:
            self._journal_fh.close()
            self._journal_fh = None
        try:
            os.remove(self._journal_path())
        except OSError:
            pass
        self._journal_records = 0

    def upsert(self, doc: dict) -> None:
        with self._lock:
            doc = copy.deepcopy(doc)
            self._docs[doc["id"]] = doc
            self._append({"op": "upsert", "doc": doc})

    def upsert_many(self, docs: list[dict]) -> None:
        """Bulk path: one lock acquisition and one snapshot for the whole
        list (per-doc journaling would write n records for a load that a
        single compacted snapshot represents)."""
        with self._lock:
            for doc in docs:
                self._docs[doc["id"]] = copy.deepcopy(doc)
            self._snapshot()

    def insert(self, doc: dict) -> bool:
        with self._lock:
            if doc["id"] in self._docs:
                return False
            doc = copy.deepcopy(doc)
            self._docs[doc["id"]] = doc
            self._append({"op": "upsert", "doc": doc})
            return True

    def get(self, doc_id: str) -> Optional[dict]:
        with self._lock:
            doc = self._docs.get(doc_id)
            return copy.deepcopy(doc) if doc is not None else None

    def delete(self, doc_id: str) -> bool:
        with self._lock:
            existed = self._docs.pop(doc_id, None) is not None
            if existed:
                self._append({"op": "delete", "id": doc_id})
            return existed

    def all(self) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(d) for d in self._docs.values()]

    def close(self) -> None:
        """Close the journal handle; shutdown must not rely on GC."""
        with self._lock:
            if self._journal_fh is not None:
                self._journal_fh.close()
                self._journal_fh = None

    def clear(self) -> None:
        with self._lock:
            self._docs.clear()
            self._snapshot()


class _FilterError(ValueError):
    pass


def _field_value(doc, path: str):
    """Dotted-path lookup into the document (missing -> None)."""
    node = doc
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        else:
            return None
    return node


def _coerce_json(raw):
    if isinstance(raw, str):
        try:
            return json.loads(raw)
        except ValueError:
            return raw
    return raw


def _predicate_matches(flt: dict, doc: dict) -> bool:
    """One {field, operation, value} predicate (reference FilterOperation
    set: eq, neq, lt, lte, gt, gte, in, isEmpty, iLike)."""
    field = flt.get("field")
    op = (flt.get("operation") or "eq")
    if not field:
        raise _FilterError("filter predicate missing field")
    actual = _field_value(doc, field)
    if op == "isEmpty":
        return actual is None or actual == "" or actual == []
    raw = flt.get("value")
    value = _coerce_json(raw)
    if op == "eq":
        # string fields whose content happens to parse as JSON (e.g. the
        # literal string "2024") must still match: compare raw AND coerced
        return actual == raw or actual == value
    if op == "neq":
        return not (actual == raw or actual == value)
    if op == "in":
        options = value if isinstance(value, list) else [value]
        return actual in options
    if op == "iLike":
        if not isinstance(actual, str) or not isinstance(value, str):
            return False
        # SQL LIKE: % is the wildcard; everything else literal,
        # case-insensitive
        pattern = ".*".join(
            re.escape(part) for part in value.lower().split("%")
        )
        return re.fullmatch(pattern, actual.lower()) is not None
    if op in ("lt", "lte", "gt", "gte"):
        try:
            a, v = float(actual), float(value)
        except (TypeError, ValueError):
            return False
        return {"lt": a < v, "lte": a <= v,
                "gt": a > v, "gte": a >= v}[op]
    raise _FilterError(f"unknown filter operation {op!r}")


def _filter_groups_match(groups: list, doc: dict) -> bool:
    """Groups AND together; predicates inside a group combine with the
    group operator (and/or, default and)."""
    for group in groups or []:
        predicates = group.get("filters") or []
        if not predicates:
            continue
        operator = group.get("operator") or "and"
        if operator not in ("and", "or"):
            raise _FilterError(f"unknown filter group operator {operator!r}")
        results = [_predicate_matches(f, doc) for f in predicates]
        combined = any(results) if operator == "or" else all(results)
        if not combined:
            return False
    return True


def _op_status(code=200, message="success"):
    return {"code": code, "message": message}


class ResourceService:
    """Generic CRUD over one resource kind with metadata stamping,
    self-authorization, event emission and tree hot-sync
    (reference: RuleService/PolicyService/PolicySetService in
    src/resourceManager.ts)."""

    KIND_EVENT = {"rule": "rule", "policy": "policy", "policy_set": "policySet"}

    def __init__(
        self,
        kind: str,
        collection: Collection,
        store: "PolicyStore",
        topic=None,
        access_check: Optional[Callable] = None,
        urns=None,
        logger=None,
    ):
        self.kind = kind
        self.collection = collection
        self.store = store
        self.topic = topic
        self.access_check = access_check
        self.urns = urns
        self.logger = logger

    # -------------------------------------------------------------- helpers

    def read_meta_data(self, doc_id: str) -> Optional[dict]:
        doc = self.collection.get(doc_id)
        return doc.get("meta") if doc else None

    def _create_metadata(self, items: list[dict], action: str, subject) -> list[dict]:
        """Owner stamping + id generation
        (reference: src/core/utils.ts:269-349)."""
        urns = self.urns
        org_owner_attrs = []
        scope = (subject or {}).get("scope")
        if subject and scope and action in ("CREATE", "MODIFY"):
            org_owner_attrs.append(
                {
                    "id": urns.get("ownerIndicatoryEntity"),
                    "value": urns.get("organization"),
                    "attributes": [
                        {"id": urns.get("ownerInstance"), "value": scope}
                    ],
                }
            )
        # monotonic-anchored: meta.modified/created are ordering-sensitive
        # stored stamps — a raw time.time() stepping backward under NTP
        # slew would reorder document history (srv/clock.py)
        now = monotonic_wall()
        for item in items:
            meta = item.setdefault("meta", {})
            # timestamp stamping (reference: resource-base fieldHandlers
            # timeStampFields meta.created/meta.modified,
            # cfg/config.json:324-331)
            meta["modified"] = now
            # created is server-stamped: always restored from the stored
            # document (a client-supplied meta.created must never overwrite
            # the original creation time — reference resource-base
            # timeStampFields semantics), falling back to now only when no
            # prior doc exists
            existing_meta = (
                self.read_meta_data(item.get("id", ""))
                if item.get("id") else None
            )
            meta["created"] = (existing_meta or {}).get("created") or now
            if action in ("MODIFY", "DELETE"):
                if existing_meta and existing_meta.get("owners"):
                    meta["owners"] = existing_meta["owners"]
                    continue
            if not item.get("id"):
                item["id"] = uuid.uuid4().hex
            owners = meta.get("owners") or list(org_owner_attrs)
            if subject and subject.get("id"):
                owners = owners + [
                    {
                        "id": urns.get("ownerIndicatoryEntity"),
                        "value": urns.get("user"),
                        "attributes": [
                            {
                                "id": urns.get("ownerInstance"),
                                "value": subject["id"],
                            }
                        ],
                    }
                ]
            meta["owners"] = owners
        return items

    def _authorize(self, items, action, subject, ctx) -> Optional[dict]:
        """Self-authorization of CRUD through the engine
        (reference: checkAccessRequest, src/core/utils.ts:212-261; every
        CRUD op in resourceManager.ts)."""
        if self.access_check is None:
            return None
        decision = self.access_check(self.kind, items, action, subject, ctx)
        if decision != Decision.PERMIT:
            return {
                "operation_status": _op_status(
                    403,
                    f"Access not allowed for request with subject:"
                    f"{(subject or {}).get('id')}, resource:{self.kind}, "
                    f"action:{action}, target_scope:{(subject or {}).get('scope')}; "
                    f"the response was {decision}",
                )
            }
        return None

    def _emit(self, event: str, doc: dict) -> None:
        if self.topic is not None:
            # the origin id lets a PolicyReplicator on this worker skip
            # its own frames when the broker streams them back (the
            # mutation was applied locally at CRUD time); an offset-based
            # guard would race the broker, which fans a frame out to
            # subscribers BEFORE answering the emit RPC
            self.topic.emit(
                event, {"payload": doc, "origin": self.store.origin}
            )

    # ----------------------------------------------------------------- CRUD

    def create(self, items: list[dict], subject=None, ctx=None) -> dict:
        items = self._create_metadata([copy.deepcopy(i) for i in items], "CREATE", subject)
        denied = self._authorize(items, "CREATE", subject, ctx)
        if denied:
            return denied
        results = []
        events = []
        for doc in items:
            events.append(CrudEvent(
                kind=self.kind, op="create", doc_id=doc["id"],
                old_doc=self.collection.get(doc["id"]), new_doc=doc,
            ))
            self.collection.upsert(doc)
            self._emit(f"{self.KIND_EVENT[self.kind]}Created", doc)
            results.append({"payload": doc, "status": _op_status()})
        self.store.sync_after_mutation(self.kind, "create", items, events)
        return {"items": results, "operation_status": _op_status()}

    def update(self, items: list[dict], subject=None, ctx=None) -> dict:
        items = self._create_metadata([copy.deepcopy(i) for i in items], "MODIFY", subject)
        denied = self._authorize(items, "MODIFY", subject, ctx)
        if denied:
            return denied
        results = []
        events = []
        for doc in items:
            old_doc = self.collection.get(doc["id"])
            if old_doc is None:
                results.append(
                    {"payload": None,
                     "status": _op_status(404, f"{doc['id']} not found")}
                )
                continue
            events.append(CrudEvent(
                kind=self.kind, op="update", doc_id=doc["id"],
                old_doc=old_doc, new_doc=doc,
            ))
            self.collection.upsert(doc)
            self._emit(f"{self.KIND_EVENT[self.kind]}Modified", doc)
            results.append({"payload": doc, "status": _op_status()})
        self.store.sync_after_mutation(self.kind, "update", items, events)
        return {"items": results, "operation_status": _op_status()}

    def upsert(self, items: list[dict], subject=None, ctx=None) -> dict:
        items = self._create_metadata([copy.deepcopy(i) for i in items], "MODIFY", subject)
        denied = self._authorize(items, "MODIFY", subject, ctx)
        if denied:
            return denied
        results = []
        events = []
        for doc in items:
            events.append(CrudEvent(
                kind=self.kind, op="upsert", doc_id=doc["id"],
                old_doc=self.collection.get(doc["id"]), new_doc=doc,
            ))
            self.collection.upsert(doc)
            self._emit(f"{self.KIND_EVENT[self.kind]}Modified", doc)
            results.append({"payload": doc, "status": _op_status()})
        self.store.sync_after_mutation(self.kind, "upsert", items, events)
        return {"items": results, "operation_status": _op_status()}

    def super_upsert(self, items: list[dict], sync: bool = True) -> dict:
        """Seed-data path: no authorization (reference: src/worker.ts:228)."""
        self.collection.upsert_many(items)
        if sync:
            self.store.sync_after_mutation(self.kind, "upsert", items)
        return {"operation_status": _op_status()}

    def read(self, filters: Optional[dict] = None) -> dict:
        """``filters`` accepts the ids shorthand ({"ids": [...]}) or the
        resource-base filter DSL ({"filters": [group, ...]}, reference:
        resource-base-interface FilterOperation via
        resourceManager.ts:61-68): groups of {field, operation, value}
        predicates, predicates combined by the group operator (and/or),
        groups combined with AND."""
        docs = self.collection.all()
        if filters and "ids" in filters:
            wanted = set(filters["ids"])
            docs = [d for d in docs if d["id"] in wanted]
        elif filters and filters.get("filters"):
            try:
                docs = [
                    d for d in docs
                    if _filter_groups_match(filters["filters"], d)
                ]
            except _FilterError as err:
                return {"operation_status": _op_status(400, str(err))}
        return {
            "items": [{"payload": d, "status": _op_status()} for d in docs],
            "operation_status": _op_status(),
        }

    def delete(self, ids=None, collection=False, subject=None, ctx=None) -> dict:
        if collection:
            denied = self._authorize([], "DROP", subject, ctx)
            if denied:
                return denied
            self.collection.clear()
            self._emit(f"{self.KIND_EVENT[self.kind]}Deleted", {"collection": True})
            self.store.sync_after_mutation(
                self.kind, "delete_all", [],
                [CrudEvent(kind=self.kind, op="delete_all", doc_id="")],
            )
            return {"operation_status": _op_status()}
        items = [{"id": i} for i in (ids or [])]
        items = self._create_metadata(items, "DELETE", subject)
        denied = self._authorize(items, "DELETE", subject, ctx)
        if denied:
            return denied
        events = []
        for doc_id in ids or []:
            events.append(CrudEvent(
                kind=self.kind, op="delete", doc_id=doc_id,
                old_doc=self.collection.get(doc_id), new_doc=None,
            ))
            self.collection.delete(doc_id)
            self._emit(f"{self.KIND_EVENT[self.kind]}Deleted", {"id": doc_id})
        self.store.sync_after_mutation(self.kind, "delete", items, events)
        return {"operation_status": _op_status()}


class PolicyStore:
    """The three collections + tree composition + hot sync
    (reference: ResourceManager, src/resourceManager.ts:1050-1092; the
    3-level load join :765-797)."""

    def __init__(
        self,
        engine: AccessController,
        evaluator=None,
        bus=None,
        snapshot_dir: Optional[str] = None,
        access_check: Optional[Callable] = None,
        logger=None,
    ):
        self.engine = engine
        self.evaluator = evaluator
        self.logger = logger
        self.collections = {
            kind: Collection(kind, snapshot_dir)
            for kind in ("rule", "policy", "policy_set")
        }
        self.services = {
            kind: ResourceService(
                kind,
                self.collections[kind],
                self,
                topic=bus.topic(f"io.restorecommerce.{kind}s.resource")
                if bus
                else None,
                access_check=access_check,
                urns=engine.urns,
                logger=logger,
            )
            for kind in ("rule", "policy", "policy_set")
        }

        # unique per store instance: stamps emitted CRUD frames so a
        # replicator can distinguish this worker's own mutations from
        # remote ones (srv/store.PolicyReplicator)
        self.origin = uuid.uuid4().hex
        # serializes tree recompose+swap: local CRUD sync and the
        # replicator's debounced sync may run on different threads, and
        # an unserialized older compose must not swap in after a newer
        # one (load() reads the collections under this lock, so the last
        # swap always reflects the latest collection state)
        self._load_lock = threading.Lock()

    def get_resource_service(self, kind: str) -> ResourceService:
        return self.services[kind]

    def load(self, events=None) -> None:
        """Compose the 3-level tree from the flat collections and swap it
        into the engine (reference: PolicySetService.load).  The new tree is
        built aside and swapped in with one reference assignment so serving
        threads never observe a cleared or half-built tree; the whole
        read-compose-swap is serialized under _load_lock (see __init__).

        ``events`` (list of ops/delta.CrudEvent) carries the CRUD diff
        captured at mutation time: it scopes the decision-cache flush to
        the delta's target-signature footprint, lets certified-empty diffs
        skip the flush entirely, and enables the evaluator's in-place
        table patching.  ``None`` (boot load, restore, reset, seed) keeps
        the pre-delta global-flush + full-recompile behavior."""
        with self._load_lock:
            self._load_locked(events)

    def _delta_footprint(self, events):
        """Conservative affected-signature footprint of a CRUD event list
        (ops/delta.footprint_from_events over the live collections); None
        means "unknown" and degrades to the global flush."""
        if events is None:
            return None
        try:
            return footprint_from_events(
                events,
                self.engine.urns,
                lambda kind, doc_id: self.collections[kind].get(doc_id),
                lambda kind: self.collections[kind].all(),
            )
        except Exception:  # noqa: BLE001 — footprint is an optimization
            if self.logger:
                self.logger.exception("delta footprint failed; global flush")
            return None

    def _load_locked(self, events=None) -> None:
        rules = {d["id"]: rule_from_dict(d) for d in self.collections["rule"].all()}
        policies = {}
        for p_doc in self.collections["policy"].all():
            child_rules = []
            for rid in p_doc.get("rules") or []:
                # missing children become None placeholders
                child_rules.append(rules.get(rid))
            policy = policy_from_dict(p_doc)
            policy.combinables = {
                (r.id if r is not None else f"__missing_{i}"): r
                for i, r in enumerate(child_rules)
            }
            policies[p_doc["id"]] = policy
        tree: dict = {}
        for ps_doc in self.collections["policy_set"].all():
            child_policies = []
            for pid in ps_doc.get("policies") or []:
                child_policies.append(policies.get(pid))
            policy_set = policy_set_from_dict(ps_doc)
            policy_set.combinables = {
                (p.id if p is not None else f"__missing_{i}"): p
                for i, p in enumerate(child_policies)
            }
            tree[policy_set.id] = policy_set
        footprint = self._delta_footprint(events)
        decision_cache = getattr(self.evaluator, "decision_cache", None)
        if decision_cache is not None:
            # epoch-flush BEFORE the swap: between the new tree going live
            # and the evaluator refresh below, no cached old-tree decision
            # may serve.  refresh() bumps AGAIN after the swap — together
            # with writers stamping entries with an epoch snapshot taken
            # before their walk reads the tree (DecisionCache.put), the
            # pre+post bumps guarantee no evaluation that saw the OLD tree
            # can store an entry whose epoch survives: its snapshot
            # predates at least the post-swap bump.  With a delta
            # footprint both bumps are SCOPED: entries (and in-flight
            # writers) whose target signatures are provably disjoint from
            # the mutation keep the same guarantee without the flush —
            # and a certified-empty diff (no-op CRUD) skips them entirely.
            if footprint is not None and footprint.empty:
                pass
            elif footprint is not None:
                decision_cache.bump_scoped(footprint)
            else:
                decision_cache.bump_epoch()
        self.engine.replace_policy_sets(tree)
        if self.evaluator is not None:
            self.evaluator.refresh(events=events, footprint=footprint)

    def sync_after_mutation(self, kind: str, op: str, items: list[dict],
                            events=None) -> None:
        """Hot-sync the in-memory tree after a CRUD mutation.  The
        reference does targeted Map surgery for creates/deletes and a full
        reload for updates/upserts (reference: resourceManager.ts:202-215,
        274, 305, 352-369); a full recompose keeps both paths consistent
        here, then the evaluator applies the delta (in-capacity table
        patch + scoped cache invalidation) or falls back to a full
        recompile (ops/delta.py taxonomy)."""
        self.load(events)

    def seed(self, policy_set_docs, policy_docs, rule_docs) -> None:
        """superUpsert seed loading (reference: src/worker.ts:200-242).
        Per-kind sync is suppressed so startup pays one tree compose +
        evaluator compile instead of three partial ones."""
        self.services["rule"].super_upsert(rule_docs, sync=False)
        self.services["policy"].super_upsert(policy_docs, sync=False)
        self.services["policy_set"].super_upsert(policy_set_docs, sync=False)
        self.load()


# remote-frame validators per resource kind (PolicyReplicator): the same
# composers store.load() runs, invoked up front so a malformed frame is
# rejected instead of persisted
_VALIDATORS = {
    "rule": rule_from_dict,
    "policy": policy_from_dict,
    "policy_set": policy_set_from_dict,
}


class PolicyReplicator:
    """Shared mutable policy state across workers, over the broker's CRUD
    topic logs.

    The reference gets multi-replica policy storage from a shared ArangoDB
    (cfg/config.json database.main) — every replica reads one durable
    store, and in-memory trees are per-replica caches.  Here the durable
    shared store IS the broker's journaled CRUD log: every mutation a
    worker serves is already emitted to ``io.restorecommerce.{kind}s.
    resource`` (ResourceService._emit); this replicator subscribes each
    worker to those topics, replays the full log at boot (idempotent
    upserts/deletes converge to the log's final state) and applies live
    frames from OTHER workers to the local collections + engine tree, so
    N workers serve one mutable policy state without restarts.

    Apply path never re-emits (no event loops); the worker's own frames
    carry its PolicyStore.origin stamp and are skipped (they were applied
    locally at CRUD time).  Tree recompose + evaluator recompile are
    debounced so a replay burst costs one compile, not one per event.
    Concurrent writers use last-frame-wins per document — the same
    semantics concurrent replicas get from the reference's shared Arango.
    """

    def __init__(self, store: PolicyStore, bus, logger=None,
                 debounce_s: float = 0.05):
        self.store = store
        self.bus = bus
        self.logger = logger
        self.debounce_s = debounce_s
        # multi-tenant registry (srv/tenancy.TenantRegistry), wired by
        # the worker: CRUD frames whose envelope carries a ``tenant`` key
        # belong to a tenant domain, not the global tree — they are routed
        # to the registry (boot replay included, so a new tenant boots by
        # replay) and never enter the global debounced sync.  None drops
        # tenant-tagged frames (single-tenant deployment).
        self.tenancy = None
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock
        self._applied = 0  # guarded-by: _lock
        # CRUD events captured per applied frame (old doc read before the
        # upsert/delete): the debounced sync hands them to store.load so
        # remote mutations get the same delta patch + scoped invalidation
        # as local ones
        self._pending_events: list = []  # guarded-by: _lock
        # policy-epoch bookkeeping (cluster tier, srv/router.py): highest
        # broker offset OBSERVED per CRUD topic, and the highest offset
        # whose effect is REFLECTED in the serving tree (own-origin frames
        # were applied at CRUD time; remote frames at the debounced sync).
        # sum(applied+1) is the replica's policy epoch — the number every
        # response is stamped with, so the router and the stale-decision
        # oracle can compare replica states without reading the trees.
        self.offsets: dict[str, int] = {}          # guarded-by: _lock
        self.applied_offsets: dict[str, int] = {}  # guarded-by: _lock
        self._topics = {
            self.store.services[kind].topic.name: kind
            for kind in ("rule", "policy", "policy_set")
            if self.store.services[kind].topic is not None
        }

    def start(self) -> "PolicyReplicator":
        for topic_name in self._topics:
            self.bus.topic(topic_name).on(self._on_event, starting_offset=0)
        return self

    @property
    def epoch(self) -> int:
        """Policy epoch: count of CRUD log frames reflected in the serving
        tree (sum of applied offsets + 1 across the CRUD topics)."""
        with self._lock:
            return sum(off + 1 for off in self.applied_offsets.values())

    def _mark_applied(self, topic: str, offset: int) -> None:
        """A frame whose effect is already in the tree (own-origin, no-op,
        malformed-and-quarantined): advance the applied watermark when no
        remote frames are pending, so the mutating replica's epoch covers
        its own CRUD immediately rather than at the next debounced sync."""
        with self._lock:
            if self._pending_events:
                # remote frames are awaiting the debounced sync: this
                # frame's effect is in the tree, but claiming it applied
                # now would overclaim any pending lower-offset frame on
                # the same topic — record it for the armed sync (which
                # snapshots self.offsets) to advance instead of dropping
                # it from the watermark entirely
                self.offsets[topic] = max(
                    self.offsets.get(topic, -1), offset
                )
            else:
                self.applied_offsets[topic] = max(
                    self.applied_offsets.get(topic, -1), offset
                )

    def _on_event(self, event_name: str, message, ctx: dict) -> None:
        # acs-lint: ignore[guarded-by] benign racy fast-path: a frame that
        # slips past a concurrent stop() is applied to collections that are
        # about to be discarded; _schedule_sync re-checks under the lock
        if self._stopped:
            return
        topic = ctx.get("topic")
        kind = self._topics.get(topic)
        if kind is None or not isinstance(message, dict):
            return
        offset = ctx.get("offset")
        offset = offset if isinstance(offset, int) else -1
        if message.get("origin") == self.store.origin:
            if offset >= 0:
                self._mark_applied(topic, offset)
            return  # our own mutation, already applied + synced
        tenant = message.get("tenant")
        if tenant is not None:
            # tenant-scoped frame: apply to the tenant registry (which
            # recomposes/patches only that tenant's domain); the global
            # tree is untouched, so no debounced sync is scheduled.  The
            # watermark still advances — tenant frames count toward the
            # replica's journal-replay epoch.
            registry = self.tenancy
            if registry is not None:
                try:
                    registry.apply_remote_frame(
                        str(tenant), kind, event_name,
                        message.get("payload"),
                    )
                except Exception:  # noqa: BLE001 — bad frame, not the pump
                    if self.logger:
                        self.logger.exception(
                            "tenant replication apply failed",
                            extra={"topic": topic, "tenant": tenant},
                        )
            if offset >= 0:
                self._mark_applied(topic, offset)
            return
        doc = message.get("payload")
        if not isinstance(doc, dict):
            if offset >= 0:
                self._mark_applied(topic, offset)
            return
        collection = self.store.collections[kind]
        try:
            event: Optional[CrudEvent] = None
            if event_name.endswith("Created") or event_name.endswith(
                "Modified"
            ):
                if doc.get("id"):
                    # quarantine malformed remote docs BEFORE they reach
                    # the collection: a doc the composers reject would
                    # otherwise poison every later store.load() on this
                    # worker (local CRUD included)
                    _VALIDATORS[kind](doc)
                    event = CrudEvent(
                        kind=kind, op="upsert", doc_id=doc["id"],
                        old_doc=collection.get(doc["id"]), new_doc=doc,
                    )
                    collection.upsert(doc)
            elif event_name.endswith("Deleted"):
                if doc.get("collection"):
                    event = CrudEvent(kind=kind, op="delete_all", doc_id="")
                    collection.clear()
                elif doc.get("id"):
                    event = CrudEvent(
                        kind=kind, op="delete", doc_id=doc["id"],
                        old_doc=collection.get(doc["id"]), new_doc=None,
                    )
                    collection.delete(doc["id"])
            else:
                if offset >= 0:
                    self._mark_applied(topic, offset)
                return
        except Exception:  # noqa: BLE001 — a bad frame must not kill the pump
            if self.logger:
                self.logger.exception(
                    "replication apply failed",
                    extra={"topic": topic, "event": event_name},
                )
            if offset >= 0:
                self._mark_applied(topic, offset)  # quarantined, not pending
            return
        with self._lock:
            self._applied += 1
        self._schedule_sync(event, topic=topic, offset=offset)

    def _schedule_sync(self, event=None, topic=None, offset=-1) -> None:
        # arm only when no sync is pending: the pending sync composes
        # from the live collections at fire time, so it covers every
        # frame applied before it runs — and a replay burst of N frames
        # costs one timer thread, not N
        with self._lock:
            if event is not None:
                self._pending_events.append(event)
            if topic is not None and offset >= 0:
                # recorded under the same lock as the pending append so a
                # concurrent _sync snapshot never advances the epoch past
                # an event it did not apply
                self.offsets[topic] = max(
                    self.offsets.get(topic, -1), offset
                )
            if self._stopped or self._timer is not None:
                return
            self._timer = threading.Timer(self.debounce_s, self._sync)
            self._timer.daemon = True
            self._timer.start()

    def _sync(self) -> None:
        with self._lock:
            self._timer = None
            events = self._pending_events
            self._pending_events = []
            observed = dict(self.offsets)
        try:
            self.store.load(events or None)
        except Exception:  # noqa: BLE001
            if self.logger:
                self.logger.exception("replication tree sync failed")
        else:
            # every frame observed before this sync started is now
            # reflected in the tree: advance the epoch watermark
            with self._lock:
                for topic, off in observed.items():
                    self.applied_offsets[topic] = max(
                        self.applied_offsets.get(topic, -1), off
                    )

    def wait_caught_up(self, timeout_s: float = 60.0) -> bool:
        """Block until every CRUD frame journaled at call time is
        reflected in the serving tree (epoch >= journal tail).  A
        rebooting replica calls this before opening its serving port —
        answering from a half-replayed tree would hand the router
        INDETERMINATE decisions stamped with a stale epoch.  Returns
        False on timeout or if the journal tail is unreadable (the
        caller serves anyway, degraded, rather than hanging boot)."""
        try:
            total = sum(
                len(self.bus.topic(name).read(0))
                for name in self._topics
            )
        except Exception:  # noqa: BLE001 — broker gone: nothing to wait on
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.epoch >= total:
                return True
            time.sleep(0.02)
        return False

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
