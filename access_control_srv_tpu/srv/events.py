"""In-process event bus with offset tracking.

Framework analog of the reference's Kafka topics + OffsetStore
(reference: src/worker.ts:114-123, 249-361; cfg/config.json events.kafka):
named topics carry CRUD events, command fan-out and the HR-scope
request/response rendezvous.  The bus interface is deliberately small so a
real broker-backed implementation can be substituted; the default keeps an
in-memory log per topic with monotonically increasing offsets, supporting
replay from a stored offset (the restore/resume semantics).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class Topic:
    def __init__(self, name: str):
        self.name = name
        self._log: list[tuple[str, Any]] = []
        self._listeners: list[Callable[[str, Any, dict], None]] = []
        self._lock = threading.Lock()

    @property
    def offset(self) -> int:
        return len(self._log)

    def emit(self, event_name: str, message: Any) -> int:
        with self._lock:
            self._log.append((event_name, message))
            offset = len(self._log) - 1
            listeners = list(self._listeners)
        for listener in listeners:
            listener(event_name, message, {"offset": offset, "topic": self.name})
        return offset

    def on(
        self,
        listener: Callable[[str, Any, dict], None],
        starting_offset: Optional[int] = None,
    ) -> None:
        """Subscribe; optionally replay the log from ``starting_offset``
        first (the stored-offset resume path, reference: worker.ts:351-361)."""
        with self._lock:
            replay = (
                list(enumerate(self._log))[starting_offset:]
                if starting_offset is not None
                else []
            )
            self._listeners.append(listener)
        for offset, (event_name, message) in replay:
            listener(event_name, message, {"offset": offset, "topic": self.name})

    def read(self, from_offset: int = 0) -> list[tuple[str, Any]]:
        with self._lock:
            return list(self._log[from_offset:])


class EventBus:
    def __init__(self):
        self._topics: dict[str, Topic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> Topic:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = Topic(name)
            return self._topics[name]

    def topics(self) -> dict[str, Topic]:
        return dict(self._topics)


def on_topics(bus, topic_names, listener) -> None:
    """Subscribe one listener to several topics (no replay).  Used to fan
    the policy CRUD topics into cross-cutting listeners — e.g. the
    decision-cache epoch flush, which must fire on REMOTE workers' frames
    immediately rather than waiting out the replicator's debounced tree
    sync (srv/worker.py)."""
    for name in topic_names:
        bus.topic(name).on(listener)


CRUD_TOPICS = tuple(
    f"io.restorecommerce.{kind}s.resource"
    for kind in ("rule", "policy", "policy_set")
)


class OffsetStore:
    """Consumer-offset checkpoints (reference: chassis OffsetStore over
    Redis DB 0, src/worker.ts:123)."""

    def __init__(self):
        self._offsets: dict[str, int] = {}

    def commit(self, topic: str, offset: int) -> None:
        self._offsets[topic] = offset

    def get(self, topic: str) -> Optional[int]:
        return self._offsets.get(topic)
