# acs-lint: host-only — fault injection is pure host-side control flow
# and must never import jax or touch the device program (the
# failpoints-zero-device-ops audit row depends on it).
"""Deterministic failpoint framework (PR 11).

Named injection sites are threaded through every external and async
boundary of the serving stack — broker journal write/fsync and the
socket topic pump, adapter HTTP, identity resolution, device
dispatch/materialize, staging-pool acquire, router proxy, replica
spawn.  Each site is one ``fire("site.name")`` call: a single attribute
load and boolean test when the registry is disarmed (the default), so
the serving path is byte-identical with faults configured but off.

Actions (``action`` key of a point spec):

- ``error``  raise at the site (``FaultError`` by default; sites that
             need a domain exception pass an ``exc`` factory so the
             failure travels the exact path a real one would)
- ``delay``  sleep ``delay_s`` then continue
- ``hang``   block up to ``hang_s`` on an event that ``clear()``
             releases — a wedged dependency the watchdogs must bound,
             never an unkillable thread
- ``torn``   only meaningful at byte-writing sites: ``tear()`` returns
             the record truncated to ``torn_frac`` of its bytes,
             simulating a crash mid-write (journal CRC catches it on
             replay)

Schedules are deterministic: given the same seed and the same call
order, the same calls hit.  A point spec combines

- ``after``  skip the first N calls (default 0)
- ``every``  then hit every k-th eligible call (default 1)
- ``count``  stop after M hits (default unlimited)
- ``p``      instead of ``every``: per-call Bernoulli from a
             ``random.Random`` seeded with ``f"{seed}:{site}"`` — a
             reproducible flap, not true randomness

Activation: the ``faults`` config block arms the process registry at
worker start (``faults: {enabled: true, seed: 7, points: [...]}``), and
the ``faults`` command (srv/command.py) arms/clears/inspects it at
runtime.  Everything is OFF by default; ``REGISTRY.clear()`` releases
any hung threads.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

ACTIONS = ("error", "delay", "hang", "torn")


class FaultError(RuntimeError):
    """The injected failure for ``action: error`` sites that do not
    supply a domain exception."""

    def __init__(self, site: str):
        super().__init__(f"fault injected at {site}")
        self.site = site


class Failpoint:
    """One armed point: a site name, an action, and a deterministic
    schedule.  Mutable call/hit counters are guarded by the registry
    lock (``evaluate`` is only called under it)."""

    __slots__ = ("site", "action", "after", "every", "count", "p",
                 "delay_s", "hang_s", "torn_frac", "calls", "hits",
                 "_rng")

    def __init__(self, spec: dict, seed: int = 0):
        self.site = str(spec["site"])
        self.action = str(spec.get("action", "error"))
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        self.after = int(spec.get("after", 0))
        self.every = max(1, int(spec.get("every", 1)))
        count = spec.get("count")
        self.count = None if count is None else int(count)
        p = spec.get("p")
        self.p = None if p is None else float(p)
        self.delay_s = float(spec.get("delay_s", 0.01))
        self.hang_s = float(spec.get("hang_s", 30.0))
        self.torn_frac = float(spec.get("torn_frac", 0.5))
        self.calls = 0
        self.hits = 0
        # per-site stream: the schedule of one point never depends on
        # how often OTHER sites fire
        self._rng = random.Random(f"{seed}:{self.site}")

    def evaluate(self) -> bool:
        """Advance the schedule one call; True when this call hits."""
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.count is not None and self.hits >= self.count:
            return False
        if self.p is not None:
            if self._rng.random() >= self.p:
                return False
        elif (self.calls - self.after - 1) % self.every != 0:
            return False
        self.hits += 1
        return True

    def spec(self) -> dict:
        out = {"site": self.site, "action": self.action}
        if self.after:
            out["after"] = self.after
        if self.every != 1:
            out["every"] = self.every
        if self.count is not None:
            out["count"] = self.count
        if self.p is not None:
            out["p"] = self.p
        return out


class FailpointRegistry:
    """Process-wide registry the ``fire()`` sites consult.

    Disarmed (the default) the hot path is one attribute load and one
    boolean test — no lock, no dict walk.  Armed, each ``fire`` takes
    the registry lock only to advance the matching point's schedule;
    the action itself (sleep / wait / raise) runs outside the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: dict[str, list[Failpoint]] = {}
        self._hits: dict[str, int] = {}
        self._release = threading.Event()
        self._seed = 0
        # armed flag is read lock-free on the hot path: a one-way-ish
        # flag flipped only by configure()/clear(); a racing fire()
        # during arm/disarm harmlessly sees the old value for one call
        self.enabled = False
        # observability hook: called as on_hit(site) for every hit so
        # telemetry can count acs_failpoint_hits_total without this
        # module importing telemetry
        self.on_hit: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------ control

    def configure(self, points: list[dict], seed: int = 0) -> None:
        """Install (replace) the armed points.  An empty list disarms."""
        parsed: dict[str, list[Failpoint]] = {}
        for spec in points or []:
            point = Failpoint(spec, seed=seed)
            parsed.setdefault(point.site, []).append(point)
        with self._lock:
            self._points = parsed
            self._hits = {}
            self._seed = seed
        self.enabled = bool(parsed)

    def clear(self) -> None:
        """Disarm and release every thread parked in a ``hang``."""
        self.enabled = False
        with self._lock:
            self._points = {}
            release = self._release
            self._release = threading.Event()
        release.set()

    def arm(self, points: list[dict], seed: int = 0):
        """Context manager for tests: arm on enter, clear on exit."""
        registry = self

        class _Armed:
            def __enter__(self):
                registry.configure(points, seed=seed)
                return registry

            def __exit__(self, *exc):
                registry.clear()
                return False

        return _Armed()

    def stats(self) -> dict:
        with self._lock:
            points = [
                dict(p.spec(), calls=p.calls, hits=p.hits)
                for plist in self._points.values() for p in plist
            ]
            hits = dict(self._hits)
        return {
            "enabled": self.enabled,
            "seed": self._seed,
            "points": points,
            "hits_by_site": hits,
        }

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    # -------------------------------------------------------------- sites

    def fire(self, site: str, exc: Optional[Callable[[], BaseException]]
             = None) -> Optional[Failpoint]:
        """The injection site.  Returns None on the (default) miss;
        raises / sleeps / hangs on a hit; returns the hit ``Failpoint``
        for site-interpreted actions (``torn``)."""
        if not self.enabled:
            return None
        hit: Optional[Failpoint] = None
        with self._lock:
            for point in self._points.get(site, ()):
                if point.evaluate():
                    hit = point
                    break
            if hit is None:
                return None
            self._hits[site] = self._hits.get(site, 0) + 1
            release = self._release
        on_hit = self.on_hit
        if on_hit is not None:
            try:
                on_hit(site)
            except Exception:  # noqa: BLE001 — metrics must never inject
                pass
        if hit.action == "error":
            raise exc() if exc is not None else FaultError(site)
        if hit.action == "delay":
            time.sleep(hit.delay_s)
            return hit
        if hit.action == "hang":
            # bounded, releasable wedge: clear() frees every hanger
            release.wait(hit.hang_s)
            return hit
        return hit  # torn: the caller applies tear()

    def tear(self, site: str, data: bytes) -> bytes:
        """Byte-writing sites: return ``data`` possibly truncated by an
        armed ``torn`` point (a crash-interrupted write); error/delay/
        hang points at the same site act as in ``fire``."""
        hit = self.fire(site)
        if hit is not None and hit.action == "torn":
            return data[: max(1, int(len(data) * hit.torn_frac))]
        return data


# the process-wide registry every site consults; worker start/stop and
# the command interface arm/clear it, tests use REGISTRY.arm(...)
REGISTRY = FailpointRegistry()

fire = REGISTRY.fire
tear = REGISTRY.tear


def configure_from(config: dict | None) -> bool:
    """Arm the registry from a ``faults`` config block; False (and
    disarmed) when the block is missing or disabled."""
    if not config or not config.get("enabled"):
        return False
    REGISTRY.configure(
        list(config.get("points") or []), seed=int(config.get("seed", 0))
    )
    return REGISTRY.enabled
