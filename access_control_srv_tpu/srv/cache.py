"""Subject cache + hierarchical-scope rendezvous protocol.

Framework analog of the reference's Redis subject cache and the Kafka
``hierarchicalScopesRequest``/``hierarchicalScopesResponse`` protocol
(reference: src/core/accessController.ts:701-783, src/worker.ts:252-345):

- HR scopes are cached under ``cache:{subjectID}:hrScopes`` for interactive
  tokens, ``cache:{subjectID}:{token}:hrScopes`` otherwise;
- on a miss, a request keyed ``token:date`` goes out on the auth topic and
  the caller parks on a waiter with a timeout; the response handler writes
  the cache and releases the waiters;
- ``userModified`` events diff role associations / token scopes and evict;
  ``userDeleted`` evicts unconditionally.
"""

from __future__ import annotations

import datetime
import threading
import time
from typing import Any, Optional

from ..core.common import get_field as _get


class SubjectCache:
    """Key-value cache with prefix eviction (Redis DB-subject analog)."""

    def __init__(self):
        self._data: dict[str, Any] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def get(self, key: str) -> Any:
        with self._lock:
            return self._data.get(key)

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def evict_prefix(self, prefix: str) -> int:
        with self._lock:
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                del self._data[k]
            return len(keys)


class HRScopeProvider:
    """createHRScope: cache lookup, else request/response rendezvous over
    the auth topic with a parked waiter + timeout
    (reference: accessController.ts:735-783).

    Rendezvous mechanics: all waiters park on ONE shared condition variable
    and wake together when their token lands in the released set — one
    kernel wait object total instead of one ``threading.Event`` allocated
    per in-flight request.  The default timeout is 15 s (config
    ``authorization:hrReqTimeout``): the reference's 300 s default parks a
    serving thread for five minutes on a dead auth service."""

    def __init__(
        self,
        cache: SubjectCache,
        auth_topic=None,
        timeout_ms: int = 15_000,
        logger=None,
    ):
        self.cache = cache
        self.auth_topic = auth_topic
        self.timeout_ms = timeout_ms
        self.logger = logger
        # token_date -> number of parked waiters; released token_dates are
        # marked until their last waiter drains
        self.waiting: dict[str, int] = {}  # guarded-by: _cond
        self._released: set[str] = set()   # guarded-by: _cond
        self._cond = threading.Condition()

    def hr_scopes_key(self, context) -> Optional[str]:
        subject = _get(context, "subject") or {}
        token = _get(subject, "token")
        subject_id = _get(subject, "id")
        tokens = _get(subject, "tokens") or []
        token_found = next(
            (t for t in tokens if _get(t, "token") == token), None
        )
        if token_found is not None and _get(token_found, "interactive"):
            return f"cache:{subject_id}:hrScopes"
        if token_found is not None:
            return f"cache:{subject_id}:{token}:hrScopes"
        return None

    def create_hr_scope(self, context):
        subject = _get(context, "subject")
        if subject is None:
            context["subject"] = subject = {}
        token = _get(subject, "token")
        key = self.hr_scopes_key(context)
        if key is None:
            return context

        if not self.cache.exists(key):
            if self.auth_topic is None:
                return context
            date = datetime.datetime.now(datetime.timezone.utc).isoformat()
            token_date = f"{token}:{date}"
            with self._cond:
                self.waiting[token_date] = self.waiting.get(token_date, 0) + 1
            # emit OUTSIDE the condition: loopback responders may answer
            # synchronously on this very thread (tests do), and the
            # response handler takes the condition to release
            self.auth_topic.emit(
                "hierarchicalScopesRequest", {"token": token_date}
            )
            with self._cond:
                released = self._cond.wait_for(
                    lambda: token_date in self._released,
                    timeout=self.timeout_ms / 1000.0,
                )
                # un-park: the last waiter out clears the bookkeeping so
                # neither the waiting map nor the released set leaks
                # (token_date keys are unique per call)
                remaining = self.waiting.get(token_date, 1) - 1
                if remaining <= 0:
                    self.waiting.pop(token_date, None)
                    self._released.discard(token_date)
                else:
                    self.waiting[token_date] = remaining
            if not released:
                if self.logger:
                    self.logger.error(
                        "hr scope read timed out", extra={"token": token_date}
                    )
                return context
        scopes = self.cache.get(key)
        if scopes is not None:
            subject["hierarchical_scopes"] = scopes
        return context

    def handle_hr_scopes_response(self, message: dict, subject_resolver=None):
        """Consume a hierarchicalScopesResponse: write the cache under the
        right key shape and release waiters
        (reference: src/worker.ts:252-299)."""
        token_date = _get(message, "token") or ""
        token = token_date.split(":", 1)[0]
        scopes = _get(message, "hierarchical_scopes") or []
        subject_id = _get(message, "subject_id")
        interactive = bool(_get(message, "interactive"))
        if subject_id is None and subject_resolver is not None:
            resolved = subject_resolver(token)
            payload = _get(resolved, "payload") or {}
            subject_id = _get(payload, "id")
            tokens = _get(payload, "tokens") or []
            token_found = next(
                (t for t in tokens if _get(t, "token") == token), None
            )
            interactive = bool(_get(token_found, "interactive")) if token_found else False
        if subject_id is not None:
            if interactive:
                key = f"cache:{subject_id}:hrScopes"
            else:
                key = f"cache:{subject_id}:{token}:hrScopes"
            self.cache.set(key, scopes)
        with self._cond:
            if token_date in self.waiting:
                self._released.add(token_date)
                self._cond.notify_all()

    def evict_hr_scopes(self, subject_id: str) -> int:
        """(reference: accessController.ts:717-725)"""
        return self.cache.evict_prefix(f"cache:{subject_id}:")


def nested_attributes_equal(cached_attrs, user_attrs) -> Optional[bool]:
    """(reference: src/core/utils.ts:364-373)"""
    if not user_attrs:
        return True
    if (cached_attrs and len(cached_attrs) > 0) and len(user_attrs) > 0:
        return all(
            any(
                _get(db_obj, "value") == _get(obj, "value")
                for db_obj in cached_attrs
            )
            for obj in user_attrs
        )
    elif len(cached_attrs or []) != len(user_attrs or []):
        return False
    return None


def compare_role_associations(user_assocs, cached_assocs, logger=None) -> bool:
    """True when the role associations changed
    (reference: src/core/utils.ts:375-421)."""
    if len(user_assocs or []) != len(cached_assocs or []):
        return True
    modified = False
    if (user_assocs and len(user_assocs) > 0) and len(cached_assocs) > 0:
        for user_assoc in user_assocs:
            found = False
            for cached in cached_assocs:
                if _get(cached, "role") == _get(user_assoc, "role"):
                    cached_attrs = _get(cached, "attributes") or []
                    if len(cached_attrs) > 0:
                        for cached_attr in cached_attrs:
                            cached_nested = _get(cached_attr, "attributes")
                            for user_attr in _get(user_assoc, "attributes") or []:
                                user_nested = _get(user_attr, "attributes")
                                if (
                                    _get(user_attr, "id") == _get(cached_attr, "id")
                                    and _get(user_attr, "value")
                                    == _get(cached_attr, "value")
                                    and nested_attributes_equal(
                                        cached_nested, user_nested
                                    )
                                ):
                                    found = True
                                    break
                    else:
                        found = True
                        break
            if not found:
                modified = True
            if modified:
                break
    return modified
