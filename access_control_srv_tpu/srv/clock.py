"""Monotonic-anchored wall clock for epoch-like timestamps.

``time.time()`` jumps — NTP slew, manual clock changes, leap-second
smearing — so consecutive calls can go BACKWARD.  That is fine for a
human-facing uptime display, but poison for stored ordering-sensitive
stamps: ``meta.modified`` written by srv/store.ResourceService is
compared against earlier stamps by replication reconciliation and by
clients ("was this doc touched since I read it?"), and a backward step
silently reorders history.

``monotonic_wall()`` is the repo-blessed source for such stamps: a wall
epoch captured ONCE at import anchors ``time.monotonic()``, so values

* read as ordinary Unix epoch seconds (serializable, human-debuggable),
* never decrease within a process, whatever the wall clock does,
* drift from true wall time only by however far the wall clock is
  adjusted after process start (bounded, and irrelevant for ordering).

The single ``time.time()`` call below is the one wall-clock read this
module is FOR; everything else in the serving path uses
``time.monotonic()`` directly (deadline/TTL math) or this function
(stored stamps).  acs-lint's ``wall-clock`` rule points here.
"""

from __future__ import annotations

import time

# acs-lint: ignore[wall-clock] the one blessed wall read: anchors the
# monotonic clock to the Unix epoch at import, never consulted again
_ANCHOR = time.time() - time.monotonic()


def monotonic_wall() -> float:
    """Unix-epoch-like seconds that never go backward in this process."""
    return _ANCHOR + time.monotonic()
