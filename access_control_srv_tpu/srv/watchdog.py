"""Device-hang watchdog: bounded materialize + kernel-path quarantine.

The depth-N pipeline's only blocking point is ``materialize()`` — the
D2H fetch of a dispatched batch.  A wedged device (XLA runtime hang,
stuck transfer, the ``device.materialize`` failpoint) turns that call
into an unbounded stall: the finalize worker blocks forever, every slot
fills, and the whole serving surface freezes behind one batch.

This module bounds that point and heals around it:

* ``run(materialize)`` executes the fetch on a disposable daemon thread
  under ``materialize_timeout_s``.  On timeout it raises
  ``DeviceTimeoutError`` — the caller resolves the batch's rows honestly
  (expired rows shed with the deadline status, the rest take the oracle
  walk, and a row nothing can answer gets the ``degraded`` envelope —
  srv/admission.degraded_response).  Never a fabricated PERMIT/DENY.
* Repeated timeouts trip a device ``CircuitBreaker``
  (srv/admission.py); an open breaker QUARANTINES the kernel path —
  ``evaluator.set_quarantined(True)`` routes every decision path to the
  oracle so traffic keeps serving degraded-but-correct.
* A background probe then re-initializes the kernel through the
  swap-stable registry (``evaluator.refresh(wait=True)``) and pushes a
  canary batch through dispatch+materialize under the same deadline;
  the first healthy probe closes the breaker and restores the kernel
  path.

Threading: each bounded call gets its OWN daemon thread, not a pool
worker — a wedged fetch strands only its thread (released when the hang
clears, leaked if it never does), and never wedges the next batch's
fetch behind it.  The probe loop is a daemon thread that lives only
while quarantined.
"""

# acs-lint: host-only — the watchdog supervises the host side of the
# device boundary and must never import the device runtime itself

from __future__ import annotations

import threading
import time
from typing import Optional

from .admission import CircuitBreaker


class DeviceTimeoutError(RuntimeError):
    """``materialize()`` exceeded the watchdog deadline — the device (or
    its D2H fetch) is wedged.  Carries no decision: callers resolve the
    affected rows down the honest ladder (oracle walk / deadline shed /
    degraded envelope), never a fabricated PERMIT/DENY."""


# the probe's refresh(wait=True) includes a full recompile; bound it far
# looser than a steady-state fetch so slow compiles don't fail probes
_REFRESH_TIMEOUT_FLOOR_S = 30.0

_BREAKER_DEFAULTS = {
    "window_s": 30.0,
    "min_volume": 2,
    "failure_ratio": 0.5,
    "open_s": 1.0,
    "half_open_probes": 1,
}


class DeviceWatchdog:
    """Materialize deadline + quarantine breaker + restore probe over one
    evaluator's kernel path (module docstring has the full contract)."""

    def __init__(
        self,
        evaluator,
        materialize_timeout_s: float = 5.0,
        probe_interval_s: float = 0.5,
        breaker_cfg: Optional[dict] = None,
        telemetry=None,
        logger=None,
    ):
        self._evaluator = evaluator
        self.materialize_timeout_s = float(materialize_timeout_s)
        self.probe_interval_s = float(probe_interval_s)
        self.logger = logger
        cfg = dict(_BREAKER_DEFAULTS)
        cfg.update(breaker_cfg or {})
        counter = telemetry.admission if telemetry is not None else None
        self.breaker = CircuitBreaker("device", counter=counter, **cfg)
        self._lock = threading.Lock()
        self._quarantined_since: Optional[float] = None  # guarded-by: _lock
        self._degraded_accum = 0.0   # guarded-by: _lock
        self.timeouts = 0            # guarded-by: _lock
        self.quarantines = 0         # guarded-by: _lock
        self.restores = 0            # guarded-by: _lock
        self._probe_thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self._shutdown = False
        evaluator.attach_watchdog(self)

    # ------------------------------------------------------------ hot path

    def run(self, materialize):
        """Materialize under the deadline; raises ``DeviceTimeoutError``
        on a hang (after breaker accounting), relays any other exception
        untouched so existing error ladders keep working."""
        try:
            out = self._bounded(materialize, self.materialize_timeout_s,
                                "acs-device-fetch")
        except DeviceTimeoutError:
            self._on_timeout()
            raise
        self.breaker.record_success()
        return out

    def _bounded(self, fn, timeout_s: float, name: str):
        """Run ``fn`` on a disposable daemon thread; DeviceTimeoutError
        after ``timeout_s``.  No breaker accounting here — run() and the
        probe account differently."""
        box: dict = {}
        done = threading.Event()

        def _call():
            try:
                box["ok"] = fn()
            except BaseException as err:  # noqa: BLE001 — relayed below
                box["err"] = err
            done.set()

        threading.Thread(target=_call, daemon=True, name=name).start()
        if not done.wait(timeout_s):
            raise DeviceTimeoutError(
                f"device materialize exceeded {timeout_s:.3f}s"
            )
        if "err" in box:
            raise box["err"]
        return box["ok"]

    def _on_timeout(self) -> None:
        self.breaker.record_failure()
        with self._lock:
            self.timeouts += 1
        if self.logger is not None:
            self.logger.warning(
                "device materialize timeout (%.3fs deadline); breaker %s",
                self.materialize_timeout_s, self.breaker.state,
            )
        if self.breaker.state != CircuitBreaker.CLOSED:
            self._enter_quarantine()

    # --------------------------------------------------------- quarantine

    def _enter_quarantine(self) -> None:
        start = False
        with self._lock:
            if self._quarantined_since is not None:
                return
            self._quarantined_since = time.monotonic()
            self.quarantines += 1
            probe = self._probe_thread
            if probe is None or not probe.is_alive():
                probe = threading.Thread(
                    target=self._probe_loop, daemon=True,
                    name="acs-device-probe",
                )
                self._probe_thread = probe
                start = True
        self._evaluator.set_quarantined(True)
        if self.logger is not None:
            self.logger.warning(
                "device path QUARANTINED — serving oracle-only while the "
                "probe re-initializes the kernel"
            )
        if start:
            probe.start()

    def _exit_quarantine(self) -> None:
        with self._lock:
            since = self._quarantined_since
            if since is None:
                return
            self._quarantined_since = None
            self._degraded_accum += time.monotonic() - since
            self.restores += 1
        self._evaluator.set_quarantined(False)
        if self.logger is not None:
            self.logger.warning(
                "device path RESTORED — kernel serving resumed"
            )

    def _probe_loop(self) -> None:
        while not self._shutdown:
            time.sleep(self.probe_interval_s)
            with self._lock:
                if self._quarantined_since is None:
                    return
            if not self.breaker.allow():
                continue  # still in the open cooldown
            ok = self._probe_once()
            if ok:
                self.breaker.record_success()
                if self.breaker.state == CircuitBreaker.CLOSED:
                    self._exit_quarantine()
                    return
            else:
                self.breaker.record_failure()

    def _probe_once(self) -> bool:
        """Re-initialize the kernel through the swap-stable registry and
        prove the device path answers end-to-end with a canary batch —
        both bounded, so a still-wedged runtime fails the probe instead
        of wedging it."""
        evaluator = self._evaluator
        refresh_timeout = max(
            _REFRESH_TIMEOUT_FLOOR_S, 10.0 * self.materialize_timeout_s
        )
        try:
            self._bounded(
                lambda: evaluator.refresh(wait=True),
                refresh_timeout, "acs-device-probe-refresh",
            )
            return bool(self._bounded(
                evaluator.kernel_probe, self.materialize_timeout_s,
                "acs-device-probe-canary",
            ))
        except BaseException as err:  # noqa: BLE001 — probe verdict only
            if self.logger is not None:
                self.logger.info("device probe failed: %r", err)
            return False

    # -------------------------------------------------------------- status

    @property
    def quarantined(self) -> bool:
        with self._lock:
            return self._quarantined_since is not None

    def degraded_seconds(self) -> float:
        """Cumulative seconds spent quarantined, including the current
        episode — the ``acs_degraded_seconds`` telemetry gauge."""
        with self._lock:
            total = self._degraded_accum
            if self._quarantined_since is not None:
                total += time.monotonic() - self._quarantined_since
            return total

    def status(self) -> dict:
        with self._lock:
            quarantined = self._quarantined_since is not None
            timeouts = self.timeouts
            quarantines = self.quarantines
            restores = self.restores
        return {
            "quarantined": quarantined,
            "timeouts": timeouts,
            "quarantines": quarantines,
            "restores": restores,
            "degraded_seconds": self.degraded_seconds(),
            "breaker": self.breaker.stats(),
        }

    def close(self) -> None:
        self._shutdown = True
