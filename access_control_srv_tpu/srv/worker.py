"""Composition root: wires the engine, evaluator, store, cache, command
interface and event listeners into a running service
(reference: src/worker.ts Worker.start/stop:105-372).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..core.engine import AccessController
from ..core.loader import load_policy_sets_from_file
from ..models.model import Decision
from ..models.urns import Urns
from .admission import AdmissionController
from .batcher import MicroBatcher
from .cache import HRScopeProvider, SubjectCache, compare_role_associations
from .command import CommandInterface
from .config import Config
from .decision_cache import from_config as decision_cache_from_config
from .evaluator import HybridEvaluator
from .events import CRUD_TOPICS, EventBus, OffsetStore, on_topics
from .identity import StaticIdentityClient
from .service import AccessControlService
from .store import PolicyStore
from .telemetry import Telemetry, make_logger


def _yaml_list(path: str) -> list[dict]:
    import yaml

    with open(path) as fh:
        docs = list(yaml.safe_load_all(fh))
    items: list[dict] = []
    for doc in docs:
        if isinstance(doc, list):
            items.extend(doc)
        elif doc:
            items.append(doc)
    return items


class Worker:
    def __init__(self):
        self.cfg: Optional[Config] = None
        self.telemetry: Optional[Telemetry] = None
        self.engine: Optional[AccessController] = None
        self.evaluator: Optional[HybridEvaluator] = None
        self.store: Optional[PolicyStore] = None
        self.service: Optional[AccessControlService] = None
        self.command_interface: Optional[CommandInterface] = None
        self.batcher: Optional[MicroBatcher] = None
        self.wire_pipeline = None  # srv/pipeline.DevicePipeline
        self.bus: Optional[EventBus] = None
        self.subject_cache: Optional[SubjectCache] = None
        self.decision_cache = None
        self.admission: Optional[AdmissionController] = None
        self.hr_provider: Optional[HRScopeProvider] = None
        self.identity_client = None
        self.offset_store: Optional[OffsetStore] = None
        self.logger = None
        self.mesh = None
        self.obs = None  # srv/tracing.Observability (None = disabled)
        self.replicator = None
        self.relation_store = None  # srv/relations.RelationTupleStore
        self.tenancy = None  # srv/tenancy.TenantRegistry (None = off)
        self.watchdog = None  # srv/watchdog.DeviceWatchdog (None = off)
        self._faults_armed = False
        # live CRUD-offset watermark per topic (policy_epoch fallback for
        # workers without a replicator)
        self._epoch_lock = threading.Lock()
        self._crud_offsets: dict = {}  # guarded-by: _epoch_lock

    def start(
        self,
        cfg: Config | dict | None = None,
        logger=None,
        identity_client=None,
    ) -> "Worker":
        self.cfg = cfg if isinstance(cfg, Config) else Config(cfg or {})
        cfg = self.cfg
        json_sink = cfg.get("logging:json_sink")
        if logger is None:
            import logging as _logging

            pre = set(
                id(h) for h in
                _logging.getLogger("access-control-srv-tpu").handlers
            )
            self.logger = make_logger(json_sink=json_sink)
            # close on stop ONLY the handlers THIS start call installed:
            # a second worker sharing the sink keeps logging through the
            # handler it found already attached
            self._log_handlers = [
                h for h in self.logger.handlers
                if id(h) not in pre
                and getattr(h, "_acs_json_sink", None) == json_sink
            ]
        else:
            self.logger = logger
            self._log_handlers = []
        self.telemetry = Telemetry()

        # observability hub (srv/tracing.py, docs/OBSERVABILITY.md):
        # stage-span tracing, sampled decision-audit log and the optional
        # /metrics endpoint.  None unless the `observability` config block
        # is enabled — absent/disabled, the serving path stays
        # byte-identical to pre-observability behavior (differential:
        # tests/test_tracing.py)
        from .tracing import Observability

        self.obs = Observability.from_config(
            cfg, telemetry=self.telemetry, logger=self.logger
        )

        # XLA dump hook (SURVEY section 5): best-effort — the flag is read
        # at backend initialization, so it only takes effect when set
        # before the first jax dispatch of the process
        dump_dir = cfg.get("profiling:xla_dump_dir")
        if dump_dir:
            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_dump_to" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_dump_to={dump_dir}"
                ).strip()
                self.logger.info("XLA dump enabled", extra={"dir": dump_dir})

        # event bus + offsets + subject cache: in-process by default;
        # a configured broker address switches all three to the
        # cross-process TCP backend (srv/broker.py — the reference's
        # separate Kafka + Redis processes, cfg events.kafka / redis)
        broker_address = cfg.get("events:broker:address")
        if broker_address:
            from .broker import (
                SocketEventBus,
                SocketOffsetStore,
                SocketSubjectCache,
            )

            broker_secret = cfg.get("events:broker:secret")
            self.bus = SocketEventBus(broker_address, secret=broker_secret)
            self.offset_store = SocketOffsetStore(
                broker_address, secret=broker_secret
            )
            self.subject_cache = SocketSubjectCache(
                broker_address, secret=broker_secret
            )
        else:
            self.bus = EventBus()
            self.offset_store = OffsetStore()
            self.subject_cache = SubjectCache()
        auth_topic = self.bus.topic("io.restorecommerce.authentication")
        self.hr_provider = HRScopeProvider(
            self.subject_cache,
            auth_topic,
            timeout_ms=cfg.get("authorization:hrReqTimeout", 15_000),
            logger=self.logger,
        )
        # server-side decision cache (srv/decision_cache.py): TTL +
        # LRU-bounded cache of evaluation_cacheable decisions, invalidated
        # by CRUD epoch bumps, user events and flush_cache commands
        self.decision_cache = decision_cache_from_config(
            cfg, telemetry=self.telemetry
        )

        # admission control (srv/admission.py): deadline-aware bounded
        # queues + shedding at the batcher, dependency circuit breakers
        # on the adapter/identity clients, graceful drain on stop.
        # Disabled (the default) the controller admits unconditionally
        # and the serving path is byte-identical to pre-admission code.
        self.admission = AdmissionController.from_config(
            cfg, telemetry=self.telemetry
        )

        # identity client: a live gRPC channel when the config names an
        # identity-service address (reference: src/worker.ts:135-143),
        # otherwise the in-memory static map
        if identity_client is not None:
            self.identity_client = identity_client
        else:
            ids_address = cfg.get("client:user:address") or cfg.get(
                "client:identity:address"
            )
            if ids_address:
                from .identity import GrpcIdentityClient

                self.identity_client = GrpcIdentityClient(
                    ids_address,
                    timeout=float(cfg.get("client:identity:timeout", 5.0)),
                    logger=self.logger,
                    cache_size=int(cfg.get(
                        "client:identity:cache:max_entries", 1024
                    )),
                    ttl_s=float(cfg.get(
                        "client:identity:cache:ttl_s", 600.0
                    )),
                    negative_ttl_s=float(cfg.get(
                        "client:identity:cache:negative_ttl_s", 30.0
                    )),
                    counter=self.telemetry.identity,
                    breaker=self.admission.breaker("identity"),
                )
            else:
                self.identity_client = StaticIdentityClient()

        # the engine + evaluator
        urns = Urns(cfg.get("policies:options:urns") or {})
        combining = cfg.get("policies:options:combiningAlgorithms") or None
        self.engine = AccessController(
            urns=urns,
            combining_algorithms=combining,
            logger=self.logger,
            identity_client=self.identity_client,
            hr_scope_provider=self.hr_provider,
        )
        adapter_cfg = cfg.get("adapter") or {}
        if adapter_cfg.get("graphql"):
            self.engine.create_resource_adapter(
                adapter_cfg, breaker=self.admission.breaker("adapter")
            )
        # multi-chip serving: `parallel:data_devices` (int, or "all")
        # builds a data-parallel mesh the evaluator shards request batches
        # over; `parallel:model_devices` (int > 1) additionally shards the
        # RULE axis of the compiled policy tensors over a second mesh axis
        # (parallel/rule_shard.py — for trees too large to replicate per
        # chip), composable with data_devices into a 2-axis mesh.  Unset
        # keeps single-device dispatch.  Touching jax.devices() initializes
        # the backend, so the mesh is only built when asked for.
        mesh = None
        model_axis = None

        def parse_devices(key):
            n_req = cfg.get(key)
            if not n_req:
                return None
            if isinstance(n_req, str):
                n_req = n_req.strip().lower()
            if n_req in ("all", "-1", -1):
                return -1
            try:
                n_req = int(n_req)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{key} must be a positive integer, -1, or 'all'; "
                    f"got {n_req!r}"
                ) from None
            if n_req <= 0:
                raise ValueError(
                    f"{key} must be a positive integer, -1, or 'all'; "
                    f"got {n_req!r}"
                )
            return n_req

        n_data_req = parse_devices("parallel:data_devices")
        n_model_req = parse_devices("parallel:model_devices")
        n_pod_req = parse_devices("parallel:pod_shards")
        if n_model_req == -1:
            raise ValueError(
                "parallel:model_devices must be an explicit integer "
                "(the rule-axis shard count is a layout choice, not "
                "'all available')"
            )
        if n_pod_req == -1:
            raise ValueError(
                "parallel:pod_shards must be an explicit integer "
                "(the set-axis shard count is a layout choice, not "
                "'all available')"
            )
        if n_pod_req and n_model_req:
            raise ValueError(
                "parallel:pod_shards (set-axis) and "
                "parallel:model_devices (rule-axis) are mutually "
                "exclusive layouts for the model mesh axis"
            )
        pod_shards = None
        if n_pod_req:
            # pod-sharded policy tree (parallel/pod_shard.py): the SET
            # axis of the bucketed compile shards over the model axis;
            # delta patching stays shard-local.  Same 2-axis mesh as the
            # rule-sharded path; n_pod_req == 1 still builds the mesh so
            # shard-count sweeps exercise one code path.
            import jax

            from ..parallel import make_mesh2

            avail = len(jax.devices())
            if n_data_req in (None, -1):
                n_data = max(1, avail // n_pod_req)
            else:
                n_data = max(1, min(n_data_req, avail // n_pod_req))
            data_axis = cfg.get("parallel:axis", "data")
            model_axis = cfg.get("parallel:model_axis", "model")
            mesh = make_mesh2(
                n_data, n_pod_req,
                data_axis=data_axis, model_axis=model_axis,
            )
            pod_shards = n_pod_req
            self.logger.info(
                "pod-sharded mesh active",
                extra={"data_devices": n_data,
                       "pod_shards": n_pod_req,
                       "available": avail},
            )
        elif n_model_req and n_model_req > 1:
            import jax

            from ..parallel import make_mesh2

            avail = len(jax.devices())
            if n_data_req in (None, -1):
                n_data = max(1, avail // n_model_req)
            else:
                # same clamp-to-available contract as the single-axis path
                n_data = max(1, min(n_data_req, avail // n_model_req))
            data_axis = cfg.get("parallel:axis", "data")
            model_axis = cfg.get("parallel:model_axis", "model")
            mesh = make_mesh2(
                n_data, n_model_req,
                data_axis=data_axis, model_axis=model_axis,
            )
            self.logger.info(
                "rule-sharded mesh active",
                extra={"data_devices": n_data,
                       "model_devices": n_model_req,
                       "available": avail},
            )
        elif n_data_req:
            import jax

            from ..parallel import make_mesh

            avail = len(jax.devices())
            n = avail if n_data_req == -1 else min(n_data_req, avail)
            mesh = make_mesh(n, axis=cfg.get("parallel:axis", "data"))
            self.logger.info(
                "data-parallel mesh active",
                extra={"devices": n, "available": avail},
            )
        self.mesh = mesh
        self.evaluator = HybridEvaluator(
            self.engine,
            backend=cfg.get("evaluator:backend", "hybrid"),
            logger=self.logger,
            async_compile=bool(cfg.get("evaluator:async_compile", False)),
            telemetry=self.telemetry,
            mesh=mesh,
            mesh_axis=cfg.get("parallel:axis", "data"),
            model_axis=model_axis,
            pod_shards=pod_shards,
            decision_cache=self.decision_cache,
            delta_enabled=bool(cfg.get("evaluator:delta_enabled", True)),
            observability=self.obs,
            # explain mode (srv/explain.py): kernel rows carry deciding-
            # node provenance.  False (the default) lowers the exact
            # pre-explain device program.
            explain=bool(cfg.get("explain:enabled", False)),
        )

        # deterministic fault injection (srv/faults.py): arm the process
        # registry from config — OFF by default, and configure_from leaves
        # the registry disarmed when the block is absent/disabled, so the
        # serving path stays byte-identical (tests/test_admission.py
        # differential)
        from .faults import REGISTRY as _faults_registry
        from .faults import configure_from as _faults_configure

        self._faults_armed = _faults_configure(cfg.get("faults"))
        if self.telemetry is not None:
            _faults_registry.on_hit = self.telemetry.failpoints.inc

        # device-hang watchdog (srv/watchdog.py): bounded materialize +
        # kernel-path quarantine + probe-driven restore.  OFF by default;
        # enabled it attaches to the evaluator so every kernel
        # materialize runs under the deadline.
        wd_cfg = cfg.get("evaluator:watchdog") or {}
        if wd_cfg.get("enabled"):
            from .watchdog import DeviceWatchdog

            self.watchdog = DeviceWatchdog(
                self.evaluator,
                materialize_timeout_s=float(
                    wd_cfg.get("materialize_timeout_s", 5.0)
                ),
                probe_interval_s=float(wd_cfg.get("probe_interval_s", 0.5)),
                breaker_cfg=wd_cfg.get("breaker"),
                telemetry=self.telemetry,
                logger=self.logger,
            )
            if self.telemetry is not None:
                self.telemetry.set_watchdog(self.watchdog)

        # policy store with self-authorization hook; the hook consults the
        # live config so authorization:enabled can be toggled at runtime via
        # config_update (reference: tests drive cfg.set + updateConfig,
        # test/microservice_acs_enabled.spec.ts:379-382)
        self.store = PolicyStore(
            self.engine,
            evaluator=self.evaluator,
            bus=self.bus,
            snapshot_dir=cfg.get("database:snapshot_dir"),
            access_check=self._access_check,
            logger=self.logger,
        )

        # multi-tenant registry (srv/tenancy.py): tenant-tagged traffic
        # resolves against per-tenant tables on class-shared compiled
        # programs; None (tenancy:enabled false, the default) keeps every
        # path byte-identical to single-tenant behavior
        from . import tenancy as tenancy_mod

        self.tenancy = tenancy_mod.from_config(
            cfg, self.engine.urns,
            logger=self.logger,
            telemetry=self.telemetry,
            decision_cache=self.decision_cache,
            store=self.store,
            observability=self.obs,
        )

        # service facade + command interface + micro-batcher
        self.service = AccessControlService(
            cfg, self.engine, self.evaluator, self.store, self.logger,
            telemetry=self.telemetry, observability=self.obs,
        )
        self.command_interface = CommandInterface(
            cfg,
            self.service,
            store=self.store,
            bus=self.bus,
            cache=self.subject_cache,
            decision_cache=self.decision_cache,
            admission=self.admission,
            observability=self.obs,
            logger=self.logger,
            worker=self,
        )
        self.batcher = MicroBatcher(
            self.evaluator,
            window_ms=cfg.get("evaluator:micro_batch_window_ms", 2),
            max_batch=cfg.get("evaluator:micro_batch_max", 4096),
            admission=self.admission,
            observability=self.obs,
            # single source of truth for in-flight depth — admission's
            # feasibility estimate reads the same config value
            pipeline_depth=cfg.get("evaluator:pipeline_depth", 2),
        )
        self.batcher.tenancy = self.tenancy
        self.batcher.start()
        self.service.batcher = self.batcher

        # streaming wire pipeline (srv/pipeline.py): one depth-bounded
        # device queue shared by every IsAllowedStream client stream;
        # same depth value as the batcher and admission
        from .pipeline import DevicePipeline

        self.wire_pipeline = DevicePipeline(
            self, depth=cfg.get("evaluator:pipeline_depth", 2)
        )

        # event listeners (reference: src/worker.ts:249-361)
        auth_topic.on(self._auth_listener)
        self.bus.topic("io.restorecommerce.users.resource").on(
            self._user_listener
        )
        # always subscribed (not only with a decision cache): the listener
        # also maintains the live CRUD-offset watermark behind
        # policy_epoch() for workers running without a replicator
        on_topics(self.bus, CRUD_TOPICS, self._crud_cache_listener)

        # seed data (reference: src/worker.ts:200-242)
        seed_cfg = cfg.get("seed_data")
        if seed_cfg:
            entities = seed_cfg.get("entities", seed_cfg) if isinstance(
                seed_cfg, dict
            ) else seed_cfg
            self.store.seed(
                _yaml_list(entities["policy_sets"]),
                _yaml_list(entities["policies"]),
                _yaml_list(entities["rules"]),
            )

        # policy load (reference: src/worker.ts:245)
        self.service.load_policies()

        # multi-worker shared policy state: over a broker bus, the
        # journaled CRUD topic logs ARE the shared durable policy store
        # (the reference's shared-Arango role) — replay them at boot and
        # apply live frames from other workers (srv/store.PolicyReplicator)
        self.replicator = None
        if broker_address and cfg.get("replication:enabled", True):
            from .store import PolicyReplicator

            self.replicator = PolicyReplicator(
                self.store, self.bus, logger=self.logger
            )
            # tenant-tagged journal frames route to the registry (boot
            # replay onboards every journaled tenant before serving)
            self.replicator.tenancy = self.tenancy
            self.replicator.start()
            # boot-time catch-up gate: don't return (and so don't let the
            # CLI open the serving port) until the journal tail observed
            # at boot is reflected in the tree — a half-replayed replica
            # would answer INDETERMINATE and the cluster router would
            # happily route to it (tests/test_cluster_chaos.py)
            self.replicator.wait_caught_up(
                timeout_s=float(
                    cfg.get("replication:catchup_timeout_s", 60.0)
                )
            )

        # Zanzibar-style relation tuples (srv/relations.py): host-side
        # tuple store behind the stage-B bit-reader's relation planes.
        # Off by default (relations:enabled) — the engine then treats
        # relation-bearing targets fail-closed.  Over a broker bus the
        # journaled tuple topic IS the shared durable tuple store (same
        # role the CRUD topics play for policies): replay at boot, then
        # follow live frames from other workers via origin-skip.
        self.relation_store = None
        if cfg.get("relations:enabled"):
            from .relations import RelationTupleStore

            self.relation_store = RelationTupleStore(
                bus=self.bus,
                topic=cfg.get(
                    "relations:topic",
                    "io.restorecommerce.relation-tuples.resource",
                ),
                logger=self.logger,
                telemetry=self.telemetry,
            )
            self.relation_store.replay()
            self.relation_store.start_replication()
            self.evaluator.attach_relation_store(self.relation_store)

        # shadow evaluation (srv/shadow.py): candidate tree beside
        # production on the same compiled programs, fed from the service
        # facade off the response path.  Built LAST so the production
        # tree (and so its size class and shared jit registry) is final
        # — the zero-new-compiles assertion inside compares against the
        # fully-warmed state.  None unless shadow:enabled with
        # candidate_paths (the default): no object, no queue, no tap.
        from . import shadow as shadow_mod

        self.shadow = shadow_mod.from_config(
            cfg, self.evaluator,
            telemetry=self.telemetry, logger=self.logger,
        )
        self.service.shadow = self.shadow

        # permission-lattice audit sweeps (srv/audit_sweep.py): built
        # after the shadow so the twin loop can sweep a loaded candidate.
        # None unless audit:enabled (the default) — no manager, no
        # threads, no command surface, byte-identical serving path.
        from . import audit_sweep as audit_mod

        self.audit = audit_mod.from_config(
            cfg, worker=self,
            telemetry=self.telemetry, logger=self.logger,
        )
        return self

    def stop(self) -> None:
        if getattr(self, "audit", None) is not None:
            # cancel sweeps before the batcher drains: in-flight bulk
            # futures resolve with the shutdown status and land in the
            # snapshot as honest sheds, never as fabricated verdicts
            self.audit.stop()
            self.audit = None
        if getattr(self, "shadow", None) is not None:
            # stop mirroring before the serving teardown below: the
            # facade tap checks for None, and the shadow owns its own
            # evaluator (joined here, never by the production shutdown)
            self.service.shadow = None
            self.shadow.stop()
            self.shadow = None
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.close()
        if getattr(self, "_faults_armed", False):
            # release any hung threads and disarm — only when THIS worker
            # armed the registry (in-process tests arm via REGISTRY.arm)
            from .faults import REGISTRY as _faults_registry

            _faults_registry.clear()
            self._faults_armed = False
        if getattr(self, "wire_pipeline", None) is not None:
            self.wire_pipeline.stop()
        if self.batcher is not None:
            # graceful drain: stop admitting, flush already-admitted
            # batches bounded by the drain deadline, fail the rest with
            # the shutdown status (srv/batcher.MicroBatcher.stop)
            self.batcher.stop()
        if self.evaluator is not None:
            # join the debounced async-compile worker instead of leaking a
            # daemon thread mid-XLA-compile (srv/evaluator.shutdown)
            self.evaluator.shutdown()
        if getattr(self, "tenancy", None) is not None:
            self.tenancy.shutdown()
        if getattr(self, "replicator", None) is not None:
            self.replicator.stop()
        if getattr(self, "relation_store", None) is not None:
            self.relation_store.stop()
        if getattr(self, "store", None) is not None:
            for collection in self.store.collections.values():
                collection.close()
        for handler in getattr(self, "_log_handlers", []):
            handler.close()
            if self.logger is not None:
                self.logger.removeHandler(handler)
        for attr in ("bus", "offset_store", "subject_cache"):
            backend = getattr(self, attr, None)
            if backend is not None and hasattr(backend, "close"):
                backend.close()
        if getattr(self, "obs", None) is not None:
            # stop the /metrics endpoint and close the audit sink
            self.obs.close()
        if hasattr(self.identity_client, "close"):
            self.identity_client.close()

    # -------------------------------------------------------- event handlers

    def _auth_listener(self, event_name: str, message, ctx: dict) -> None:
        """hierarchicalScopesResponse -> cache write + waiter release
        (reference: src/worker.ts:252-299)."""
        if event_name == "hierarchicalScopesResponse":
            self.hr_provider.handle_hr_scopes_response(
                message, subject_resolver=self.identity_client.find_by_token
            )

    def _crud_cache_listener(self, event_name: str, message, ctx: dict) -> None:
        """Rule/Policy/PolicySet Created/Modified/Deleted from REMOTE
        workers -> decision-cache epoch flush (their tree mutations make
        cached decisions suspect before the replicator's debounced sync
        lands).  This worker's OWN frames are skipped: the local CRUD path
        already bumped through store hot-sync — with a delta-scoped
        footprint, which an unconditional global bump here would defeat.

        All frames (own included) advance the live CRUD-offset watermark
        behind policy_epoch() — the fallback epoch source when no
        replicator is running."""
        offset = ctx.get("offset")
        topic = ctx.get("topic")
        if isinstance(offset, int) and topic:
            with self._epoch_lock:
                self._crud_offsets[topic] = max(
                    self._crud_offsets.get(topic, -1), offset
                )
        if not event_name.endswith(("Created", "Modified", "Deleted")):
            return
        if (
            isinstance(message, dict)
            and self.store is not None
            and message.get("origin") == self.store.origin
        ):
            return
        if isinstance(message, dict) and message.get("tenant") is not None:
            # tenant-scoped frame: the replicator routes it to the tenant
            # registry, which bumps ONLY that tenant's cache namespace —
            # a global bump here would flush every other tenant's entries
            # on one tenant's CRUD (isolation + perf)
            return
        if self.decision_cache is not None:
            self.decision_cache.bump_epoch()

    def policy_epoch(self) -> int:
        """The replica's policy epoch: number of CRUD log frames reflected
        in the serving tree.  Replicated workers read the replicator's
        applied watermark (replay-inclusive, so replicas that booted at
        different times agree once converged); standalone workers count the
        frames the live listener has seen.  Responses are stamped with this
        value (transport_grpc) so the cluster router and the stale-decision
        oracle can reason about replica state without reading the trees."""
        replicator = getattr(self, "replicator", None)
        if replicator is not None:
            return replicator.epoch
        with self._epoch_lock:
            return sum(off + 1 for off in self._crud_offsets.values())

    def _user_listener(self, event_name: str, message, ctx: dict) -> None:
        """userModified / userDeleted -> subject-cache + decision-cache
        eviction (reference: src/worker.ts:300-345).  A ``tenant`` key on
        the event scopes the decision-cache eviction to the originating
        tenant's namespace: one tenant's user churn must not evict
        another tenant's cached decisions (isolation + perf)."""
        tenant = (message or {}).get("tenant") if isinstance(
            message, dict
        ) else None
        if event_name == "userDeleted":
            user_id = (message or {}).get("id")
            if user_id:
                self.hr_provider.evict_hr_scopes(user_id)
                if self.decision_cache is not None:
                    self.decision_cache.evict_subject(
                        user_id, tenant=tenant
                    )
                # the event carries no token list; the resolution cache
                # indexes entries by payload subject id for exactly this
                if hasattr(self.identity_client, "evict_subject"):
                    self.identity_client.evict_subject(user_id)
        elif event_name == "userModified":
            user_id = (message or {}).get("id")
            if not user_id:
                return
            # cached decisions fingerprint the subject's resolved role
            # associations, so changed-assoc requests miss anyway — the
            # prefix eviction also clears entries for the OLD associations
            # (reference analog: utils.ts flushACSCache on user mutation)
            if self.decision_cache is not None:
                self.decision_cache.evict_subject(user_id, tenant=tenant)
            # token resolutions for a mutated user are stale regardless of
            # role-association diffing
            if hasattr(self.identity_client, "evict"):
                for token in (message or {}).get("tokens") or []:
                    tok = token.get("token") if isinstance(token, dict) else token
                    if tok:
                        self.identity_client.evict(tok)
            # ...and tokens the event does NOT list (rotated/expired ones
            # the cache may still hold) drop via the subject-id index
            if hasattr(self.identity_client, "evict_subject"):
                self.identity_client.evict_subject(user_id)
            cached = self.subject_cache.get(f"cache:{user_id}:subject")
            if cached is None:
                return
            changed = compare_role_associations(
                (message or {}).get("role_associations") or [],
                cached.get("role_associations") or [],
                self.logger,
            )
            if changed:
                self.hr_provider.evict_hr_scopes(user_id)
                data = {"db_index": 5, "pattern": user_id}
                if tenant is not None:
                    # scope the fleet-wide flush to the originating
                    # tenant's cache namespace
                    data["tenant"] = tenant
                self.bus.topic("io.restorecommerce.command").emit(
                    "flushCacheCommand",
                    {"name": "flush_cache", "payload": {"data": data}},
                )

    # ------------------------------------------------- CRUD self-authorization

    def _access_check(self, kind, items, action, subject, ctx):
        """The service authorizes its own policy CRUD by asking itself
        (reference: checkAccessRequest -> gRPC back into this service's
        isAllowed, src/core/utils.ts:212-261, cfg client.acs-srv = self).
        A disabled authorization config short-circuits to PERMIT
        (reference: utils.ts:216-219)."""
        from ..models.model import Attribute, Request, Target

        if not self.cfg.get("authorization:enabled"):
            return Decision.PERMIT
        # api-key bypass: a subject bearing the operator key set via the
        # set_api_key command (or authentication:apiKey config) skips
        # self-authorization (chassis behavior the reference's suite
        # exercises, microservice_acs_enabled.spec.ts set_api_key flow)
        api_key = None
        if getattr(self, "command_interface", None) is not None:
            api_key = self.command_interface.api_key
        api_key = api_key or self.cfg.get("authentication:apiKey")
        if api_key and subject and subject.get("token") == api_key:
            return Decision.PERMIT

        urns = self.engine.urns
        action_urn = {
            "CREATE": urns.get("create"),
            "MODIFY": urns.get("modify"),
            "DELETE": urns.get("delete"),
            "DROP": urns.get("delete"),
            "READ": urns.get("read"),
        }.get(action, urns.get("read"))
        entity = f"urn:restorecommerce:acs:model:{kind}.{kind.title().replace('_', '')}"
        resources = []
        ctx_resources = []
        for item in items or [{}]:
            resources.append(Attribute(id=urns.get("entity"), value=entity))
            if item.get("id"):
                resources.append(
                    Attribute(id=urns.get("resourceID"), value=item["id"])
                )
                ctx_resources.append(
                    {"id": item["id"], "meta": item.get("meta") or {}}
                )
        subjects = []
        if subject:
            token = subject.get("token")
            if token:
                subjects.append(Attribute(id="token", value=token))
        request = Request(
            target=Target(
                subjects=subjects,
                resources=resources,
                actions=[Attribute(id=urns.get("actionID"), value=action_urn)],
            ),
            context={
                "subject": dict(subject or {}),
                "resources": ctx_resources,
            },
        )
        response = self.service.is_allowed(request)
        return response.decision
