"""Cluster router: one gRPC front door over N worker replicas.

Pod-scale serving tier (docs/CLUSTER.md): each replica is a full Worker
process serving the same policy state (converging through the broker's
journaled CRUD topics + srv/store.PolicyReplicator), and the router
load-balances every unary call AND whole IsAllowedStream streams across
them:

* **Pick**: least-inflight among healthy, non-draining replicas whose
  per-replica circuit breaker (srv/admission.CircuitBreaker) admits the
  call.  No eligible replica -> honest UNAVAILABLE, never a fabricated
  decision.
* **Retry**: a transport failure or a whole-request shed (the replica's
  ``x-acs-shed`` trailer, srv/transport_grpc.stamp_trailers) retries on
  a different replica while deadline budget remains — shed work migrates
  instead of failing, mirroring the admission tier's honest-degradation
  ladder.
* **Streams**: one replica serves a stream; response frame i answers
  request frame i, so on mid-stream failure only the unanswered frame
  tail replays on another replica and the client sees an unbroken
  response sequence.
* **Epochs**: every response trailer carries the replica's policy epoch
  (count of CRUD log frames reflected in its serving tree); the router
  tracks per-replica epochs from live traffic plus a background health
  poll — the cluster's convergence dashboard (``cluster_status``).
* **Drain**: ``cluster_drain`` marks a replica draining (no new calls,
  in-flight finishes); ``cluster_undrain`` reverses it.  Both are
  router-level commands intercepted from the ordinary CommandInterface
  wire surface; every other command forwards to a replica.
"""

from __future__ import annotations

# acs-lint: host-only — the router proxies raw bytes between processes
# and must never pull the device runtime into its address space

import json
import threading
import time
from collections import deque
from concurrent import futures
from typing import Optional

import grpc

from .admission import CircuitBreaker
from .faults import REGISTRY as FAULTS
from .gen import access_control_pb2 as pb
from .telemetry import Histogram
from .transport_grpc import (
    _MESSAGE_SIZE_OPTIONS,
    POLICY_EPOCH_METADATA_KEY,
    SHED_METADATA_KEY,
)

# CommandInterface methods intercepted at the router (all other methods
# proxy through untouched)
_COMMAND_METHODS = (
    "/acstpu.CommandInterface/Command",
    "/io.restorecommerce.commandinterface.CommandInterfaceService/Command",
)
_STREAM_SUFFIX = "/IsAllowedStream"

_identity = lambda raw: raw  # noqa: E731 — raw-bytes pass-through


class _InjectedUnavailable(grpc.RpcError):
    """Failpoint stand-in for a replica transport failure: quacks like a
    grpc.RpcError so the router's retry/exclusion path treats it exactly
    like a real wire error."""

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return "fault injected at router.proxy"


def _deadline_budget(context) -> Optional[float]:
    """Seconds left on the caller's deadline, or None when unbounded.
    grpc reports "no deadline" as an int64-max sentinel (the same one
    srv/admission.deadline_from_context guards) — forwarding it as a
    ``timeout=`` overflows grpc's own deadline math into an instant
    DEADLINE_EXCEEDED, so anything implausibly large means None."""
    try:
        remaining = context.time_remaining()
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        return None
    if remaining is None or remaining > 3.15e8:  # ~10 years
        return None
    return remaining


def _trailer_map(trailers) -> dict:
    out = {}
    for key, value in trailers or ():
        out[str(key).lower()] = value
    return out


class ReplicaHandle:
    """Router-side state for one replica: channel, breaker, drain flag,
    inflight gauge, last observed policy epoch."""

    def __init__(self, addr: str, breaker_cfg: dict | None = None):
        self.addr = addr
        self.channel = grpc.insecure_channel(
            addr, options=_MESSAGE_SIZE_OPTIONS
        )
        self.breaker = CircuitBreaker(
            f"replica-{addr}", **(breaker_cfg or {})
        )
        self.healthy = True
        self.draining = False
        self.inflight = 0
        self.policy_epoch = -1
        # pod-sharded replicas (parallel/pod_shard.py) report a combined
        # pod fingerprint through program_identity; the router tracks it
        # per replica so cluster_status exposes shard-level convergence
        self.pod_fingerprint = None
        # multi-tenant replicas (srv/tenancy.py) report a tenancy block
        # through program_identity; the router tracks tenant count and
        # the per-tenant epoch digest so cluster_status exposes
        # tenant-level convergence across replicas
        self.tenancy = None
        self.last_seen = 0.0
        self.calls = 0
        self.failures = 0
        self.sheds = 0
        self.retries_absorbed = 0  # calls this replica served on retry

    def observe_trailers(self, trailers) -> bool:
        """Update epoch from a response's trailing metadata; True when
        the response was a whole-request shed."""
        md = _trailer_map(trailers)
        epoch = md.get(POLICY_EPOCH_METADATA_KEY)
        if epoch is not None:
            try:
                self.policy_epoch = max(self.policy_epoch, int(epoch))
            except (TypeError, ValueError):
                pass
        self.last_seen = time.monotonic()
        return md.get(SHED_METADATA_KEY) == "1"

    def snapshot(self) -> dict:
        return {
            "addr": self.addr,
            "healthy": self.healthy,
            "draining": self.draining,
            "inflight": self.inflight,
            "policy_epoch": self.policy_epoch,
            "pod_fingerprint": self.pod_fingerprint,
            "tenancy": self.tenancy,
            "breaker": self.breaker.state,
            "calls": self.calls,
            "failures": self.failures,
            "sheds": self.sheds,
            "retries_absorbed": self.retries_absorbed,
        }


class ClusterRouter:
    """gRPC server proxying every service the replicas expose.

    ``replica_addrs`` are ``host:port`` strings of running worker
    transports (parallel/cluster.LocalCluster spawns them).  The router
    never parses decision payloads — handlers are raw-bytes in/out, so
    proxy overhead is routing + one extra hop, not re-serialization."""

    def __init__(self, replica_addrs, addr: str = "127.0.0.1:0",
                 cfg: dict | None = None, max_workers: int = 32,
                 logger=None):
        cfg = cfg or {}
        self.logger = logger
        self._lock = threading.Lock()
        breaker_cfg = cfg.get("breaker") or {}
        self.replicas = [  # guarded-by: _lock
            ReplicaHandle(a, breaker_cfg) for a in replica_addrs
        ]
        self.health_interval_s = float(cfg.get("health_interval_s", 1.0))
        self.retry_budget_fraction = float(
            cfg.get("retry_budget_fraction", 0.2)
        )
        self.max_retries = int(cfg.get("max_retries", 1))
        self.overhead = Histogram()  # router-added seconds per unary call
        self.retries = 0     # guarded-by: _lock
        self.unroutable = 0  # guarded-by: _lock
        self._rr = 0  # round-robin cursor for inflight ties  # guarded-by: _lock
        self._stop = threading.Event()
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_MESSAGE_SIZE_OPTIONS,
        )
        self.server.add_generic_rpc_handlers((_ProxyHandler(self),))
        self.port = self.server.add_insecure_port(addr)
        self.addr = addr.rsplit(":", 1)[0] + f":{self.port}"
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "ClusterRouter":
        self.server.start()
        self._health_thread.start()
        return self

    def stop(self, grace: float = 0.5) -> None:
        self._stop.set()
        self.server.stop(grace)
        with self._lock:
            replicas = list(self.replicas)
        for replica in replicas:
            try:
                replica.channel.close()
            except Exception:  # noqa: BLE001
                pass

    def add_replica(self, addr: str,
                    breaker_cfg: dict | None = None) -> ReplicaHandle:
        """Register a replica that joined after router start (a restarted
        chaos victim re-registers under its new port)."""
        handle = ReplicaHandle(addr, breaker_cfg)
        with self._lock:
            self.replicas.append(handle)
        return handle

    def remove_replica(self, addr: str) -> int:
        """Deregister a replica (a killed process whose port will never
        answer again); returns how many handles matched."""
        with self._lock:
            removed = [r for r in self.replicas if r.addr == addr]
            self.replicas = [r for r in self.replicas if r.addr != addr]
        for replica in removed:
            try:
                replica.channel.close()
            except Exception:  # noqa: BLE001
                pass
        return len(removed)

    # -------------------------------------------------------------- health

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            # acs-lint: ignore[guarded-by] benign racy snapshot: add/remove
            # REBIND self.replicas (never mutate in place), so list() over
            # the old reference iterates a consistent replica set
            for replica in list(self.replicas):
                self._poll(replica)

    def _poll(self, replica: ReplicaHandle) -> None:
        try:
            raw = pb.CommandRequest(name="program_identity")
            fn = replica.channel.unary_unary(
                "/acstpu.CommandInterface/Command",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.CommandResponse.FromString,
            )
            resp = fn(raw, timeout=max(0.5, self.health_interval_s))
            payload = json.loads(resp.payload or b"{}")
            epoch = payload.get("policy_epoch")
            if isinstance(epoch, int):
                replica.policy_epoch = max(replica.policy_epoch, epoch)
            sharding = payload.get("sharding")
            if isinstance(sharding, dict):
                replica.pod_fingerprint = sharding.get("pod_fingerprint")
            tenancy = payload.get("tenancy")
            if isinstance(tenancy, dict):
                replica.tenancy = tenancy
            replica.last_seen = time.monotonic()
            replica.healthy = True
        except Exception:  # noqa: BLE001 — an unreachable replica
            replica.healthy = False

    # ---------------------------------------------------------------- pick

    def _pick(self, excluded=()) -> Optional[ReplicaHandle]:
        """Least-inflight healthy, non-draining replica whose breaker
        admits the call; ties rotate round-robin so sequential traffic
        (inflight always 0 at pick time) still spreads across replicas.
        Half-open breakers hand out probe slots through ``allow()``, so
        the caller MUST report the outcome."""
        with self._lock:
            candidates = [
                r for r in self.replicas
                if r not in excluded and r.healthy and not r.draining
            ]
            if candidates:
                self._rr = (self._rr + 1) % len(candidates)
                candidates = (
                    candidates[self._rr:] + candidates[:self._rr]
                )
            # stable sort: rotation order survives among inflight ties
            candidates.sort(key=lambda r: r.inflight)
        for replica in candidates:
            if replica.breaker.allow():
                with self._lock:
                    replica.inflight += 1
                    replica.calls += 1
                return replica
        return None

    def _release(self, replica: ReplicaHandle) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)

    # --------------------------------------------------------------- unary

    def _proxy_unary(self, method: str, raw: bytes, context):
        t0 = time.perf_counter()
        deadline_s = _deadline_budget(context)
        metadata = tuple(context.invocation_metadata() or ())
        excluded: list[ReplicaHandle] = []
        attempts = 0
        last_shed_payload = None
        last_error: Optional[grpc.RpcError] = None
        backend_s = 0.0
        while attempts <= self.max_retries:
            attempts += 1
            replica = self._pick(excluded)
            if replica is None:
                break
            remaining = None
            if deadline_s is not None:
                remaining = deadline_s - (time.perf_counter() - t0)
                if remaining <= 0:
                    self._release(replica)
                    replica.breaker.record_success()
                    context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "deadline exhausted at router",
                    )
            fn = replica.channel.unary_unary(
                method,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            t_call = time.perf_counter()
            try:
                # failpoint (srv/faults.py): replica hop — error takes the
                # real retry/exclusion path below, like a wire failure
                FAULTS.fire("router.proxy", exc=_InjectedUnavailable)
                payload, call = fn.with_call(
                    raw, metadata=metadata, timeout=remaining
                )
            except grpc.RpcError as err:
                backend_s += time.perf_counter() - t_call
                self._release(replica)
                replica.breaker.record_failure()
                with self._lock:
                    replica.failures += 1
                last_error = err
                excluded.append(replica)
                if not self._retry_ok(t0, deadline_s):
                    break
                with self._lock:
                    self.retries += 1
                continue
            backend_s += time.perf_counter() - t_call
            self._release(replica)
            replica.breaker.record_success()
            trailers = call.trailing_metadata()
            shed = replica.observe_trailers(trailers)
            if shed:
                with self._lock:
                    replica.sheds += 1
                last_shed_payload = (payload, trailers)
                excluded.append(replica)
                if not self._retry_ok(t0, deadline_s):
                    break
                with self._lock:
                    self.retries += 1
                continue
            if attempts > 1:
                with self._lock:
                    replica.retries_absorbed += 1
            try:
                context.set_trailing_metadata(trailers)
            except Exception:  # noqa: BLE001
                pass
            self.overhead.observe(
                time.perf_counter() - t0 - backend_s
            )
            return payload
        # exhausted: an honest shed beats a fabricated failure; a
        # transport error propagates its own status; nothing at all is
        # UNAVAILABLE
        self.overhead.observe(time.perf_counter() - t0 - backend_s)
        if last_shed_payload is not None:
            payload, trailers = last_shed_payload
            try:
                context.set_trailing_metadata(trailers)
            except Exception:  # noqa: BLE001
                pass
            return payload
        with self._lock:
            self.unroutable += 1
        if last_error is not None:
            context.abort(
                last_error.code() or grpc.StatusCode.UNAVAILABLE,
                f"all replicas failed: {last_error.details()}",
            )
        context.abort(
            grpc.StatusCode.UNAVAILABLE,
            "no eligible replica (all draining, unhealthy or "
            "breaker-open)",
        )

    def _retry_ok(self, t0: float, deadline_s: Optional[float]) -> bool:
        if deadline_s is None:
            return True
        remaining = deadline_s - (time.perf_counter() - t0)
        return remaining > deadline_s * self.retry_budget_fraction

    # -------------------------------------------------------------- stream

    def _proxy_stream(self, method: str, request_iterator, context):
        """Proxy one IsAllowedStream: a feeder thread owns the client's
        request iterator and lands frames on a shared deque; per attempt,
        a pump thread moves frames shared -> per-attempt queue, recording
        each frame in ``pending`` BEFORE handing it to grpc — so a frame
        a dying attempt pulled but never answered is still replayed, and
        the dead attempt's grpc consumer thread can never swallow one.
        Response frame i answers request frame i, so after a failure only
        ``pending`` (the unanswered tail, in order) replays elsewhere."""
        import queue as _queue

        metadata = tuple(context.invocation_metadata() or ())
        deadline_s = _deadline_budget(context)
        t0 = time.perf_counter()
        shared: deque = deque()
        shared_cv = threading.Condition()
        feed_done = threading.Event()
        feed_error: list = []

        def feed():
            try:
                for raw in request_iterator:
                    with shared_cv:
                        shared.append(raw)
                        shared_cv.notify_all()
            except BaseException as err:  # noqa: BLE001 — client abort
                feed_error.append(err)
            feed_done.set()
            with shared_cv:
                shared_cv.notify_all()

        threading.Thread(target=feed, daemon=True).start()

        pending: deque = deque()  # sent-but-unanswered frames, in order
        pending_lock = threading.Lock()
        excluded: list[ReplicaHandle] = []

        while True:
            replica = self._pick(excluded)
            if replica is None:
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "no eligible replica for stream",
                )
            attempt_q: "_queue.Queue" = _queue.Queue()
            stop_pump = threading.Event()

            def pump(q=attempt_q, stop=stop_pump):
                # replay the unanswered tail first, then live frames
                with pending_lock:
                    replay = list(pending)
                for raw in replay:
                    q.put(raw)
                while not stop.is_set():
                    with shared_cv:
                        while not shared and not feed_done.is_set() \
                                and not stop.is_set():
                            shared_cv.wait(0.05)
                        if stop.is_set():
                            return
                        if not shared:
                            if feed_done.is_set():
                                q.put(None)
                                return
                            continue
                        raw = shared.popleft()
                    with pending_lock:
                        pending.append(raw)
                    if stop.is_set():
                        # attempt died between popleft and send: the
                        # frame is in pending, the next attempt replays
                        # it — never lost, never double-answered
                        return
                    q.put(raw)

            pump_thread = threading.Thread(target=pump, daemon=True)
            pump_thread.start()

            def gen(q=attempt_q):
                while True:
                    item = q.get()
                    if item is None:
                        return
                    yield item

            fn = replica.channel.stream_stream(
                method,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            remaining = None
            if deadline_s is not None:
                remaining = max(
                    0.001, deadline_s - (time.perf_counter() - t0)
                )
            call = fn(gen(), metadata=metadata, timeout=remaining)
            try:
                for payload in call:
                    with pending_lock:
                        if pending:
                            pending.popleft()
                    yield payload
                # backend stream completed: propagate its trailers
                # (policy epoch) and finish
                replica.observe_trailers(call.trailing_metadata())
                replica.breaker.record_success()
                self._release(replica)
                stop_pump.set()
                try:
                    context.set_trailing_metadata(
                        call.trailing_metadata()
                    )
                except Exception:  # noqa: BLE001
                    pass
                if feed_error and not isinstance(
                    feed_error[0], StopIteration
                ):
                    raise feed_error[0]
                return
            except grpc.RpcError:
                stop_pump.set()
                call.cancel()
                self._release(replica)
                replica.breaker.record_failure()
                with self._lock:
                    replica.failures += 1
                    self.retries += 1
                excluded.append(replica)
                pump_thread.join(timeout=1.0)
                # next attempt replays pending then resumes live frames
                continue
            except BaseException:
                # client-side cancellation / generator close: tear down
                # the backend attempt and give up the slot
                stop_pump.set()
                call.cancel()
                self._release(replica)
                replica.breaker.record_success()
                raise

    # ------------------------------------------------------------ commands

    def _proxy_command(self, method: str, raw: bytes, context):
        try:
            request = pb.CommandRequest.FromString(raw)
        except Exception:  # noqa: BLE001 — undecodable: just forward
            return self._proxy_unary(method, raw, context)
        if request.name == "cluster_status":
            return pb.CommandResponse(
                payload=json.dumps(self.status()).encode()
            ).SerializeToString()
        if request.name in ("cluster_drain", "cluster_undrain"):
            payload = {}
            if request.payload:
                try:
                    payload = json.loads(request.payload)
                except ValueError:
                    payload = {}
            result = self.set_drain(
                payload.get("addr"), request.name == "cluster_drain"
            )
            return pb.CommandResponse(
                payload=json.dumps(result).encode()
            ).SerializeToString()
        return self._proxy_unary(method, raw, context)

    def set_drain(self, addr: Optional[str], draining: bool) -> dict:
        matched = []
        with self._lock:
            for replica in self.replicas:
                if addr is None or replica.addr == addr:
                    replica.draining = draining
                    matched.append(replica.addr)
        if not matched:
            return {"error": f"no replica {addr!r}"}
        return {
            "status": "draining" if draining else "serving",
            "replicas": matched,
        }

    def status(self) -> dict:
        with self._lock:
            replicas = [r.snapshot() for r in self.replicas]
            retries = self.retries
            unroutable = self.unroutable
        epochs = [r["policy_epoch"] for r in replicas]
        pod_fps = {
            r["pod_fingerprint"] for r in replicas
            if r.get("pod_fingerprint") is not None
        }
        tenancy_blocks = [
            r["tenancy"] for r in replicas
            if isinstance(r.get("tenancy"), dict)
        ]
        tenant_digests = {
            b.get("epoch_digest") for b in tenancy_blocks
            if b.get("epoch_digest") is not None
        }
        snap = self.overhead.snapshot()
        out = {
            "addr": self.addr,
            "replicas": replicas,
            "converged": len(set(epochs)) <= 1,
            # pod-sharded replicas only: every replica reporting a pod
            # fingerprint holds byte-identical per-shard tables
            "pod_converged": len(pod_fps) <= 1,
            "min_epoch": min(epochs) if epochs else None,
            "max_epoch": max(epochs) if epochs else None,
            "retries": retries,
            "unroutable": unroutable,
            "router_overhead": {
                "count": snap["count"],
                "p50_ms": round(snap["p50_s"] * 1e3, 3)
                if snap["p50_s"] is not None else None,
                "p99_ms": round(snap["p99_s"] * 1e3, 3)
                if snap["p99_s"] is not None else None,
            },
        }
        if tenancy_blocks:
            # tenant-level convergence: every replica reporting a tenancy
            # block holds identical per-tenant epochs (blake2b digest over
            # the sorted tenant->epoch map, srv/tenancy.py epoch_digest)
            out["tenancy"] = {
                "replicas_reporting": len(tenancy_blocks),
                "tenant_count": max(
                    (b.get("tenant_count") or 0) for b in tenancy_blocks
                ),
                "tenant_converged": len(tenant_digests) <= 1,
            }
        return out


class _ProxyHandler(grpc.GenericRpcHandler):
    """Routes every incoming method to the matching proxy path: stream
    methods to the stream proxy, CommandInterface to the intercepting
    command proxy, everything else to the unary proxy — all raw bytes."""

    def __init__(self, router: ClusterRouter):
        self.router = router

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method.endswith(_STREAM_SUFFIX):
            return grpc.stream_stream_rpc_method_handler(
                lambda it, ctx: self.router._proxy_stream(method, it, ctx),
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        if method in _COMMAND_METHODS:
            return grpc.unary_unary_rpc_method_handler(
                lambda raw, ctx: self.router._proxy_command(
                    method, raw, ctx
                ),
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        return grpc.unary_unary_rpc_method_handler(
            lambda raw, ctx: self.router._proxy_unary(method, raw, ctx),
            request_deserializer=_identity,
            response_serializer=_identity,
        )
